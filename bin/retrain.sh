#!/usr/bin/env bash
# Continuous-training launcher (no reference counterpart — the reference
# retrained offline and restarted its predictors; docs/continual.md).
# Warm-start a candidate on new data, gate it against the serving
# incumbent, and atomically promote on pass; the serving registry's
# watcher hot-swaps the promoted model under traffic.
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:${PYTHONPATH}}"

# usage: retrain.sh <model_name> <config_path> [--data new.ytk]
#        [--mode warm|ftrl] [--extra-rounds N] [--rollback] [extra args...]
model_name="${1:?usage: retrain.sh <model_name> <config_path> [extra args...]}"
properties_path="${2:?usage: retrain.sh <model_name> <config_path> [extra args...]}"
shift 2

exec python -m ytklearn_tpu.cli retrain "${model_name}" "${properties_path}" "$@"
