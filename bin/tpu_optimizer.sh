#!/usr/bin/env bash
# Train launcher (reference surface: bin/local_optimizer.sh:38-47).
# One host process drives the whole TPU mesh - no CommMaster rendezvous,
# no per-slave JVMs; jax discovers the devices.
set -euo pipefail

# make the package importable no matter where the script is invoked from
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:${PYTHONPATH}}"

# model name: linear | multiclass_linear | fm | ffm
#             | gbmlr | gbsdt | gbhmlr | gbhsdt | gbdt
model_name="${1:?usage: tpu_optimizer.sh <model_name> <config_path> [extra args...]}"
properties_path="${2:?usage: tpu_optimizer.sh <model_name> <config_path> [extra args...]}"
shift 2

# data transform python script (reference: bin/transform.py hook);
# pass --transform [--transform-script path] in the extra args to enable
exec python -m ytklearn_tpu.cli train "${model_name}" "${properties_path}" "$@"
