#!/usr/bin/env bash
# libsvm -> ytklearn converter (reference surface:
# bin/libsvm_convert_2_ytklearn.sh + utils/LibsvmConvertTool.java:43).
set -euo pipefail

# make the package importable no matter where the script is invoked from
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:${PYTHONPATH}}"

# mode: binary_classification@label0,label1
#       | multi_classification@l0,l1,... | regression
mode="${1:?usage: libsvm_convert_2_ytklearn.sh <mode> <libsvm_path> <out_path>}"
libsvm_data_path="${2:?usage: libsvm_convert_2_ytklearn.sh <mode> <libsvm_path> <out_path>}"
ytklearn_data_path="${3:?usage: libsvm_convert_2_ytklearn.sh <mode> <libsvm_path> <out_path>}"
shift 3

exec python -m ytklearn_tpu.cli convert "${mode}" "${libsvm_data_path}" "${ytklearn_data_path}" "$@"
