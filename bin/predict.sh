#!/usr/bin/env bash
# Offline batch predict (reference surface: bin/predict.sh:30-33).
set -euo pipefail

# make the package importable no matter where the script is invoked from
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:${PYTHONPATH}}"

config_path="${1:?usage: predict.sh <config_path> <model_name> <file_dir> [extra args...]}"
model_name="${2:?usage: predict.sh <config_path> <model_name> <file_dir> [extra args...]}"
file_dir="${3:?usage: predict.sh <config_path> <model_name> <file_dir> [extra args...]}"
shift 3

# extra args: --save-mode M --suffix S --max-error-tol N
#             --eval-metric "auc,mae" --predict-type value|leafid
exec python -m ytklearn_tpu.cli predict "${config_path}" "${model_name}" "${file_dir}" "$@"
