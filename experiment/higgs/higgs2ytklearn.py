"""HIGGS.csv -> ytklearn text format (weight###label###f0:v,f1:v,...).

Python-3 rebuild of the reference converter
(reference experiment/higgs/higgs2ytklearn.py): first 10.5M rows become
higgs.train, the rest (500k) higgs.test; feature names are the column
indices, zero-valued features kept (dense physics features).
"""

import os
import sys

INPUT = sys.argv[1] if len(sys.argv) > 1 else "HIGGS.csv"
NUM_TRAIN = int(os.environ.get("HIGGS_NUM_TRAIN", 10_500_000))


def write_line(tokens, out):
    label = int(float(tokens[0]))
    feats = ",".join(
        f"{i - 1}:{float(tokens[i]):.7g}" for i in range(1, len(tokens))
    )
    out.write(f"1###{label}###{feats}\n")


def main():
    n = 0
    with open(INPUT) as f, open("higgs.train", "w") as tr, open(
        "higgs.test", "w"
    ) as te:
        for line in f:
            tokens = line.rstrip("\n").split(",")
            write_line(tokens, tr if n < NUM_TRAIN else te)
            n += 1
            if n % 1_000_000 == 0:
                print(f"{n} rows", file=sys.stderr)
    print(f"done: {min(n, NUM_TRAIN)} train / {max(n - NUM_TRAIN, 0)} test")


if __name__ == "__main__":
    main()
