#!/usr/bin/env bash
# Train the Higgs acceptance config on the TPU and batch-predict the test
# set (reference surface: experiment/higgs/local_optimizer.sh + predict.sh).
# Run from the repo root:  bash experiment/higgs/run.sh
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/../.."

bin/tpu_optimizer.sh gbdt experiment/higgs/local_gbdt.conf "$@"

python -m ytklearn_tpu.cli predict experiment/higgs/local_gbdt.conf gbdt \
  experiment/higgs/higgs.test --eval-metric auc \
  --save-mode label_and_predict
