#!/usr/bin/env bash
# Download HIGGS and produce higgs.train / higgs.test in this directory
# (reference surface: experiment/higgs/get_data.sh). Requires network.
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")"

if [ ! -f HIGGS.csv ]; then
  if [ ! -f HIGGS.csv.gz ]; then
    echo "downloading HIGGS.csv.gz (2.6 GB)..."
    wget https://archive.ics.uci.edu/ml/machine-learning-databases/00280/HIGGS.csv.gz
  fi
  gunzip HIGGS.csv.gz
fi

if [ ! -f higgs.train ] || [ ! -f higgs.test ]; then
  python3 higgs2ytklearn.py HIGGS.csv
else
  echo "higgs.train and higgs.test already exist"
fi
