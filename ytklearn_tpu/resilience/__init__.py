"""ytklearn_tpu.resilience — the fault-tolerance layer (docs/fault_tolerance.md).

Three pillars over the r8 flight recorder + r12 atomic dumps:

  chaos     deterministic fault injection: named `chaos_point(site)`
            seams armed by `YTK_CHAOS=<site>:<kind>:<rate>:<seed>` with
            counter-based draws — every injected fault reproduces
            exactly and leaves an obs counter + flight-ring event
  retry     `retry_call(fn, site)` — exponential backoff, deterministic
            jitter, typed transient-vs-fatal classification,
            `io.retry.*` evidence; the one sanctioned retry loop
            (ytklint `sleep-in-except` forbids ad-hoc ones)
  preempt   `PreemptionGuard` — SIGTERM/SIGINT deferred to the next safe
            training boundary, emergency checkpoint through the existing
            atomic dump paths, `Preempted` -> exit 128+signum; the
            relaunch resumes via `--resume auto` (GBDT: bit-identical)

Knobs: YTK_CHAOS, YTK_RETRY_{MAX,BASE_S,MAX_S}, YTK_PREEMPT.
Drill: scripts/chaos_drill.py proves the whole loop end to end.
"""

from __future__ import annotations

from .chaos import (  # noqa: F401
    FAULT_SITES,
    KINDS,
    ChaosError,
    ChaosOSError,
    ChaosRule,
    chaos_enabled,
    chaos_point,
    parse_chaos_spec,
    reset_chaos,
    site_draw,
)
from .preempt import (  # noqa: F401
    Preempted,
    PreemptionGuard,
    preemption_guard,
    trainer_guard,
)
from .retry import (  # noqa: F401
    RetryPolicy,
    is_transient,
    retry_call,
    retry_lines,
)

__all__ = [
    "FAULT_SITES",
    "KINDS",
    "ChaosError",
    "ChaosOSError",
    "ChaosRule",
    "Preempted",
    "PreemptionGuard",
    "RetryPolicy",
    "chaos_enabled",
    "chaos_point",
    "is_transient",
    "parse_chaos_spec",
    "preemption_guard",
    "reset_chaos",
    "retry_call",
    "retry_lines",
    "site_draw",
    "trainer_guard",
]
