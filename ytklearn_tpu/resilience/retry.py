"""Retry/backoff — the one transient-fault primitive the whole tree uses.

Before this module a transient `OSError` anywhere (an ingest shard read
off flaky storage, a continual promotion move, a serve warm load) killed
the run or stranded a reload until the next poll. Now every such seam
routes through `retry_call`:

  - *typed classification*: `is_transient` retries plain OSErrors (EIO,
    connection resets, timeouts — what preemptible storage actually
    throws) but never the fatal shapes (FileNotFoundError & friends,
    where retrying only delays the real error) and never non-IO bugs
  - *exponential backoff with deterministic jitter*: delay for attempt k
    is `min(max_s, base_s * 2^(k-1))` scaled into [0.5, 1.0) by a
    counter-hash of (site, k) — no host RNG, so two runs back off
    identically and a test can pin the schedule
  - *evidence*: `io.retry.attempts` / `io.retry.<site>` /
    `io.retry.recovered` / `io.retry.giveup` counters plus `io.retry`
    trace events, so a postmortem shows exactly which seam flapped

The ytklint `sleep-in-except` rule forbids ad-hoc `time.sleep` retry
loops everywhere else in the tree — this module is the one sanctioned
implementation (docs/fault_tolerance.md).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional, TypeVar

from ..config import knobs
from ..obs import event as obs_event, inc as obs_inc
from .chaos import ChaosError, site_draw

log = logging.getLogger("ytklearn_tpu.resilience")

T = TypeVar("T")

_JITTER_SEED = 0x5EED  # fixed: jitter must reproduce across runs

#: OSError shapes where a retry can only re-raise the same answer slower
_FATAL_OS = (
    FileNotFoundError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
    FileExistsError,
)


def is_transient(exc: BaseException) -> bool:
    """Default transient-vs-fatal classification. Transient: OSError
    (incl. ConnectionError/TimeoutError/Interrupted) and EOFError, minus
    the fatal OSError shapes above. ChaosError (kind=error) is fatal by
    construction — the drill's proof that classification is typed, not
    catch-all."""
    if isinstance(exc, ChaosError):
        return False
    if isinstance(exc, _FATAL_OS):
        return False
    return isinstance(exc, (OSError, EOFError))


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 4
    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0

    @classmethod
    def from_knobs(cls) -> "RetryPolicy":
        return cls(
            max_attempts=max(int(knobs.get_int("YTK_RETRY_MAX")), 1),
            base_s=max(float(knobs.get_float("YTK_RETRY_BASE_S")), 0.0),
            max_s=max(float(knobs.get_float("YTK_RETRY_MAX_S")), 0.0),
        )

    def delay_s(self, attempt: int, site: str) -> float:
        """Backoff before retry `attempt+1` (attempt is 1-based): capped
        exponential, deterministically jittered into [0.5, 1.0)x."""
        raw = min(self.max_s, self.base_s * self.multiplier ** (attempt - 1))
        return raw * (0.5 + 0.5 * site_draw(_JITTER_SEED, site, attempt))


def _backoff_or_reraise(
    e: BaseException,
    attempt: int,
    policy: RetryPolicy,
    site: str,
    classify: Callable[[BaseException], bool],
    context: str = "",
) -> None:
    """The one classify/budget/evidence/backoff block (shared by
    retry_call and retry_lines so the policy can never diverge). Called
    from inside an except handler: re-raises fatal exceptions and
    exhausted budgets (with the `io.retry.giveup` record), otherwise
    records the attempt evidence and sleeps the jittered backoff."""
    if not classify(e):
        raise
    if attempt >= policy.max_attempts:
        obs_inc("io.retry.giveup")
        obs_event(
            "io.retry.giveup", site=site, attempts=attempt,
            error=f"{type(e).__name__}: {e}"[:200],
        )
        log.error(
            "retry[%s]: giving up after %d attempts: %s: %s",
            site, attempt, type(e).__name__, e,
        )
        raise
    delay = policy.delay_s(attempt, site)
    obs_inc("io.retry.attempts")
    obs_inc(f"io.retry.{site}")
    obs_event(
        "io.retry", site=site, attempt=attempt,
        delay_s=round(delay, 4), error=type(e).__name__,
    )
    log.warning(
        "retry[%s]: attempt %d/%d failed%s (%s: %s); backing off %.3fs",
        site, attempt, policy.max_attempts, context,
        type(e).__name__, e, delay,
    )
    time.sleep(delay)


def _record_recovered(site: str, attempt: int) -> None:
    obs_inc("io.retry.recovered")
    obs_event("io.retry.recovered", site=site, attempts=attempt)
    log.info("retry[%s]: recovered on attempt %d", site, attempt)


def retry_call(
    fn: Callable[[], T],
    site: str,
    policy: Optional[RetryPolicy] = None,
    classify: Callable[[BaseException], bool] = is_transient,
) -> T:
    """Run `fn()` with transient-fault retries. `site` names the seam in
    counters/events (conventionally a FAULT_SITES name, so the chaos site
    and its retry evidence line up). Fatal exceptions propagate on the
    first throw; transient ones propagate after the attempt budget with
    an `io.retry.giveup` record."""
    policy = policy or RetryPolicy.from_knobs()
    attempt = 0
    while True:
        attempt += 1
        try:
            out = fn()
        except Exception as e:
            _backoff_or_reraise(e, attempt, policy, site, classify)
            continue
        if attempt > 1:
            _record_recovered(site, attempt)
        return out


def retry_lines(
    open_fn: Callable[[], object],
    site: str,
    policy: Optional[RetryPolicy] = None,
    classify: Callable[[BaseException], bool] = is_transient,
):
    """Stream lines from a re-openable source with transient-fault
    retries at O(1) memory: on a mid-read transient failure the source is
    reopened and the already-yielded line count is skipped, so no line is
    ever yielded twice and no file is ever held whole in memory (the
    generator twin of `retry_call`; `FileSystem.read_lines` rides it)."""
    policy = policy or RetryPolicy.from_knobs()
    attempt = 0
    yielded = 0
    while True:
        attempt += 1
        try:
            f = open_fn()
            try:
                skip = yielded
                for line in f:
                    if skip:
                        skip -= 1
                        continue
                    yielded += 1
                    yield line
            finally:
                f.close()
        except Exception as e:
            _backoff_or_reraise(
                e, attempt, policy, site, classify,
                context=f" mid-stream after {yielded} lines",
            )
            continue
        if attempt > 1:
            _record_recovered(site, attempt)
        return
