"""Deterministic fault injection — named chaos sites on the hot paths.

A *fault site* is one `chaos_point("<site>")` call threaded through a
failure-prone seam (ingest read, collective, round sync, dump, continual
copy/promote, serve warm load — `FAULT_SITES` is the catalog). With no
spec armed a chaos point is one env read and a return; armed via

    YTK_CHAOS=<site>:<kind>:<rate>:<seed>[,<site>:<kind>:<rate>:<seed>...]

each matching call draws from a *counter-based* hash — draw n at a site
is a pure function of (seed, site, n), no host RNG state — so an injected
fault schedule reproduces exactly across runs, processes, and the
postmortem: rerunning with the same spec injects at the same calls.
`<site>` may end in `*` for prefix matching (`io.*`).

Kinds:

  oserror   raise ChaosOSError (an OSError, EIO) — *transient*: the
            resilience.retry classification retries it, so an armed run
            proves the retry budget absorbs transient faults
  error     raise ChaosError (RuntimeError) — fatal, never retried
  sigterm   SIGTERM to self — exercises the preemption guard / flight
            recorder emergency paths (the graceful-preemption drill)
  kill      os._exit(137) — a kill -9 stand-in: no handlers, no atexit,
            no flushes; only the on-disk checkpoint survives

Every injected fault increments `chaos.injected` (+ the per-site
counter) and lands a `chaos.inject` event in the flight-recorder ring
BEFORE acting, so a crash dump names exactly which draw fired.
See docs/fault_tolerance.md for the grammar and the drill.
"""

from __future__ import annotations

import errno
import logging
import os
import signal
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ..config import knobs
from ..obs import event as obs_event, inc as obs_inc

log = logging.getLogger("ytklearn_tpu.resilience")

#: site -> where it lives (docs + chaos_drill validation)
FAULT_SITES: Dict[str, str] = {
    "io.read": "ingest/model text read (FileSystem.read_lines, native "
               "parser byte reads)",
    "io.dump": "atomic dump commit (FileSystem.atomic_open replace)",
    "collective.host": "host-side collective (host_allgather_objects / "
                       "load_on_rank0 broadcast)",
    "gbdt.sync": "GBDT round-boundary loss sync (device pipeline drain)",
    "continual.copy": "continual shadow/archive chunked file copy",
    "continual.promote": "continual promotion/restore per-file replace",
    "serve.load": "serve registry warm load (initial load + hot reload)",
    "serve.worker": "serve replica worker /predict hot path (fleet front "
                    "restart drill — kind=kill takes one replica down "
                    "mid-load)",
}

KINDS = ("oserror", "error", "sigterm", "kill")

_MASK = (1 << 64) - 1


class ChaosError(RuntimeError):
    """A fatal injected fault (kind=error): never classified transient."""


class ChaosOSError(OSError):
    """A transient injected IO fault (kind=oserror): retry-classified."""


@dataclass(frozen=True)
class ChaosRule:
    site: str  # exact name or "prefix*"
    kind: str
    rate: float
    seed: int

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site


def parse_chaos_spec(raw: str) -> Tuple[ChaosRule, ...]:
    """`site:kind:rate:seed[,...]` -> rules; a malformed spec fails loud
    (a typo silently disarming the drill would defeat its purpose)."""
    rules = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 4:
            raise ValueError(
                f"bad YTK_CHAOS entry {part!r}: want site:kind:rate:seed"
            )
        site, kind, rate_s, seed_s = (f.strip() for f in fields)
        if kind not in KINDS:
            raise ValueError(
                f"bad YTK_CHAOS kind {kind!r} (one of {'|'.join(KINDS)})"
            )
        rate = float(rate_s)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"bad YTK_CHAOS rate {rate_s!r}: want [0, 1]")
        known = site in FAULT_SITES or (
            site.endswith("*")
            and any(s.startswith(site[:-1]) for s in FAULT_SITES)
        )
        if not known:
            log.warning(
                "YTK_CHAOS names unknown fault site %r (catalog: %s)",
                site, ", ".join(sorted(FAULT_SITES)),
            )
        rules.append(ChaosRule(site, kind, rate, int(seed_s)))
    return tuple(rules)


def site_draw(seed: int, site: str, n: int) -> float:
    """Draw n (1-based) at a site under a seed, in [0, 1): a splitmix64
    finalizer over (seed, site-hash, n). Pure + platform-stable — tests
    and the drill precompute injection schedules with it."""
    h = 0
    for ch in site.encode("utf-8"):
        h = (h * 131 + ch) & _MASK
    x = (h ^ ((seed & _MASK) * 0x9E3779B97F4A7C15)) & _MASK
    x = (x + n * 0xBF58476D1CE4E5B9) & _MASK
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _MASK
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _MASK
    x ^= x >> 31
    return x / 2.0**64


class _ChaosState:
    def __init__(self):
        self.lock = threading.Lock()
        self.counters: Dict[str, int] = {}  # site -> calls seen
        self.cached_raw: Optional[str] = None
        self.cached_rules: Tuple[ChaosRule, ...] = ()


_state = _ChaosState()


def reset_chaos() -> None:
    """Clear per-site call counters (test isolation; the armed spec itself
    lives in the env and is re-read on every chaos_point)."""
    with _state.lock:
        _state.counters.clear()
        _state.cached_raw = None
        _state.cached_rules = ()


def chaos_enabled() -> bool:
    return bool(knobs.get_str("YTK_CHAOS"))


def _rules() -> Tuple[ChaosRule, ...]:
    raw = knobs.get_str("YTK_CHAOS") or ""
    with _state.lock:
        if raw != _state.cached_raw:
            # parse BEFORE updating the cache: a malformed spec must raise
            # on EVERY chaos_point, not just the first — caching the raw
            # string first would silently disarm the drill after one
            # swallowed ValueError
            rules = parse_chaos_spec(raw) if raw else ()
            _state.cached_rules = rules
            _state.cached_raw = raw
        return _state.cached_rules


def chaos_point(site: str) -> None:
    """Named fault site. Disarmed: one env read. Armed: advance the site
    counter and inject per the first matching rule whose draw < rate."""
    rules = _rules()
    if not rules:
        return
    matching = [r for r in rules if r.matches(site)]
    if not matching:
        return
    with _state.lock:
        n = _state.counters.get(site, 0) + 1
        _state.counters[site] = n
    for r in matching:
        if site_draw(r.seed, site, n) < r.rate:
            _inject(site, r.kind, n)
            return  # sigterm returns here; one injection per call


def _inject(site: str, kind: str, n: int) -> None:
    # evidence FIRST: the counter + flight-ring event must exist even when
    # the injection is about to take the process down
    obs_inc("chaos.injected")
    obs_inc(f"chaos.injected.{site}")
    obs_event("chaos.inject", site=site, kind=kind, call=n)
    log.warning("chaos: injecting %s at %s (call %d)", kind, site, n)
    if kind == "oserror":
        raise ChaosOSError(
            errno.EIO, f"chaos: injected transient IO fault at {site} (call {n})"
        )
    if kind == "error":
        raise ChaosError(f"chaos: injected fatal fault at {site} (call {n})")
    if kind == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return
    # kill: the preemption that never knocks — skips handlers and atexit
    # exactly like an external kill -9 / hard preemption would
    os._exit(137)
