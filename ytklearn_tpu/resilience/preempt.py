"""Preemption-safe exit: deferred SIGTERM/SIGINT + emergency checkpoint.

Preemptible TPU VMs get a SIGTERM and a short grace window; the old
answer was the flight recorder's dump-and-die. Now trainers run under a
`PreemptionGuard`:

  1. the handler only SETS A FLAG (async-signal-safe: no locks, no IO —
     the registry lock the recorder has to tiptoe around is never touched
     from the handler here),
  2. the training loop checks the flag at its next *safe boundary* (GBDT
     round boundary, L-BFGS iteration callback, GBST tree boundary),
     dumps a complete checkpoint through the existing atomic dump path,
     writes a flight dump (reason=preempt) when the recorder is
     installed, and raises `Preempted`,
  3. the CLI maps `Preempted` to the conventional 128+signum exit code
     (143 for SIGTERM, 130 for SIGINT) and logs the resume line;
     `--resume auto` on the relaunch finds the checkpoint and re-enters
     training through the existing continue_train machinery.

GBDT resume is *bit-identical* to the uninterrupted run: the round
cursor derives from the dumped tree count and every per-round RNG key is
`fold_in(root_key, absolute_round)`, so nothing depends on where the run
was cut (pinned in tests/test_resilience.py). Convex families resume as
an L-BFGS warm start from the checkpoint weights; GBST resumes at the
last finished tree.

A second SIGINT escalates to the previous handler (the operator's double
Ctrl-C still kills a hung run immediately); SIGTERM stays deferred —
preemption only sends it once and the boundary is the safest exit.
"""

from __future__ import annotations

import contextlib
import logging
import signal
import threading
from typing import Dict, Iterator, Optional

from ..config import knobs
from ..obs import event as obs_event, inc as obs_inc, recorder

log = logging.getLogger("ytklearn_tpu.resilience")


class Preempted(RuntimeError):
    """Training exited early on a deferred SIGTERM/SIGINT after dumping
    an emergency checkpoint; `exit_code` is the conventional 128+signum."""

    def __init__(self, signum: int, checkpoint: str = ""):
        name = signal.Signals(signum).name if signum else "signal"
        msg = f"preempted by {name}"
        if checkpoint:
            msg += f"; emergency checkpoint at {checkpoint}"
        super().__init__(msg)
        self.signum = signum
        self.checkpoint = checkpoint

    @property
    def exit_code(self) -> int:
        return 128 + self.signum


class PreemptionGuard:
    """Deferred-signal flag + the boundary-side exit helper."""

    def __init__(self):
        self._event = threading.Event()
        self._signum: Optional[int] = None
        self._counts: Dict[int, int] = {}
        self._prev: Dict[int, object] = {}
        self.installed = False

    # -- handler side (async-signal-safe: flag + counters only) -----------

    def _handler(self, signum, frame):
        first_of_kind = self._counts.get(signum, 0) == 0
        self._counts[signum] = self._counts.get(signum, 0) + 1
        if self._signum is None:
            self._signum = signum
        self._event.set()
        if signum == signal.SIGINT and not first_of_kind:
            # second Ctrl-C: the operator means NOW — hand back to the
            # previous disposition (recorder hook / python default)
            prev = self._prev.get(signum)
            if callable(prev):
                signal.signal(signal.SIGINT, prev)
                prev(signum, frame)
                return
            raise KeyboardInterrupt

    def install(self) -> "PreemptionGuard":
        """Hook SIGTERM+SIGINT (idempotent). Off the main thread
        signal.signal is unavailable — the guard stays inert and
        `triggered` is always False, so a retrain embedded in a server
        thread trains exactly as before."""
        if self.installed:
            return self
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.signal(sig, self._handler)
            self.installed = True
        except ValueError:
            self._prev.clear()
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self.installed = False

    # -- boundary side -----------------------------------------------------

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self) -> int:
        return self._signum or signal.SIGTERM

    def preempt(self, checkpoint: str = "", **attrs) -> None:
        """Record the evidence and raise `Preempted`. Call AFTER the
        emergency checkpoint dump so the resume line names a complete
        model; the flight dump (when the recorder is installed) carries
        the chaos/retry/preempt event trail for the postmortem."""
        obs_inc("preempt.exits")
        obs_event(
            "preempt.checkpoint", signum=self.signum,
            checkpoint=checkpoint, **attrs,
        )
        if recorder.installed():
            recorder.dump("preempt")
        log.warning(
            "preempted (signal %d): emergency checkpoint %s — rerun with "
            "--resume auto to continue", self.signum, checkpoint or "n/a",
        )
        raise Preempted(self.signum, checkpoint)


@contextlib.contextmanager
def preemption_guard(enabled: Optional[bool] = None) -> Iterator[Optional[PreemptionGuard]]:
    """Install a guard for the duration of a training loop (YTK_PREEMPT=0
    opts out -> yields None and the loop runs with the process's existing
    signal dispositions)."""
    if enabled is None:
        enabled = knobs.get_bool("YTK_PREEMPT")
    if not enabled:
        yield None
        return
    guard = PreemptionGuard().install()
    try:
        yield guard
    finally:
        guard.uninstall()


@contextlib.contextmanager
def trainer_guard(trainer) -> Iterator[Optional[PreemptionGuard]]:
    """THE trainer-entry hook: flight-recorder hooks first, then the
    guard, with `trainer._guard` set for the loop's boundary checks. The
    install order is a LIFO invariant — the guard uninstalls at train
    end and must hand the signals back to the RECORDER'S handlers, not
    the other way around (a recorder installed second would chain to a
    dead guard handler after training). Keeping it here means every
    trainer gets the ordering right by construction."""
    recorder.auto_install()
    with preemption_guard() as guard:
        trainer._guard = guard
        try:
            yield guard
        finally:
            trainer._guard = None
