"""Command-line entry points — the bin/ layer.

Rebuild of reference bin/local_optimizer.sh:38-47 (model name + config +
optional py-transform, one local worker), predictor/Predicts.java:36-54
(offline batch predict CLI) and utils/LibsvmConvertTool.java:43 (format
converter). One host process drives the whole device mesh, so the
CommMaster rendezvous / per-slave JVM machinery has no equivalent: the
mesh is discovered from jax.devices() (or jax.distributed for
multi-host) instead of a TCP master.

Console scripts (pyproject.toml):
  ytklearn-tpu-train   <model_name> <config_path> [options]
  ytklearn-tpu-retrain <model_name> <config_path> [options]
  ytklearn-tpu-predict <config_path> <model_name> <file_dir> [options]
  ytklearn-tpu-serve   <config_path> <model_name> [options]
plus `python -m ytklearn_tpu.cli {train,retrain,predict,convert,serve} ...`.

`serve` and `retrain` have no reference counterpart (the reference stops
at the thread-safe OnlinePredictor library): `serve` fronts that API with
the compiled-scorer + micro-batching online layer (docs/serving.md), and
`retrain` is the continuous-training driver feeding its hot-reload
registry (docs/continual.md).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import List, Optional

MODEL_NAMES = (
    "linear",
    "multiclass_linear",
    "fm",
    "ffm",
    "gbmlr",
    "gbsdt",
    "gbhmlr",
    "gbhsdt",
    "gbdt",
)
GBST_NAMES = ("gbmlr", "gbsdt", "gbhmlr", "gbhsdt")


def _setup_logging(verbose: bool) -> None:
    logging.basicConfig(
        level=logging.DEBUG if verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        stream=sys.stderr,
    )


def _apply_overrides(cfg: dict, sets: List[str]) -> dict:
    """--set key=value overrides (reference: TrainWorker.setCustomParam ->
    config.withValue, worker/TrainWorker.java:118-131). Values parse as
    JSON when possible, else stay strings."""
    from .config import hocon

    for kv in sets or []:
        key, sep, val = kv.partition("=")
        if not sep:
            raise SystemExit(f"--set expects key=value, got {kv!r}")
        try:
            parsed = json.loads(val)
        except json.JSONDecodeError:
            parsed = val
        cfg = hocon.set_path(cfg, key.strip(), parsed)
    return cfg


def _make_mesh(n_devices: Optional[int]):
    import jax

    from .parallel.mesh import make_mesh

    avail = len(jax.devices())
    n = n_devices if n_devices and n_devices > 0 else avail
    if n > avail:
        raise SystemExit(f"requested {n} devices, only {avail} available")
    return make_mesh(n) if n > 1 else None


def _load_hook(need: bool, script: str):
    if not need:
        return None
    from .io.reader import load_transform_hook

    return load_transform_hook(script)


def _setup_trace(trace_out: str) -> None:
    """--trace-out: enable obs + register the Chrome-trace export."""
    if trace_out:
        from . import obs

        obs.configure(enabled=True, trace_path=trace_out)


def _flush_trace(trace_out: str) -> None:
    """Write the trace now — *_main may be driven in-process (no atexit)."""
    if trace_out:
        from . import obs

        obs.flush()


def _setup_profile(profile: Optional[str]) -> None:
    """--profile [DIR]: arm the ytkprof profiling plane (phase accounting,
    compile ledger, memory-watermark sampler); with DIR, also capture
    jax.profiler traces per phase into it (YTK_PROF everywhere else)."""
    if profile is None:
        return
    from .obs import profiler

    if profile:
        profiler.configure_profiler(on=True, capture_dir=profile)
    else:
        profiler.configure_profiler(on=True)


def train_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytklearn-tpu-train",
        description="Train any ytk-learn model family on the TPU mesh "
        "(reference: bin/local_optimizer.sh + LocalTrainWorker)",
    )
    ap.add_argument("model_name", choices=MODEL_NAMES)
    ap.add_argument("config_path")
    ap.add_argument("--transform", action="store_true", help="enable the python line-transform hook")
    ap.add_argument("--transform-script", default="bin/transform.py")
    ap.add_argument("--devices", type=int, default=0, help="mesh size (default: all local devices)")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="on failure, retry with model.continue_train=true to "
                    "resume from the last checkpoint dump (reference: the "
                    "bin/hadoop_optimizer.sh:53-80 restart loop)")
    ap.add_argument("--resume", default="never", choices=("never", "auto"),
                    help="auto: when a complete checkpoint already exists at "
                    "model.data_path, re-enter training from it "
                    "(model.continue_train=true) — the relaunch half of the "
                    "preemption contract: a SIGTERM'd run dumps an emergency "
                    "checkpoint at its next round/iteration boundary and "
                    "exits 143 (docs/fault_tolerance.md)")
    ap.add_argument("--coordinator", default="",
                    help="host:port of the jax.distributed coordinator — the "
                    "CommMaster equivalent; use with --num-processes/"
                    "--process-id for multi-host training")
    ap.add_argument("--num-processes", type=int, default=0)
    ap.add_argument("--process-id", type=int, default=-1)
    ap.add_argument("--set", action="append", dest="sets", metavar="KEY=VALUE",
                    help="config override, repeatable")
    ap.add_argument("--trace-out", default="",
                    help="write a Chrome-trace/Perfetto JSON of the run to "
                    "this path (YTK_TRACE=path everywhere else; see "
                    "docs/observability.md)")
    ap.add_argument("--profile", nargs="?", const="", default=None,
                    metavar="DIR",
                    help="arm the ytkprof profiling plane: phase/device-time "
                    "accounting, compile ledger, memory watermarks; with DIR "
                    "also capture jax.profiler traces into it (YTK_PROF "
                    "everywhere else; see docs/observability.md)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    _setup_logging(args.verbose)
    _setup_trace(args.trace_out)
    _setup_profile(args.profile)

    from .config import knobs

    platform = knobs.get_str("YTK_PLATFORM")
    if platform:
        # explicit platform pin that works even when a sitecustomize
        # pre-imported jax and already captured JAX_PLATFORMS
        import jax

        jax.config.update("jax_platforms", platform)
    # multi-host rendezvous BEFORE any backend touch (the CommMaster
    # equivalent; reference: bin/cluster_optimizer.sh slave fan-out).
    # Without --coordinator this is a no-op unless YTKLEARN_TPU_DISTRIBUTED=1
    # asks for pod auto-detection; unset world params stay None so jax
    # auto-detects topology.
    from .parallel.mesh import distributed_initialize_if_needed

    kw = {}
    if args.coordinator:
        kw["coordinator_address"] = args.coordinator
        if args.num_processes > 0:
            kw["num_processes"] = args.num_processes
        if args.process_id >= 0:
            kw["process_id"] = args.process_id
    distributed_initialize_if_needed(**kw)

    from .config import hocon

    cfg = _apply_overrides(hocon.load(args.config_path), args.sets)
    mesh = _make_mesh(args.devices)
    hook = _load_hook(args.transform, args.transform_script)
    name = args.model_name

    log = logging.getLogger("ytklearn_tpu.cli")
    if args.resume == "auto":
        # atomic dumps (fs.atomic_open) mean model.data_path either holds
        # the newest COMPLETE checkpoint or nothing — no torn-file triage
        from .io.fs import create_filesystem as _mkfs

        _fs = _mkfs(str(cfg.get("fs_scheme", "local")))
        _mpath = hocon.get_path(cfg, "model.data_path")
        if _mpath and _fs.exists(str(_mpath)):
            cfg = hocon.set_path(cfg, "model.continue_train", True)
            log.info("--resume auto: checkpoint found at %s; resuming", _mpath)
        else:
            log.info(
                "--resume auto: no checkpoint at %s; cold start", _mpath
            )
    restarts = max(args.max_restarts, 0)
    import jax as _jax

    if restarts and _jax.process_count() > 1:
        # a single rank re-entering training would desynchronize the
        # group's collectives; multi-process recovery = restart the whole
        # launcher with continue_train (the reference's model too:
        # bin/hadoop_optimizer.sh restarts the entire job)
        log.warning(
            "--max-restarts is per-process and unsafe in multi-process "
            "mode; disabled — restart the launcher to resume from the "
            "last checkpoint"
        )
        restarts = 0
    from .resilience import Preempted

    for attempt in range(restarts + 1):
        try:
            rc = _train_once(name, cfg, mesh, hook)
            _flush_trace(args.trace_out)
            return rc
        except Preempted as e:
            # not a failure: the emergency checkpoint is on disk and the
            # restart loop must NOT eat the grace period re-entering
            # training — exit with the signal's conventional status so
            # the scheduler relaunches (with --resume auto) instead
            log.warning("%s; exiting %d", e, e.exit_code)
            _flush_trace(args.trace_out)
            return e.exit_code
        except KeyboardInterrupt:
            raise
        except Exception:
            if attempt >= restarts:
                raise
            log.exception(
                "training attempt %d/%d failed; restarting with "
                "model.continue_train=true",
                attempt + 1, restarts + 1,
            )
            # resume from the last periodic dump (fail-fast + restart is the
            # reference's recovery model: checkpoint-as-model + relaunch)
            cfg = hocon.set_path(cfg, "model.continue_train", True)
    return 1  # unreachable


def _train_once(name: str, cfg: dict, mesh, hook) -> int:
    from .io.fs import create_filesystem

    fs = create_filesystem(str(cfg.get("fs_scheme", "local")))
    if name == "gbdt":
        from .config.params import GBDTParams
        from .gbdt.data import GBDTIngest
        from .gbdt.trainer import GBDTTrainer

        p = GBDTParams.from_config(cfg)
        ingest = GBDTIngest(p, fs=fs, transform_hook=hook)
        train, test = ingest.load()
        res = GBDTTrainer(p, mesh=mesh, fs=fs).train(train=train, test=test)
        print(json.dumps({
            "model": name,
            "trees": len(res.model.trees),
            "train_loss": res.train_loss,
            "test_loss": res.test_loss,
            "train_metrics": res.train_metrics,
            "test_metrics": res.test_metrics,
        }))
        return 0

    from .config.params import CommonParams

    p = CommonParams.from_config(cfg)
    if name in GBST_NAMES:
        from .boost import GBSTTrainer
        from .io.reader import DataIngest

        ingest = DataIngest(p, fs=fs, transform_hook=hook).load()
        res = GBSTTrainer(p, name, mesh=mesh, fs=fs).train(ingest=ingest)
        print(json.dumps({
            "model": name,
            "trees": res.n_trees,
            "train_loss": res.train_loss,
            "test_loss": res.test_loss,
            "train_metrics": res.train_metrics,
            "test_metrics": res.test_metrics,
        }))
        return 0

    from .train import HoagTrainer

    res = HoagTrainer(p, name, mesh=mesh, fs=fs, transform_hook=hook).train()
    print(json.dumps({
        "model": name,
        "n_iter": res.n_iter,
        "status": res.status,
        "avg_loss": res.avg_loss,
        "test_loss": res.test_loss,
        "train_metrics": res.train_metrics,
        "test_metrics": res.test_metrics,
    }))
    return 0


def predict_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytklearn-tpu-predict",
        description="Offline batch prediction "
        "(reference: bin/predict.sh + predictor/Predicts.java:36-54)",
    )
    ap.add_argument("config_path")
    ap.add_argument("model_name", choices=MODEL_NAMES)
    ap.add_argument("file_dir", help="file or directory of data to predict")
    ap.add_argument("--transform", action="store_true")
    ap.add_argument("--transform-script", default="bin/transform.py")
    ap.add_argument("--save-mode", default="predict_result_only",
                    choices=("predict_result_only", "label_and_predict", "predict_as_feature"))
    ap.add_argument("--suffix", default="_predict")
    ap.add_argument("--max-error-tol", type=int, default=100)
    ap.add_argument("--eval-metric", default="", help='e.g. "auc,mae"')
    ap.add_argument("--predict-type", default="value", choices=("value", "leafid"))
    ap.add_argument("--set", action="append", dest="sets", metavar="KEY=VALUE")
    ap.add_argument("--trace-out", default="",
                    help="write a Chrome-trace/Perfetto JSON of the batch "
                    "predict to this path")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    _setup_logging(args.verbose)
    _setup_trace(args.trace_out)

    from .config import hocon
    from .predict import batch_predict_from_files, create_predictor

    cfg = _apply_overrides(hocon.load(args.config_path), args.sets)
    predictor = create_predictor(args.model_name, cfg)
    K = int(cfg.get("k", -1)) if args.model_name == "multiclass_linear" else -1
    avg_loss = batch_predict_from_files(
        predictor,
        args.model_name,
        args.file_dir,
        need_py_transform=args.transform,
        py_transform_script=args.transform_script,
        result_save_mode=args.save_mode,
        result_file_suffix=args.suffix,
        max_error_tol=args.max_error_tol,
        eval_metric_str=args.eval_metric,
        predict_type_str=args.predict_type,
        K=K,
    )
    _flush_trace(args.trace_out)
    print(json.dumps({"model": args.model_name, "avg_loss": avg_loss}))
    return 0


def convert_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytklearn-tpu-convert",
        description="libsvm -> ytklearn format "
        "(reference: bin/libsvm_convert_2_ytklearn.sh + utils/LibsvmConvertTool.java)",
    )
    ap.add_argument("mode", help='binary_classification@l0,l1 | '
                                 'multi_classification@l0,l1,... | regression')
    ap.add_argument("input_path")
    ap.add_argument("output_path")
    ap.add_argument("--x-delim", default="###")
    ap.add_argument("--y-delim", default=",")
    ap.add_argument("--features-delim", default=",")
    ap.add_argument("--feature-name-val-delim", default=":")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    _setup_logging(args.verbose)

    from .io.libsvm import convert_libsvm

    cnt = convert_libsvm(
        args.mode,
        args.input_path,
        args.output_path,
        x_delim=args.x_delim,
        y_delim=args.y_delim,
        features_delim=args.features_delim,
        feature_name_val_delim=args.feature_name_val_delim,
    )
    print(json.dumps({"lines": cnt, "output": args.output_path}))
    return 0


def retrain_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytklearn-tpu-retrain",
        description="Continuous training driver: warm-start a candidate on "
        "new data in a shadow path, validate it against the health gates "
        "and a held-out metric band versus the serving incumbent, and "
        "atomically promote only on pass — the serving registry's "
        "fingerprint watcher hot-swaps the promoted model under traffic "
        "(docs/continual.md)",
    )
    ap.add_argument("model_name", choices=MODEL_NAMES)
    ap.add_argument("config_path")
    ap.add_argument("--data", default="",
                    help="fresh training data path(s) (comma-separated); "
                    "overrides data.train.data_path")
    ap.add_argument("--test", default="",
                    help="held-out data path(s) for the metric gate; "
                    "overrides data.test.data_path")
    ap.add_argument("--mode", default="", choices=("", "warm", "ftrl"),
                    help="warm = full warm-start refit (default); ftrl = "
                    "one FTRL-proximal online pass (convex families)")
    ap.add_argument("--extra-rounds", type=int, default=-1,
                    help="extra boosting rounds for GBDT/GBST warm starts "
                    "(default: continual.extra_rounds)")
    ap.add_argument("--rollback", action="store_true",
                    help="restore the newest archived version over the "
                    "served path instead of retraining")
    ap.add_argument("--transform", action="store_true",
                    help="enable the python line-transform hook")
    ap.add_argument("--transform-script", default="bin/transform.py")
    ap.add_argument("--devices", type=int, default=0,
                    help="mesh size (default: all local devices)")
    ap.add_argument("--set", action="append", dest="sets", metavar="KEY=VALUE",
                    help="config override, repeatable")
    ap.add_argument("--trace-out", default="",
                    help="write a Chrome-trace/Perfetto JSON of the retrain")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    _setup_logging(args.verbose)
    _setup_trace(args.trace_out)

    from .config import hocon
    from .continual import RetrainRejected, retrain, rollback

    cfg = _apply_overrides(hocon.load(args.config_path), args.sets)
    if args.data:
        cfg = hocon.set_path(cfg, "data.train.data_path", args.data)
    if args.test:
        cfg = hocon.set_path(cfg, "data.test.data_path", args.test)

    if args.rollback:
        res = rollback(args.model_name, cfg)
        _flush_trace(args.trace_out)
        print(json.dumps(res.to_json()))
        return 0

    mesh = _make_mesh(args.devices)
    hook = _load_hook(args.transform, args.transform_script)
    from .resilience import Preempted

    try:
        res = retrain(
            args.model_name, cfg, mesh=mesh,
            mode=args.mode or None,
            extra_rounds=args.extra_rounds if args.extra_rounds >= 0 else None,
            transform_hook=hook,
        )
    except Preempted as e:
        # candidate training was preempted; the incumbent keeps serving,
        # the lock is released, and the next cron tick simply retrains
        logging.getLogger("ytklearn_tpu.cli").warning("%s; exiting %d", e, e.exit_code)
        _flush_trace(args.trace_out)
        return e.exit_code
    except RetrainRejected as e:
        # YTK_CONTINUAL_STRICT=1: a rejection is a hard failure for the
        # surrounding pipeline, but still a clean JSON record on stdout
        print(json.dumps({
            "promoted": False,
            "strict": True,
            "reasons": e.report.reasons,
        }))
        _flush_trace(args.trace_out)
        return 1
    _flush_trace(args.trace_out)
    print(json.dumps(res.to_json()))
    return 0


def serve_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytklearn-tpu-serve",
        description="Online prediction server: compiled batch scorer with a "
        "padded shape ladder, dynamic micro-batching with backpressure, and "
        "fingerprint-watch hot model reload (docs/serving.md)",
    )
    ap.add_argument("config_path")
    ap.add_argument("model_name", choices=MODEL_NAMES)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080,
                    help="listen port (0 picks an ephemeral port)")
    ap.add_argument("--name", default="default",
                    help="registry name for this model (the default target "
                    "of /predict requests without a \"model\" field)")
    ap.add_argument("--extra-model", action="append", default=[],
                    metavar="NAME:MODEL_NAME:CONFIG_PATH",
                    help="load an additional model into the registry "
                    "(repeatable) — multi-model serving from one process; "
                    "requests address it via the \"model\" field. In fleet "
                    "mode every replica loads every model")
    ap.add_argument("--ladder", default="",
                    help='compiled batch-shape ladder, e.g. "1,8,64,512" '
                    "(default; env YTK_SERVE_LADDER). Every rung compiles "
                    "once at load, so steady-state traffic never retraces")
    ap.add_argument("--max-batch", type=int, default=512,
                    help="max rows coalesced into one scorer call")
    ap.add_argument("--max-wait-ms", type=float, default=2.0,
                    help="micro-batch straggler wait after the first request")
    ap.add_argument("--max-queue", type=int, default=2048,
                    help="pending-request bound; beyond it requests are shed "
                    "with a typed 429 instead of queueing unboundedly")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="default per-request deadline (0 = none); expired "
                    "requests fail with 504 before wasting scorer time")
    ap.add_argument("--watch-interval", type=float, default=None,
                    help="model-file fingerprint poll seconds for hot reload "
                    "(default 5; 0 disables; env YTK_SERVE_WATCH_S)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="serving fleet size: N spawns N replica worker "
                    "processes behind a shared-nothing front (-1 = one per "
                    "device, or per core on CPU; 0 = single-process; env "
                    "YTK_SERVE_REPLICAS — see docs/serving.md)")
    ap.add_argument("--replicas-min", type=int, default=None,
                    help="fleet autoscaler floor: minimum replica slots "
                    "(default: --replicas; env YTK_SERVE_REPLICAS_MIN — "
                    "see docs/serving.md autoscaling)")
    ap.add_argument("--replicas-max", type=int, default=None,
                    help="fleet autoscaler ceiling: maximum replica slots "
                    "(default: --replicas, which disarms autoscaling; env "
                    "YTK_SERVE_REPLICAS_MAX). A band wider than one value "
                    "arms the load-driven autoscaler: the front grows or "
                    "drain-reaps replicas within [min, max] from backlog/"
                    "shed/p99 signals")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="p99 latency SLO in ms for the AIMD batch-size "
                    "controller (0 disables AIMD and restores the fixed "
                    "--max-batch/--max-wait-ms; env YTK_SERVE_SLO_MS, "
                    "default 100)")
    ap.add_argument("--cache-rows", type=int, default=None,
                    help="bounded LRU prediction-cache rows, keyed on "
                    "(model fingerprint, feature row); 0 disables (env "
                    "YTK_SERVE_CACHE_ROWS)")
    ap.add_argument("--replica-id", type=int, default=None,
                    help="fleet-internal: this process is replica N (set by "
                    "the front; stamps obs identity for postmortems)")
    ap.add_argument("--set", action="append", dest="sets", metavar="KEY=VALUE",
                    help="config override, repeatable")
    ap.add_argument("--trace-out", default="",
                    help="write a Chrome-trace/Perfetto JSON at shutdown")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    _setup_logging(args.verbose)
    _setup_trace(args.trace_out)

    from .config import knobs

    replicas = (args.replicas if args.replicas is not None
                else knobs.get_int("YTK_SERVE_REPLICAS"))
    slo_ms = (args.slo_ms if args.slo_ms is not None
              else knobs.get_float("YTK_SERVE_SLO_MS"))
    cache_rows = (args.cache_rows if args.cache_rows is not None
                  else knobs.get_int("YTK_SERVE_CACHE_ROWS"))
    # autoscaling band (0 / unset = follow --replicas = fixed fleet); a
    # band alone is enough to go fleet mode: `--replicas-max 4` on a
    # default single-process invocation serves one replica that can grow
    r_min = (args.replicas_min if args.replicas_min is not None
             else knobs.get_int("YTK_SERVE_REPLICAS_MIN")) or 0
    r_max = (args.replicas_max if args.replicas_max is not None
             else knobs.get_int("YTK_SERVE_REPLICAS_MAX")) or 0

    if replicas != 0 or r_max > 0 or r_min > 0:
        return _serve_fleet_main(args, replicas, slo_ms, cache_rows,
                                 r_min, r_max)

    from .config import hocon
    from . import obs
    from .serve import BatchPolicy, ModelRegistry, ServeApp, parse_ladder

    if args.replica_id is not None:
        # fleet worker: every obs event / flight dump / metrics scrape
        # from this process names its replica
        obs.set_identity(replica_id=args.replica_id)

    cfg = _apply_overrides(hocon.load(args.config_path), args.sets)
    ladder = parse_ladder(args.ladder) if args.ladder else None
    registry = ModelRegistry(ladder=ladder, watch_interval_s=args.watch_interval)
    registry.load(args.name, args.model_name, cfg)
    for spec in args.extra_model:
        try:
            xname, xmodel, xconf = spec.split(":", 2)
        except ValueError:
            ap.error(f"--extra-model {spec!r}: expected "
                     "NAME:MODEL_NAME:CONFIG_PATH")
        if xmodel not in MODEL_NAMES:
            ap.error(f"--extra-model {spec!r}: unknown model family "
                     f"{xmodel!r} (choices: {', '.join(MODEL_NAMES)})")
        registry.load(xname, xmodel,
                      _apply_overrides(hocon.load(xconf), args.sets))
    registry.start_watching()
    policy = BatchPolicy(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        default_deadline_ms=args.deadline_ms,
    )
    app = ServeApp(
        registry, policy, host=args.host, port=args.port,
        slo_ms=slo_ms, cache_rows=cache_rows, replica_id=args.replica_id,
    ).start()
    app.install_signal_handlers()
    print(json.dumps({
        "serving": args.name,
        "model": args.model_name,
        "host": args.host,
        "port": app.port,
        "replica_id": args.replica_id,
        "ladder": list(registry.get(args.name).scorer.ladder),
        # monotonic-offset handshake: this process's obs clock origin on
        # the wall clock — the fleet front stamps it on the replica handle
        # so cross-process trace merges stay aligned (obs/trace.py)
        "wall_t0": obs.core.WALL_T0,
    }), flush=True)
    try:
        while app._serve_thread is not None and app._serve_thread.is_alive():
            app._serve_thread.join(timeout=1.0)
    except KeyboardInterrupt:
        app.stop(drain=True)
    _flush_trace(args.trace_out)
    return 0


def _serve_fleet_main(args, replicas: int, slo_ms, cache_rows,
                      r_min: int = 0, r_max: int = 0) -> int:
    """`serve --replicas N`: front process owning N worker subprocesses."""
    from .serve import (
        BatchPolicy,
        FleetFront,
        default_replica_count,
        serve_worker_argv,
    )

    if replicas < 0:
        replicas = default_replica_count()
    if replicas == 0:
        # reached via a bare autoscaling band (--replicas-max without
        # --replicas): start at the floor and let load grow the fleet
        replicas = max(1, r_min)
    worker_flags = []
    for flag, val in (
        ("--name", args.name),
        ("--ladder", args.ladder),
        ("--max-batch", args.max_batch),
        ("--max-wait-ms", args.max_wait_ms),
        ("--max-queue", args.max_queue),
        ("--deadline-ms", args.deadline_ms),
        ("--watch-interval", args.watch_interval),
        ("--slo-ms", slo_ms),
        ("--cache-rows", cache_rows),
    ):
        if val not in (None, ""):
            worker_flags += [flag, str(val)]
    for s in args.sets or []:
        worker_flags += ["--set", s]
    for spec in getattr(args, "extra_model", None) or []:
        # every replica serves the full model set (shared-nothing fleet:
        # any replica can answer any named-model request)
        worker_flags += ["--extra-model", spec]
    if args.verbose:
        worker_flags.append("--verbose")
    front = FleetFront(
        serve_worker_argv(args.config_path, args.model_name, worker_flags),
        replicas,
        policy=BatchPolicy(
            max_batch=args.max_batch,
            max_wait_ms=min(args.max_wait_ms, 1.0),
            max_queue=args.max_queue,
            default_deadline_ms=args.deadline_ms,
        ),
        host=args.host,
        port=args.port,
        slo_ms=slo_ms,
        replicas_min=(r_min or None),
        replicas_max=(r_max or None),
    )
    front.start().serve_http()
    front.install_signal_handlers()
    from . import obs

    print(json.dumps({
        "serving": args.name,
        "model": args.model_name,
        "host": args.host,
        "port": front.port,
        "replicas": front.n_replicas,
        "replicas_min": front.replicas_min,
        "replicas_max": front.replicas_max,
        "autoscale": front.autoscaler is not None,
        "fleet": True,
        "replica_ports": {
            str(rid): h.port for rid, h in sorted(front.handles.items())
        },
        "wall_t0": obs.core.WALL_T0,
    }), flush=True)
    try:
        while front._serve_thread is not None and front._serve_thread.is_alive():
            front._serve_thread.join(timeout=1.0)
    except KeyboardInterrupt:
        front.stop(drain=True)
    _flush_trace(args.trace_out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m ytklearn_tpu.cli "
              "{train,retrain,predict,convert,serve} ...")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "train":
        return train_main(rest)
    if cmd == "retrain":
        return retrain_main(rest)
    if cmd == "predict":
        return predict_main(rest)
    if cmd == "convert":
        return convert_main(rest)
    if cmd == "serve":
        return serve_main(rest)
    print(f"unknown command {cmd!r}; expected "
          "train|retrain|predict|convert|serve", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
