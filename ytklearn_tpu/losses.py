"""Loss/activation library — the TPU rebuild of the reference `loss/` package.

Every loss is a set of *pure, elementwise jnp functions* designed to be
vmapped/jitted over sample batches (the reference instead calls scalar
virtual methods per sample inside the per-thread loops —
reference: loss/ILossFunction.java:47, loss/LossFunctions.java:31-79).

Scalar-score losses expose:
    loss(score, label)              objective per sample
    predict(score)                  score -> prediction
    pred2score(pred)                inverse of predict
    first_derivative(score, label)  dL/dscore
    second_derivative(score, label) d2L/dscore2
    grad_hess(pred, label)          (g, h) from *prediction* — the GBDT fast
                                    path (reference: ILossFunction.getDerivativeFast)

Multiclass losses (softmax / hsoftmax / multiclass_*hinge) operate on the
trailing axis K:
    loss(scores[..., K], labels[..., K])      -> [...]
    predict(scores[..., K])                   -> [..., K]
    first_derivative(scores, labels)          -> [..., K]
    grad_hess(pred[..., K], labels[..., K])   -> (g, h) each [..., K]

All functions accept arrays and broadcast; labels for binary losses are in
{0,1} (margin losses internally map to ±1 exactly as the reference does).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Clamp constants mirrored from the reference.
_POISSON_MAX_EXP = 30.0  # reference: loss/PoissonFunction.java MAX_EXP
_EXP_MAX_EXP = 8.0  # reference: loss/ExponentialFunction.java MAX_EXP


def _softplus(x):
    """Numerically-stable log(1+exp(x)) (the reference branches on sign;
    jnp.logaddexp is the branch-free equivalent)."""
    return jnp.logaddexp(0.0, x)


class LossFunction:
    """Base: scalar-score loss. Subclasses override the static math."""

    name = "base"
    is_multiclass = False
    # reference: loss/LossFunctions.java:79 pureClassification
    pure_classification = False

    def loss(self, score, label):
        raise NotImplementedError

    def predict(self, score):
        return score

    def pred2score(self, pred):
        return pred

    def first_derivative(self, score, label):
        raise NotImplementedError

    def second_derivative(self, score, label):
        return jnp.ones_like(jnp.asarray(score, jnp.float32))

    def grad_hess(self, pred, label):
        """(g,h) wrt score, given *prediction* (GBDT fast path)."""
        score = self.pred2score(pred)
        return self.first_derivative(score, label), self.second_derivative(score, label)

    def check_label(self, y) -> bool:
        return True


class Sigmoid(LossFunction):
    """Logistic loss (reference: loss/SigmoidFunction.java)."""

    name = "sigmoid"
    pure_classification = True

    def __init__(self, zmax: float = 0.0):
        # sigmoid_zmax clamps |g/h| in the GBDT fast path
        # (reference: SigmoidFunction.getDerivativeFast + setParam).
        self.zmax = float(zmax)

    def loss(self, score, label):
        return _softplus(score) - score * label

    def predict(self, score):
        return jax.nn.sigmoid(score)

    def pred2score(self, pred):
        return -jnp.log(1.0 / pred - 1.0)

    def first_derivative(self, score, label):
        return jax.nn.sigmoid(score) - label

    def second_derivative(self, score, label):
        p = jax.nn.sigmoid(score)
        return p * (1.0 - p)

    def grad_hess(self, pred, label):
        g = pred - label
        h = pred * (1.0 - pred)
        if self.zmax > 0.0:
            # cap the implied newton step z=-g/h at ±zmax by inflating h
            z = jnp.where(h != 0.0, -g / h, 0.0)
            h = jnp.where(z > self.zmax, -(g / self.zmax), h)
            h = jnp.where(z < -self.zmax, g / self.zmax, h)
        return g, h

    def check_label(self, y) -> bool:
        return bool(jnp.all((y >= 0.0) & (y <= 1.0)))


class L2(LossFunction):
    """Squared error (reference: loss/L2Function.java)."""

    name = "l2"

    def loss(self, score, label):
        d = label - score
        return 0.5 * d * d

    def first_derivative(self, score, label):
        return score - label

    def grad_hess(self, pred, label):
        return pred - label, jnp.ones_like(pred)


class L1(LossFunction):
    """Absolute error; 2nd derivative reported as 1.0 like the reference so
    L-BFGS curvature stays positive (reference: loss/L1Function.java)."""

    name = "l1"

    def loss(self, score, label):
        return jnp.abs(label - score)

    def first_derivative(self, score, label):
        return jnp.sign(score - label)

    def grad_hess(self, pred, label):
        return jnp.sign(pred - label), jnp.ones_like(pred)


class Huber(LossFunction):
    """Huber loss with threshold delta (reference: loss/HuberFunction.java)."""

    name = "huber"

    def __init__(self, delta: float = 0.5):
        self.delta = float(delta)

    def loss(self, score, label):
        a = jnp.abs(score - label)
        return jnp.where(
            a <= self.delta, 0.5 * a * a, self.delta * (a - 0.5 * self.delta)
        )

    def first_derivative(self, score, label):
        a = score - label
        return jnp.where(jnp.abs(a) <= self.delta, a, jnp.sign(a) * self.delta)

    def second_derivative(self, score, label):
        return jnp.zeros_like(jnp.asarray(score, jnp.float32))


class Poisson(LossFunction):
    """Poisson regression, score = log(rate); the log(y!) constant term is
    dropped (the reference adds it via a lookup table, which shifts the loss
    by a constant and never affects gradients — reference:
    loss/PoissonFunction.java logyfunc)."""

    name = "poisson"

    def loss(self, score, label):
        s = jnp.minimum(score, _POISSON_MAX_EXP)
        lbl = jnp.maximum(label, 0.0)
        # lgamma(y+1) = log(y!) — exact counterpart of the reference's table.
        return -label * score + jnp.exp(s) + jax.lax.lgamma(lbl + 1.0)

    def predict(self, score):
        return jnp.exp(jnp.minimum(score, _POISSON_MAX_EXP))

    def pred2score(self, pred):
        return jnp.log(pred)

    def first_derivative(self, score, label):
        return jnp.exp(jnp.minimum(score, _POISSON_MAX_EXP)) - label

    def second_derivative(self, score, label):
        return jnp.exp(jnp.minimum(score, _POISSON_MAX_EXP))

    def grad_hess(self, pred, label):
        return pred - label, pred

    def check_label(self, y) -> bool:
        return bool(jnp.all(y >= 0.0))


class MAPE(LossFunction):
    """reference: loss/MAPEFunction.java."""

    name = "mape"

    def loss(self, score, label):
        return jnp.abs((label - score) / label)

    def first_derivative(self, score, label):
        return jnp.sign(score - label) / label


class InvMAPE(LossFunction):
    """reference: loss/InvMAPEFunction.java."""

    name = "inv_mape"

    def loss(self, score, label):
        return jnp.abs((label - score) / score)

    def first_derivative(self, score, label):
        return jnp.sign((score - label) / score) * label / (score * score)


class SMAPE(LossFunction):
    """reference: loss/SMAPEFunction.java."""

    name = "smape"

    def loss(self, score, label):
        return jnp.abs(score - label) / ((label + jnp.abs(score)) / 2.0)

    def first_derivative(self, score, label):
        deno = (label + jnp.abs(score)) / 2.0
        return (
            jnp.sign(score - label) * deno
            - 0.5 * jnp.sign(score) * jnp.abs(score - label)
        ) / (deno * deno)


class Hinge(LossFunction):
    """reference: loss/HingeFunction.java. Labels in {0,1} mapped to ±1."""

    name = "hinge"
    pure_classification = True

    def loss(self, score, label):
        return jnp.maximum(0.0, 1.0 - (2.0 * label - 1.0) * score)

    def first_derivative(self, score, label):
        ylab = 2.0 * label - 1.0
        return jnp.where(ylab * score < 1.0, -ylab, 0.0)

    def second_derivative(self, score, label):
        return jnp.zeros_like(jnp.asarray(score, jnp.float32))


class L2Hinge(LossFunction):
    """reference: loss/L2HingeFunction.java."""

    name = "l2_hinge"
    pure_classification = True

    def loss(self, score, label):
        m = jnp.maximum(0.0, 1.0 - (2.0 * label - 1.0) * score)
        return 0.5 * m * m

    def first_derivative(self, score, label):
        ylab = 2.0 * label - 1.0
        z = ylab * score
        return jnp.where(z <= 1.0, (z - 1.0) * ylab, 0.0)


class SmoothHinge(LossFunction):
    """reference: loss/SmoothHingeFunction.java."""

    name = "smooth_hinge"
    pure_classification = True

    def loss(self, score, label):
        z = (2.0 * label - 1.0) * score
        return jnp.where(
            z <= 0.0,
            0.5 - z,
            jnp.where(z < 1.0, 0.5 * (1.0 - z) * (1.0 - z), 0.0),
        )

    def first_derivative(self, score, label):
        ylab = 2.0 * label - 1.0
        z = ylab * score
        return jnp.where(z <= 0.0, -ylab, jnp.where(z < 1.0, -ylab * (1.0 - z), 0.0))

    def second_derivative(self, score, label):
        ylab = 2.0 * label - 1.0
        z = ylab * score
        return jnp.where((z > 0.0) & (z < 1.0), ylab * ylab, 0.0)


class Exponential(LossFunction):
    """AdaBoost-style exponential loss, exp clamp at 8
    (reference: loss/ExponentialFunction.java)."""

    name = "exponential"
    pure_classification = True

    def loss(self, score, label):
        ylab = 2.0 * label - 1.0
        return jnp.exp(jnp.minimum(-score * ylab, _EXP_MAX_EXP))

    def first_derivative(self, score, label):
        ylab = 2.0 * label - 1.0
        return -ylab * jnp.exp(jnp.minimum(-score * ylab, _EXP_MAX_EXP))

    def second_derivative(self, score, label):
        ylab = 2.0 * label - 1.0
        return ylab * ylab * jnp.exp(jnp.minimum(-score * ylab, _EXP_MAX_EXP))


# ---------------------------------------------------------------------------
# Multiclass losses — operate on trailing axis K
# ---------------------------------------------------------------------------


class MulticlassLoss(LossFunction):
    is_multiclass = True

    def check_label(self, y) -> bool:
        # one-hot rows must sum to ~1 (reference: SoftmaxFunction.checkLabel)
        return bool(jnp.all(jnp.abs(jnp.sum(y, axis=-1) - 1.0) < 1e-3))


class Softmax(MulticlassLoss):
    """Softmax cross-entropy (reference: loss/SoftmaxFunction.java)."""

    name = "softmax"
    pure_classification = True

    def loss(self, scores, labels):
        m = jnp.max(scores, axis=-1, keepdims=True)
        shifted = scores - m
        return jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) - jnp.sum(
            shifted * labels, axis=-1
        )

    def predict(self, scores):
        return jax.nn.softmax(scores, axis=-1)

    def first_derivative(self, scores, labels):
        return jax.nn.softmax(scores, axis=-1) - labels

    def second_derivative(self, scores, labels):
        p = jax.nn.softmax(scores, axis=-1)
        return 2.0 * p * (1.0 - p)

    def grad_hess(self, pred, labels):
        # reference: SoftmaxFunction.getDerivativeFast — h = 2 p (1-p)
        return pred - labels, 2.0 * (pred * (1.0 - pred))


class HSoftmax(MulticlassLoss):
    """Hierarchical softmax over a complete binary tree of K leaves with
    K-1 internal sigmoid gates (reference: loss/HSoftmaxFunction.java).

    Scores are the K-1 internal-node logits in heap order (node 1 = root,
    node j's children are 2j, 2j+1; leaves are nodes K..2K-1). Requires K a
    power of two for a complete tree, matching the reference's heap layout.
    """

    name = "hsoftmax"
    pure_classification = True

    def _mu(self, labels):
        """Bottom-up subtree label mass: mu[j] for heap nodes 1..2K-1."""
        K = labels.shape[-1]
        # mu laid out 1-indexed in a (..., 2K) buffer; mu[K+i] = labels[i]
        mu = jnp.zeros(labels.shape[:-1] + (2 * K,), labels.dtype)
        mu = mu.at[..., K:].set(labels)
        for j in range(K - 1, 0, -1):
            mu = mu.at[..., j].set(mu[..., 2 * j] + mu[..., 2 * j + 1])
        return mu

    def loss(self, scores, labels):
        K = labels.shape[-1]
        mu = self._mu(labels)
        # internal node k (1-indexed, score scores[k-1]): children 2k (left,
        # goes with sigmoid(score)) and 2k+1; loss contribution =
        # mu_parent * softplus(s) - mu_left * s  (rearranged stable form)
        s = scores  # (..., K-1)
        mu_parent = mu[..., 1:K]
        mu_left = mu[..., 2 : 2 * K : 2]
        return jnp.sum(mu_parent * _softplus(s) - mu_left * s, axis=-1)

    def predict(self, scores):
        K = scores.shape[-1] + 1
        g = jax.nn.sigmoid(scores)  # P(left) at internal node 1..K-1
        # leaf probability: product of gate probs along root->leaf path
        probs = jnp.ones(scores.shape[:-1] + (1,), scores.dtype)
        # iterative doubling down the heap levels
        level = probs  # nodes at current level, size 2^d
        for _ in range(int(math.log2(K))):
            n = level.shape[-1]
            gates = jax.lax.dynamic_slice_in_dim(g, n - 1, n, axis=-1)
            left = level * gates
            right = level * (1.0 - gates)
            level = jnp.stack([left, right], axis=-1).reshape(
                scores.shape[:-1] + (2 * n,)
            )
        return level

    def first_derivative(self, scores, labels):
        K = labels.shape[-1]
        mu = self._mu(labels)
        g = jax.nn.sigmoid(scores)
        mu_parent = mu[..., 1:K]
        mu_left = mu[..., 2 : 2 * K : 2]
        return g * mu_parent - mu_left

    def second_derivative(self, scores, labels):
        K = labels.shape[-1]
        mu = self._mu(labels)
        g = jax.nn.sigmoid(scores)
        return g * (1.0 - g) * mu[..., 1:K]


class _MulticlassMarginLoss(MulticlassLoss):
    """Shared scaffolding for the three multiclass hinge variants
    (reference: loss/MulticlassHingeFunction.java and friends): per-class
    margin terms vs the target class, with the target-class gradient set to
    -(sum of others) when the target is not the last class — replicating the
    reference's exact (asymmetric) convention, including *not* fixing the
    target component when target == K-1."""

    def _margin_terms(self, diff):
        raise NotImplementedError  # per-class loss term from diff = s_j - s_t

    def _margin_grad(self, diff):
        raise NotImplementedError

    def _extra(self) -> float:
        raise NotImplementedError  # constant subtracted once per sample

    def predict(self, scores):
        return scores

    def loss(self, scores, labels):
        st = jnp.sum(scores * labels, axis=-1, keepdims=True)
        return jnp.sum(self._margin_terms(scores - st), axis=-1) - self._extra()

    def first_derivative(self, scores, labels):
        st = jnp.sum(scores * labels, axis=-1, keepdims=True)
        d = self._margin_grad(scores - st)
        total = jnp.sum(d, axis=-1, keepdims=True)
        target_is_last = labels[..., -1:] == 1.0
        fixed = jnp.where(labels == 1.0, -total + 1.0, d)
        return jnp.where(target_is_last, d, fixed)


class MulticlassHinge(_MulticlassMarginLoss):
    name = "multiclass_hinge"
    pure_classification = True

    def _margin_terms(self, diff):
        return jnp.maximum(0.0, diff + 1.0)

    def _margin_grad(self, diff):
        return jnp.where(diff + 1.0 > 0.0, 1.0, 0.0)

    def _extra(self) -> float:
        return 1.0


class MulticlassL2Hinge(_MulticlassMarginLoss):
    name = "multiclass_l2_hinge"
    pure_classification = True

    def _margin_terms(self, diff):
        m = jnp.maximum(0.0, diff + 1.0)
        return 0.5 * m * m

    def _margin_grad(self, diff):
        return jnp.maximum(0.0, diff + 1.0)

    def _extra(self) -> float:
        return 0.5

    def loss(self, scores, labels):
        # reference computes (sum m^2 - 1) * 0.5
        st = jnp.sum(scores * labels, axis=-1, keepdims=True)
        m = jnp.maximum(0.0, scores - st + 1.0)
        return 0.5 * (jnp.sum(m * m, axis=-1) - 1.0)


class MulticlassSmoothHinge(_MulticlassMarginLoss):
    name = "multiclass_smooth_hinge"
    pure_classification = True

    def _margin_terms(self, diff):
        return jnp.where(
            diff >= 0.0,
            diff + 0.5,
            jnp.where(diff < -1.0, 0.0, 0.5 * (1.0 + diff) * (1.0 + diff)),
        )

    def _margin_grad(self, diff):
        return jnp.where(
            diff >= 0.0, 1.0, jnp.where(diff < -1.0, 0.0, 1.0 + diff)
        )

    def _extra(self) -> float:
        return 0.5


# ---------------------------------------------------------------------------
# Factory (reference: loss/LossFunctions.java:31-79)
# ---------------------------------------------------------------------------

_PURE_CLASSIFICATION = {
    "sigmoid", "softmax", "hinge", "smooth_hinge", "l2_hinge",
    "multiclass_l2_hinge", "exponential", "multiclass_hinge",
    "multiclass_smooth_hinge", "hsoftmax",
}


def create_loss(name: str, params: Optional[dict] = None) -> LossFunction:
    """name -> LossFunction; supports `huber@delta` (the reference intends a
    delta suffix — its factory splits on '@' — and defaults to 0.5), plus the
    *_cross_entropy aliases."""
    base, _, arg = str(name).lower().partition("@")
    params = params or {}
    if base in ("sigmoid", "sigmoid_cross_entropy"):
        return Sigmoid(zmax=float(params.get("sigmoid_zmax", 0.0)))
    if base == "l2":
        return L2()
    if base == "l1":
        return L1()
    if base == "huber":
        return Huber(delta=float(arg) if arg else 0.5)
    if base == "poisson":
        return Poisson()
    if base == "mape":
        return MAPE()
    if base == "inv_mape":
        return InvMAPE()
    if base == "smape":
        return SMAPE()
    if base == "hinge":
        return Hinge()
    if base == "l2_hinge":
        return L2Hinge()
    if base == "smooth_hinge":
        return SmoothHinge()
    if base == "exponential":
        return Exponential()
    if base in ("softmax", "softmax_cross_entropy"):
        return Softmax()
    if base in ("hsoftmax", "hsoftmax_cross_entropy"):
        return HSoftmax()
    if base == "multiclass_hinge":
        return MulticlassHinge()
    if base == "multiclass_l2_hinge":
        return MulticlassL2Hinge()
    if base == "multiclass_smooth_hinge":
        return MulticlassSmoothHinge()
    raise ValueError(f"unsupported loss function: {name!r}")


def pure_classification(name: str) -> bool:
    """reference: loss/LossFunctions.java:79."""
    base = str(name).lower().partition("@")[0]
    if base.endswith("_cross_entropy"):
        base = base[: -len("_cross_entropy")]
    return base in _PURE_CLASSIFICATION
