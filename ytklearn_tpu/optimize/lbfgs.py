"""Distributed L-BFGS with OWL-QN and the reference's three line-search modes.

Rebuild of reference optimizer/HoagOptimizer.java:306-1201 as *one jitted
program per iteration*: the line search (each trial = full loss+grad) runs as
a `lax.while_loop` on device, the two-loop recursion as `lax.fori_loop`s over
a fixed-size (m, dim) history, and the OWL-QN pseudo-gradient / orthant
projection / direction constraint as elementwise selects. The host loop only
handles convergence checks, eval, and checkpoint dumps — the reference
instead paid a full network allreduce per line-search trial
(HoagOptimizer.lineSearch:1068-1201); here trials stay on-device and data
parallelism rides XLA-inserted psums (rows sharded, w replicated).

Data arrays are threaded through the jitted programs as *arguments*
(`batch`), never closures — closed-over device arrays are captured as
constants at lowering time, which bloats the HLO and makes compiles scale
with data size. Compiled programs are cached per (loss_fn, config, reg
shape), so hyper-search rounds and repeat calls don't recompile.

Semantics kept bit-for-bit where they matter:
  - loss bookkeeping is *weighted sums* (unnormalized), reg scaled by the
    total train weight (calcLossAndGrad:985-1006)
  - OWL-QN pseudo-gradient via partPos/partNeg (:1040-1062)
  - orthant projection in the line search (:1089-1103)
  - direction constraint p=0 where p*g>=0 on L1-regularized slots (:697-705)
  - ys < 1e-60 -> 0.01*yy guard (:678-681)
  - convergence: ||g|| / max(||w||,1) <= eps (:534)
  - line-search failure statuses -1/-2/-3 and revert-to-prev (:1150-1175)
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import health, inc as obs_inc, profiler, span as obs_span

_MODES = {"sufficient_decrease": 0, "wolfe": 1, "strong_wolfe": 2}


@dataclass(frozen=True)
class LBFGSConfig:
    """Mirror of param/LineSearchParams.java:43."""

    m: int = 8
    max_iter: int = 60
    eps: float = 1e-3
    mode: str = "wolfe"
    c1: float = 1e-4
    c2: float = 0.9
    step_decr: float = 0.5
    step_incr: float = 2.1
    ls_max_iter: int = 55
    min_step: float = 1e-16
    max_step: float = 1e18

    @classmethod
    def from_params(cls, lsp) -> "LBFGSConfig":
        return cls(
            m=lsp.lbfgs_m,
            max_iter=lsp.lbfgs_max_iter,
            eps=lsp.lbfgs_eps,
            mode=lsp.mode,
            c1=lsp.c1,
            c2=lsp.c2,
            step_decr=lsp.step_decr,
            step_incr=lsp.step_incr,
            ls_max_iter=lsp.max_iter,
            min_step=lsp.min_step,
            max_step=lsp.max_step,
        )


class LBFGSState(NamedTuple):
    w: jnp.ndarray
    g: jnp.ndarray  # (pseudo-)gradient at w
    loss: jnp.ndarray  # regularized weighted-sum loss
    pure_loss: jnp.ndarray
    step: jnp.ndarray  # initial step for next line search
    S: jnp.ndarray  # (m, dim) s history
    Y: jnp.ndarray  # (m, dim) y history
    ys: jnp.ndarray  # (m,)
    cursor: jnp.ndarray  # next write slot
    hist_len: jnp.ndarray
    ls_status: jnp.ndarray  # >0 ok (trial count), <0 failed


@dataclass
class LBFGSResult:
    w: jnp.ndarray
    loss: float
    pure_loss: float
    n_iter: int
    status: str
    converged: bool
    state: Optional[LBFGSState] = None  # final state (curvature history for HOAG)


class Reg(NamedTuple):
    """Regularization operands threaded through the jitted programs."""

    l1_vec: jnp.ndarray  # (dim,) — zeros when no L1
    l2_vec: jnp.ndarray  # (dim,)
    g_weight: jnp.ndarray  # scalar total train weight


def _two_loop_core(g, S, Y, ys_arr, cursor, hist_len, m: int):
    """-H⁻¹·g via the two-loop recursion over the (m, dim) ring buffer
    (reference: HoagOptimizer.Hv:904-929; history replicated here — on a
    TPU mesh the dots are local FLOPs, so the reference's history-slice
    sharding + allgather dance is unnecessary at these dims; for very
    large dim shard w/S/Y over the mesh and XLA re-inserts the psums)."""
    dtype = g.dtype
    p = -g

    def fwd(i, carry):
        p, alphas = carry
        idx = (cursor - 1 - i) % m
        valid = i < hist_len
        alpha = jnp.where(valid, jnp.vdot(S[idx], p) / ys_arr[idx], 0.0)
        p = p - alpha * Y[idx]
        return p, alphas.at[idx].set(alpha)

    p, alphas = lax.fori_loop(0, m, fwd, (p, jnp.zeros((m,), dtype)))

    newest = (cursor - 1) % m
    yy_newest = jnp.vdot(Y[newest], Y[newest])
    p = p * ys_arr[newest] / yy_newest

    def bwd(j, p):
        i = m - 1 - j  # oldest valid first
        idx = (cursor - 1 - i) % m
        valid = i < hist_len
        beta = jnp.where(valid, jnp.vdot(Y[idx], p) / ys_arr[idx], 0.0)
        return p + jnp.where(valid, alphas[idx] - beta, 0.0) * S[idx]

    return lax.fori_loop(0, m, bwd, p)


@partial(jax.jit, static_argnames=("m",))
def inv_hessian_vp(state: LBFGSState, v, m: int):
    """H⁻¹·v from a converged L-BFGS state's curvature history — the Hv
    call HOAG uses to precondition the test gradient (reference:
    HoagOptimizer.hyperHoagOptimization:822-826 -> Hv:904-929). Falls back
    to identity when no history exists."""
    return jnp.where(
        state.hist_len > 0,
        -_two_loop_core(v, state.S, state.Y, state.ys, state.cursor, state.hist_len, m),
        v,
    )


def _loss_grad(vg_fn, has_l1: bool, w, reg: Reg, batch):
    """calcLossAndGrad equivalent (reference: HoagOptimizer.java:978-1066).
    vg_fn(w, *batch) -> (pure_loss, grad) — plain value_and_grad or the
    row-chunked variant (optimize/blocked.py).
    -> (pure_loss, all_loss, pseudo_grad)."""
    pure, G = vg_fn(w, *batch)
    gw = reg.g_weight
    all_loss = pure + 0.5 * gw * jnp.sum(reg.l2_vec * w * w)
    G = G + gw * reg.l2_vec * w
    if has_l1:
        l1v = reg.l1_vec
        all_loss = all_loss + gw * jnp.sum(l1v * jnp.abs(w))
        sign_or_pos = jnp.where(w != 0.0, jnp.sign(w), 1.0)
        gpos = G + gw * l1v * sign_or_pos
        gneg = jnp.where(w != 0.0, gpos, gpos - 2.0 * gw * l1v)
        pg = jnp.where(gneg > 0.0, gneg, jnp.where(gpos < 0.0, gpos, 0.0))
        G = jnp.where(l1v > 0.0, pg, G)
    return pure, all_loss, G


# program cache: (pure_loss_fn, trace-relevant config fields, has_l1,
# chunking) -> (first_eval, iteration). max_iter/eps only drive the host
# loop and must not key the cache (they'd force pointless recompiles).
# Bounded LRU so a long-lived process sweeping many models doesn't pin
# executables forever.
from collections import OrderedDict

_PROGRAMS: "OrderedDict" = OrderedDict()
_PROGRAMS_MAX = 16


def _trace_key(config: LBFGSConfig):
    return (
        config.m,
        config.mode,
        config.c1,
        config.c2,
        config.step_decr,
        config.step_incr,
        config.ls_max_iter,
        config.min_step,
        config.max_step,
    )


def _build_programs(
    pure_loss_fn,
    config: LBFGSConfig,
    has_l1: bool,
    row_chunk=None,
    row_mask=None,
    mesh=None,
    data_axis="data",
    n_batch=0,
):
    key = (pure_loss_fn, _trace_key(config), has_l1, row_chunk, row_mask, mesh)
    hit = _PROGRAMS.get(key)
    if hit is not None:
        _PROGRAMS.move_to_end(key)
        return hit

    m = config.m
    mode = _MODES[config.mode]
    c1, c2 = config.c1, config.c2
    from .blocked import make_value_and_grad

    vg_fn = make_value_and_grad(
        pure_loss_fn, row_chunk, row_mask, mesh, data_axis, n_batch
    )
    lg = partial(_loss_grad, vg_fn, has_l1)

    def orthant_project(l1v, w_try, wprev, gprev):
        """reference: lineSearch orthant block :1089-1103."""
        if not has_l1:
            return w_try
        zero_cross = jnp.where(
            wprev != 0.0, w_try * wprev <= 0.0, w_try * gprev >= 0.0
        )
        return jnp.where((l1v > 0.0) & zero_cross, 0.0, w_try)

    def line_search(wprev, gprev, p, step0, loss0, pure0, reg, batch):
        """reference: HoagOptimizer.lineSearch:1068-1201. Returns
        (w, g, loss, pure, status) — status<0: failed (reverted)."""
        dginit = jnp.vdot(gprev, p)

        def body(carry):
            step, ls_iter, _, _, _, _, _ = carry
            w_try = orthant_project(reg.l1_vec, wprev + step * p, wprev, gprev)
            pure, loss, g = lg(w_try, reg, batch)
            ls_iter = ls_iter + 1
            dgtest = jnp.vdot(w_try - wprev, gprev)
            dg = jnp.vdot(p, g)

            suff_ok = loss <= loss0 + c1 * dgtest
            wolfe_ok = dg >= c2 * dginit
            strong_ok = dg <= -c2 * dginit
            if mode == 0:
                ok = suff_ok
                factor = config.step_decr
            elif mode == 1:
                ok = suff_ok & wolfe_ok
                factor = jnp.where(~suff_ok, config.step_decr, config.step_incr)
            else:
                ok = suff_ok & wolfe_ok & strong_ok
                factor = jnp.where(
                    ~suff_ok,
                    config.step_decr,
                    jnp.where(~wolfe_ok, config.step_incr, config.step_decr),
                )

            status = jnp.where(
                ok,
                ls_iter,
                jnp.where(
                    step < config.min_step,
                    -1,
                    jnp.where(
                        step > config.max_step,
                        -2,
                        jnp.where(ls_iter >= config.ls_max_iter, -3, 0),
                    ),
                ),
            ).astype(jnp.int32)
            return (step * factor, ls_iter, status, w_try, g, loss, pure)

        init = (
            step0,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0, jnp.int32),
            wprev,
            gprev,
            loss0,
            pure0,
        )
        _, _, status, w, g, loss, pure = lax.while_loop(
            lambda c: c[2] == 0, body, init
        )
        failed = status < 0
        # on failure move back to the previous point (reference :585-589)
        w = jnp.where(failed, wprev, w)
        g = jnp.where(failed, gprev, g)
        loss = jnp.where(failed, loss0, loss)
        pure = jnp.where(failed, pure0, pure)
        return w, g, loss, pure, status

    def two_loop(g, S, Y, ys_arr, cursor, hist_len):
        return _two_loop_core(g, S, Y, ys_arr, cursor, hist_len, m)

    @jax.jit
    def first_eval(w, reg, batch):
        pure, loss, g = lg(w, reg, batch)
        return pure, loss, g, jnp.linalg.norm(w), jnp.linalg.norm(g)

    @jax.jit
    def iteration(state: LBFGSState, reg: Reg, batch):
        """One full L-BFGS iteration: direction from history -> line search
        -> history update (reference main loop :566-715)."""
        wprev, gprev = state.w, state.g
        p = jnp.where(
            state.hist_len > 0,
            two_loop(gprev, state.S, state.Y, state.ys, state.cursor, state.hist_len),
            -gprev,
        )
        if has_l1:
            # constrain search direction (reference :697-705)
            p = jnp.where((reg.l1_vec > 0.0) & (p * gprev >= 0.0), 0.0, p)

        w, g, loss, pure, status = line_search(
            wprev, gprev, p, state.step, state.loss, state.pure_loss, reg, batch
        )

        s = w - wprev
        y = g - gprev
        ys = jnp.vdot(y, s)
        yy = jnp.vdot(y, y)
        ys = jnp.where(ys < 1e-60, 0.01 * yy, ys)  # curvature guard (:678-681)

        ok = status > 0
        cursor = state.cursor
        S = jnp.where(ok, state.S.at[cursor].set(s), state.S)
        Y = jnp.where(ok, state.Y.at[cursor].set(y), state.Y)
        ys_arr = jnp.where(ok, state.ys.at[cursor].set(ys), state.ys)
        new_cursor = jnp.where(ok, (cursor + 1) % m, cursor)
        new_len = jnp.where(ok, jnp.minimum(state.hist_len + 1, m), state.hist_len)

        new_state = LBFGSState(
            w=w,
            g=g,
            loss=loss,
            pure_loss=pure,
            step=jnp.ones((), w.dtype),  # step=1 after first iteration (:707)
            S=S,
            Y=Y,
            ys=ys_arr,
            cursor=new_cursor.astype(jnp.int32),
            hist_len=new_len.astype(jnp.int32),
            ls_status=status,
        )
        return new_state, jnp.linalg.norm(w), jnp.linalg.norm(g)

    _PROGRAMS[key] = (first_eval, iteration)
    while len(_PROGRAMS) > _PROGRAMS_MAX:
        _PROGRAMS.popitem(last=False)
    return first_eval, iteration


def minimize_lbfgs(
    pure_loss_fn: Callable,
    w0: jnp.ndarray,
    config: LBFGSConfig,
    batch: Tuple = (),
    l1_vec: Optional[jnp.ndarray] = None,
    l2_vec: Optional[jnp.ndarray] = None,
    g_weight: float = 1.0,
    callback: Optional[Callable[[int, LBFGSState], bool]] = None,
    row_chunk: Optional[int] = None,
    row_mask: Optional[Tuple[bool, ...]] = None,
    mesh=None,
    data_axis: str = "data",
) -> LBFGSResult:
    """Run distributed L-BFGS/OWL-QN to convergence.

    pure_loss_fn(w, *batch) must return the *weighted-sum* data loss
    (jit-safe; batch arrays may be sharded over a mesh — XLA inserts the
    psums the reference issued by hand at HoagOptimizer.java:1014,1038).
    Pass the SAME function object across calls to reuse compiled programs.

    row_chunk: evaluate loss+grad as a scan over row chunks of this size so
    peak memory is O(chunk) — the reference's blocked-CoreData contract
    (dataflow/CoreData.java:51-52; see optimize/blocked.py). row_mask marks
    which batch elements are row-aligned (default: all). With `mesh`, the
    chunked scan runs per-shard under shard_map over `data_axis` + psum.

    callback(iter, state) runs on host once per iteration (eval/dump hook —
    the reference's per-iteration eval + dump_freq block :605-660); returning
    True stops early.
    """
    dim = w0.shape[0]
    dtype = jnp.asarray(w0).dtype
    has_l1 = l1_vec is not None and bool(jnp.any(jnp.asarray(l1_vec) > 0))
    reg = Reg(
        l1_vec=(
            jnp.zeros((dim,), dtype) if l1_vec is None else jnp.asarray(l1_vec, dtype)
        ),
        l2_vec=(
            jnp.zeros((dim,), dtype) if l2_vec is None else jnp.asarray(l2_vec, dtype)
        ),
        g_weight=jnp.asarray(g_weight, dtype),
    )
    first_eval, iteration = _build_programs(
        pure_loss_fn,
        config,
        has_l1,
        row_chunk=row_chunk,
        row_mask=row_mask,
        mesh=mesh,
        data_axis=data_axis,
        n_batch=len(batch),
    )

    obs_inc("lbfgs.runs")
    from ..obs import recorder

    recorder.auto_install()  # flight ring for postmortems (no-op when obs off)
    # phase + ledger label: first_eval absorbs the program compiles, so
    # the ytkprof compile ledger names them (and the wall decomposition
    # separates compile-dominated warmup from steady iterations)
    with profiler.phase("lbfgs.first_eval", dim=dim), profiler.LEDGER.program(
        "lbfgs.first_eval",
        sig_fn=lambda: profiler.abstract_signature(w0, reg, batch),
    ):
        pure, loss, g, wnorm, gnorm = first_eval(jnp.asarray(w0, dtype), reg, batch)
    wnorm = max(float(wnorm), 1.0)
    state = LBFGSState(
        w=jnp.asarray(w0, dtype),
        g=g,
        loss=loss,
        pure_loss=pure,
        step=jnp.asarray(1.0 / max(float(gnorm), 1e-300), dtype),
        S=jnp.zeros((config.m, dim), dtype),
        Y=jnp.zeros((config.m, dim), dtype),
        ys=jnp.ones((config.m,), dtype),
        cursor=jnp.asarray(0, jnp.int32),
        hist_len=jnp.asarray(0, jnp.int32),
        ls_status=jnp.asarray(1, jnp.int32),
    )
    if callback is not None and callback(0, state):
        return _result(state, 0, "callback_stop")
    if float(gnorm) / wnorm <= config.eps:
        return _result(state, 0, "converged_at_init", converged=True)

    it = 0
    status = "max_iter"
    converged = False
    # health sentinels piggyback on the per-iteration ls_status sync: the
    # loss is computed by then, so the fetch is a 4-byte RTT, not a stall.
    # YTK_HEALTH=0 drops both the checks and the fetch (one attribute load).
    health_on = health.enabled()
    guard = health.ProgressGuard("lbfgs", window=10) if health_on else None
    # iterations run inside one ytkprof phase (opt-in capture: the kernel
    # table for the solve comes from here); state/reg/batch shapes are
    # static after warmup, so any ledger entry the loop produces IS an
    # unexpected retrace with its signature attached
    with profiler.phase("lbfgs.iterations", capture=True):
        for it in range(1, config.max_iter + 1):
            # the span's ls_status fetch doubles as the device sync the loop
            # needs anyway — the duration is device-settled for free
            with obs_span("lbfgs.iteration", it=it), profiler.LEDGER.program(
                "lbfgs.iteration",
                sig_fn=lambda: profiler.abstract_signature(state, reg, batch),
            ):
                state, wnorm, gnorm = iteration(state, reg, batch)
                ls = int(state.ls_status)
            obs_inc("lbfgs.iterations")
            if health_on:
                # outside the span so a strict escalation's flight dump
                # carries the failing iteration's completed span in its ring
                loss_val = float(state.loss)
                if not health.check_loss("lbfgs.loss", loss_val, it=it):
                    status = "nan_loss"
                    break
                guard.update(loss_val, it=it)
            if ls > 1:
                # trials beyond the first = line-search retries (step rescales)
                obs_inc("lbfgs.ls_retries", ls - 1)
            if ls < 0:
                obs_inc("lbfgs.ls_failures")
                status = f"line_search_failed({ls})"
                break
            if callback is not None and callback(it, state):
                status = "callback_stop"
                break
            if float(gnorm) / max(float(wnorm), 1.0) <= config.eps:
                status = "converged"
                converged = True
                break
    return _result(state, it, status, converged)


def _result(state, n_iter, status, converged=False) -> LBFGSResult:
    return LBFGSResult(
        w=state.w,
        loss=float(state.loss),
        pure_loss=float(state.pure_loss),
        n_iter=n_iter,
        status=status,
        converged=converged,
        state=state,
    )
