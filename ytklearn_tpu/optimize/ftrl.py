"""FTRL-Proximal — the streaming-update optimizer for the convex families.

McMahan et al., "Ad Click Prediction: a View from the Trenches" (KDD 2013;
PAPERS.md) — per-coordinate adaptive learning rates with L1-induced
sparsity, the standard online-update rule for linear/logistic models under
a stream of fresh examples. No reference counterpart: the reference
retrains offline with L-BFGS and restarts; here FTRL closes the
train->serve freshness loop (docs/continual.md) as the cheap alternative
to a full warm-start refit when only a delta of new rows arrived.

Per coordinate i, after observing gradient g_i:

    n_i  += g_i^2
    sigma = (sqrt(n_i) - sqrt(n_i - g_i^2)) / alpha
    z_i  += g_i - sigma * w_i
    w_i   = 0                                      if |z_i| <= l1_i
          = -(z_i - sign(z_i) l1_i) / ((beta + sqrt(n_i))/alpha + l2_i)

The whole minibatch step (gradient + accumulator update + closed-form
weight solve) is ONE jitted program; state stays on device across the
stream, so a pass over k minibatches costs k dispatches and zero host
round-trips. The update is deterministic for a fixed data order —
`tests/test_continual.py` pins bit-stable convergence.

Warm start: `ftrl_init(w0, ...)` inverts the closed form so the first
weight solve reproduces the checkpoint exactly (z0 chosen with n0 = 0),
making "resume from the incumbent model" the natural entry state.

L1/L2 arrive as per-coordinate VECTORS (models' `reg_vectors` surface) so
the bias slot rides unregularized exactly like the L-BFGS path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..obs import inc as obs_inc, span as obs_span


@dataclass(frozen=True)
class FTRLConfig:
    """Hyperparameters (the paper's alpha/beta/lambda1/lambda2); l1/l2
    here are scalars broadcast through the model's reg_vectors."""

    alpha: float = 0.1
    beta: float = 1.0
    l1: float = 0.0
    l2: float = 0.0


class FTRLState(NamedTuple):
    w: jnp.ndarray  # current weights (the closed-form solve of z, n)
    z: jnp.ndarray  # accumulated (gradient - sigma*w) sums
    n: jnp.ndarray  # accumulated squared gradients


def ftrl_init(
    w0: jnp.ndarray,
    cfg: FTRLConfig,
    l1_vec: Optional[jnp.ndarray] = None,
    l2_vec: Optional[jnp.ndarray] = None,
) -> FTRLState:
    """State whose closed-form solve reproduces `w0` bit-for-bit at n=0:
    z0 = -w0 * (beta/alpha + l2) - sign(w0) * l1 (zero weights get z0=0,
    which the solve keeps at exactly 0 whenever l1 >= 0)."""
    w0 = jnp.asarray(w0, jnp.float32)
    l1v = jnp.zeros_like(w0) if l1_vec is None else jnp.asarray(l1_vec)
    l2v = jnp.zeros_like(w0) if l2_vec is None else jnp.asarray(l2_vec)
    denom = cfg.beta / cfg.alpha + l2v
    z0 = jnp.where(w0 != 0.0, -w0 * denom - jnp.sign(w0) * l1v, 0.0)
    return FTRLState(w=w0, z=z0, n=jnp.zeros_like(w0))


def make_ftrl_step(
    grad_fn: Callable, cfg: FTRLConfig
) -> Callable:
    """Build the jitted minibatch update.

    grad_fn(w, *batch) -> gradient of the AVERAGE (weight-normalized) loss
    over the minibatch. Returned step(state, l1_vec, l2_vec, *batch) ->
    FTRLState; reg vectors ride as arguments so one compiled program
    serves every (l1, l2) setting.
    """
    alpha, beta = cfg.alpha, cfg.beta

    def step(state: FTRLState, l1_vec, l2_vec, *batch) -> FTRLState:
        g = grad_fn(state.w, *batch)
        n_new = state.n + g * g
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(state.n)) / alpha
        z_new = state.z + g - sigma * state.w
        denom = (beta + jnp.sqrt(n_new)) / alpha + l2_vec
        w_new = jnp.where(
            jnp.abs(z_new) <= l1_vec,
            0.0,
            -(z_new - jnp.sign(z_new) * l1_vec) / denom,
        )
        return FTRLState(w=w_new, z=z_new, n=n_new)

    return jax.jit(step)


def ftrl_pass(
    model,
    w0,
    batch: tuple,
    cfg: FTRLConfig,
    batch_rows: int = 8192,
    n_real: Optional[int] = None,
) -> FTRLState:
    """One deterministic pass of FTRL minibatch updates over `batch` (the
    model's make_batch arrays, host or device, rows first).

    Rows are consumed in order, `batch_rows` at a time — for a freshness
    delta the stream IS the new data, so one pass is the intended use
    (call repeatedly for more epochs). `n_real` clips trailing padding
    rows; partially-weighted rows are handled by the weight column
    (grad_fn normalizes by the minibatch weight sum).
    """
    l1_vec, l2_vec = model.reg_vectors(cfg.l1, cfg.l2)

    def grad_fn(w, *b):
        *rest, weight = b
        total = jnp.maximum(jnp.sum(weight), 1e-12)
        return jax.grad(model.pure_loss)(w, *b) / total

    step = make_ftrl_step(grad_fn, cfg)
    state = ftrl_init(w0, cfg, l1_vec, l2_vec)
    n = int(batch[0].shape[0]) if n_real is None else int(n_real)
    n_steps = 0
    with obs_span("continual.ftrl_pass", rows=n, batch_rows=batch_rows):
        for lo in range(0, n, batch_rows):
            hi = min(lo + batch_rows, n)
            mb = tuple(jnp.asarray(a[lo:hi]) for a in batch)
            state = step(state, l1_vec, l2_vec, *mb)
            n_steps += 1
    obs_inc("continual.ftrl_steps", n_steps)
    obs_inc("continual.ftrl_rows", n)
    return state
