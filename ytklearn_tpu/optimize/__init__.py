from .lbfgs import LBFGSConfig, LBFGSResult, minimize_lbfgs
