from .ftrl import FTRLConfig, FTRLState, ftrl_init, ftrl_pass, make_ftrl_step
from .lbfgs import LBFGSConfig, LBFGSResult, inv_hessian_vp, minimize_lbfgs
