from .lbfgs import LBFGSConfig, LBFGSResult, inv_hessian_vp, minimize_lbfgs
