"""Blocked (row-chunked) loss / gradient / score evaluation.

The reference never materializes per-sample intermediates for a whole
partition at once: CoreData is deliberately *blocked* storage
(MAX_2D_LEN=50000 / MAX_1D_LEN=2e6 caps, reference dataflow/CoreData.java:51-52)
and every convex optimizer walks blocks in its loss loop (e.g. reference
optimizer/FMHoagOptimizer.java:88). The TPU equivalent implemented here:
evaluate loss+grad as a `lax.scan` over fixed-size row chunks — loss and
gradient are row sums, so the scan accumulates both with peak memory
O(chunk x per-row cost) instead of O(n x per-row cost). This is what lets
FM/FFM train full-batch L-BFGS on data whose per-row score intermediates
(latent gathers) would otherwise exceed HBM.

On a device mesh the scan runs per-shard inside `shard_map` with a final
psum — the same collective XLA inserts for the unchunked row-sharded
program, so chunked and unchunked mesh evaluation are interchangeable.

Batch elements that are NOT row-aligned (e.g. the GBST per-feature gate
mask) are threaded through unchunked via `row_mask`.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _to_varying(x, axes):
    """Mark x varying over the given mesh axes (shard_map vma typing).
    jax 0.9 deprecates lax.pvary in favor of lax.pcast(..., to="varying");
    pre-vma jax (0.4.x) has neither and needs no marking — identity."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, axes, to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, axes)
    return x


def _split(batch, row_mask):
    rows = tuple(a for a, r in zip(batch, row_mask) if r)
    consts = tuple(a for a, r in zip(batch, row_mask) if not r)
    return rows, consts


def _rebuild(row_mask, rows, consts):
    ri, ci = iter(rows), iter(consts)
    return tuple(next(ri) if r else next(ci) for r in row_mask)


def _stack_chunks(rows, chunk: int):
    """Pad row arrays to a multiple of `chunk` and reshape to
    (n_chunks, chunk, ...). Padding rows are all-zero — ingest already pads
    with zero-weight rows, and every model loss masks weight==0 rows, so
    padded rows contribute exactly 0 to loss and gradient.

    A chunk is NEVER padded beyond the data: chunking exists to cap memory
    on large n, not to tax small n (reference contract: blocks cap memory,
    optimizer/FMHoagOptimizer.java:88). Under shard_map n is the SHARD's
    row count, so a small per-shard slice of a big batch — the r5
    eval-amplification bug, ~20x compute per line-search trial on the
    8-device test mesh — collapses to one exact-size chunk here."""
    n = rows[0].shape[0]
    chunk = min(chunk, n)
    nc = -(-n // chunk)
    pad = nc * chunk - n

    def prep(a):
        if pad:
            a = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        return a.reshape((nc, chunk) + a.shape[1:])

    return tuple(prep(a) for a in rows), n


def chunked_value_and_grad(
    fn: Callable,
    chunk: int,
    row_mask: Optional[Sequence[bool]] = None,
    vary_axes: Tuple[str, ...] = (),
) -> Callable:
    """(w, *batch) -> (sum loss, sum grad), scanning row chunks.

    `fn(w, *batch)` must return a weighted-sum (not averaged) scalar loss —
    the same contract `minimize_lbfgs` imposes — so chunk sums compose.
    `vary_axes`: mesh axes this runs under inside shard_map. `w` is made
    explicitly varying over them so the computed gradient stays the
    *per-shard local* grad (AD would otherwise transpose the implicit
    pvary of replicated w into a psum, and the caller's own psum would
    then double-count) — the caller psums loss and grad exactly once.
    """

    def run(w, *batch):
        mask = tuple(row_mask) if row_mask is not None else (True,) * len(batch)
        rows, consts = _split(batch, mask)
        xs, _ = _stack_chunks(rows, chunk)
        if vary_axes:
            w = _to_varying(w, vary_axes)

        def body(carry, ch):
            l, g = jax.value_and_grad(fn)(w, *_rebuild(mask, ch, consts))
            return (carry[0] + l, carry[1] + g), None

        init = (jnp.zeros((), w.dtype), jnp.zeros_like(w))
        if vary_axes:
            init = (_to_varying(init[0], vary_axes), init[1])
        (loss, grad), _ = lax.scan(body, init, xs)
        return loss, grad

    return run


def chunked_sum(
    fn: Callable,
    chunk: int,
    row_mask: Optional[Sequence[bool]] = None,
    vary_axes: Tuple[str, ...] = (),
) -> Callable:
    """(w, *batch) -> sum loss only (no gradient) — the cheap evaluation
    path (per-iteration test loss, round selection)."""

    def run(w, *batch):
        mask = tuple(row_mask) if row_mask is not None else (True,) * len(batch)
        rows, consts = _split(batch, mask)
        xs, _ = _stack_chunks(rows, chunk)

        def body(carry, ch):
            return carry + fn(w, *_rebuild(mask, ch, consts)), None

        init = jnp.zeros(())
        if vary_axes:
            init = _to_varying(init, vary_axes)
        loss, _ = lax.scan(body, init, xs)
        return loss

    return run


def blocked_rows(
    fn: Callable, chunk: int, row_mask: Optional[Sequence[bool]] = None
) -> Callable:
    """Chunked per-row outputs: fn(w, *batch) -> (n, ...) evaluated as
    `lax.map` over row chunks, concatenated and sliced back to n rows.
    Used for scores/predicts on batches whose per-row intermediates don't
    fit at once (reference analog: OnlinePredictor scoring block-by-block
    over CoreData blocks)."""

    def run(w, *batch):
        mask = tuple(row_mask) if row_mask is not None else (True,) * len(batch)
        rows, consts = _split(batch, mask)
        xs, n = _stack_chunks(rows, chunk)
        out = lax.map(lambda ch: fn(w, *_rebuild(mask, ch, consts)), xs)
        return out.reshape((-1,) + out.shape[2:])[:n]

    return run


def mesh_chunked_value_and_grad(
    fn: Callable,
    chunk: int,
    row_mask: Optional[Sequence[bool]],
    mesh,
    axis: str,
    n_batch: int,
) -> Callable:
    """`chunked_value_and_grad` run per-shard under shard_map with a final
    psum over the data axis — the reference's grad allreduce
    (optimizer/HoagOptimizer.java:1038) with the block loop inside each
    rank, matching its per-thread CoreData block walk."""
    from ..parallel.mesh import shard_map_compat as shard_map

    mask = tuple(row_mask) if row_mask is not None else (True,) * n_batch
    cvg = chunked_value_and_grad(fn, chunk, mask, vary_axes=(axis,))
    in_specs = (P(), tuple(P(axis) if r else P() for r in mask))
    out_specs = (P(), P())

    from ..parallel.collectives import psum

    def local(w, batch):
        loss, grad = cvg(w, *batch)
        return psum(loss, axis), psum(grad, axis)

    sm = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return lambda w, *batch: sm(w, batch)


def mesh_chunked_sum(
    fn: Callable,
    chunk: int,
    row_mask: Optional[Sequence[bool]],
    mesh,
    axis: str,
    n_batch: int,
) -> Callable:
    """`chunked_sum` per shard under shard_map + psum. Reshaping a
    row-sharded global array for the plain scan would make XLA all-gather
    the batch onto every device — this keeps each shard's chunks local."""
    from ..parallel.mesh import shard_map_compat as shard_map

    mask = tuple(row_mask) if row_mask is not None else (True,) * n_batch
    cs = chunked_sum(fn, chunk, mask, vary_axes=(axis,))
    in_specs = (P(), tuple(P(axis) if r else P() for r in mask))

    from ..parallel.collectives import psum

    def local(w, batch):
        return psum(cs(w, *batch), axis)

    sm = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=P())
    return lambda w, *batch: sm(w, batch)


def mesh_blocked_rows(
    fn: Callable,
    chunk: int,
    row_mask: Optional[Sequence[bool]],
    mesh,
    axis: str,
    n_batch: int,
) -> Callable:
    """`blocked_rows` per shard under shard_map — per-row outputs stay
    row-sharded (out_specs P(axis)), no collective needed."""
    from ..parallel.mesh import shard_map_compat as shard_map

    mask = tuple(row_mask) if row_mask is not None else (True,) * n_batch
    br = blocked_rows(fn, chunk, mask)
    in_specs = (P(), tuple(P(axis) if r else P() for r in mask))

    def local(w, batch):
        return br(w, *batch)

    sm = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=P(axis))
    return lambda w, *batch: sm(w, batch)


# -- dispatch factories: one place for the (unchunked | chunked | mesh-
# chunked) selection so every call site (lbfgs programs, trainer eval
# paths, HOAG test gradient) stays in sync ---------------------------------


def make_value_and_grad(
    fn, chunk=None, row_mask=None, mesh=None, axis="data", n_batch=0
):
    if chunk is None:
        return jax.value_and_grad(fn)
    if mesh is None:
        return chunked_value_and_grad(fn, chunk, row_mask)
    return mesh_chunked_value_and_grad(fn, chunk, row_mask, mesh, axis, n_batch)


def make_sum(fn, chunk=None, row_mask=None, mesh=None, axis="data", n_batch=0):
    if chunk is None:
        return fn
    if mesh is None:
        return chunked_sum(fn, chunk, row_mask)
    return mesh_chunked_sum(fn, chunk, row_mask, mesh, axis, n_batch)


def make_rows(fn, chunk=None, row_mask=None, mesh=None, axis="data", n_batch=0):
    if chunk is None:
        return fn
    if mesh is None:
        return blocked_rows(fn, chunk, row_mask)
    return mesh_blocked_rows(fn, chunk, row_mask, mesh, axis, n_batch)


def pow2_floor(x: int) -> int:
    return 1 << max(int(x).bit_length() - 1, 0)


def suggest_chunk(
    n_rows: int,
    bytes_per_row: int,
    budget_bytes: Optional[int] = None,
    min_chunk: int = 4096,
    n_shards: int = 1,
) -> Optional[int]:
    """Pick a power-of-two row chunk so the score intermediates stay under
    `budget_bytes` (default 1 GiB, env YTK_CHUNK_BUDGET_MB). Returns None
    when the whole batch already fits (no chunking needed).

    All decisions are made on the PER-SHARD row count (`n_rows` is the
    global batch; on a mesh each shard scans its own rows): a shard at or
    under `min_chunk` rows never chunks — chunking exists to cap memory on
    large n, never to tax small n. The r5 regression this guards against:
    FFM's padded per-row estimate forced chunking at ~1.6k global rows,
    and each 200-row test-mesh shard was padded to a 4096-row chunk —
    ~20x compute amplification per line-search trial (test_ffm_agaricus
    3088 s). Now: local_rows <= min_chunk -> None."""
    from ..config import knobs

    local_rows = -(-n_rows // max(n_shards, 1))
    if budget_bytes is None:
        budget_bytes = knobs.get_int("YTK_CHUNK_BUDGET_MB") << 20
    env = knobs.get_int("YTK_ROW_CHUNK")
    if env is not None:
        chunk = env
        return chunk if 0 < chunk < local_rows else None
    if local_rows <= min_chunk:
        return None
    if local_rows * bytes_per_row <= budget_bytes:
        return None
    chunk = max(min_chunk, pow2_floor(budget_bytes // max(bytes_per_row, 1)))
    return chunk if chunk < local_rows else None
