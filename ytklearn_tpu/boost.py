"""GBST boosting driver — the GBMLROperation equivalent.

Rebuild of reference operation/GBMLROperation.java:39-124: per tree, run a
full L-BFGS fit of the soft-tree mixture against the residual objective
(loss evaluated at z + tree output), then fold the finished tree into z with
the learning rate (GBMLRDataFlow.accumulate:540), re-randomize the
instance/feature Bernoulli masks, re-init weights, and continue. Supports
gradient_boosting and random_forest types, continue_train via the
tree-info + tree-%05d model files.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config.params import CommonParams
from .eval import EvalSet
from .io.fs import FileSystem, LocalFileSystem
from .io.reader import DataIngest, IngestResult
from .losses import create_loss
from .models.gbst import GBSTModel
from .optimize import LBFGSConfig, minimize_lbfgs
from .resilience import trainer_guard

log = logging.getLogger("ytklearn_tpu.boost")


@dataclass
class BoostResult:
    n_trees: int
    train_loss: float  # avg loss of the accumulated ensemble
    test_loss: Optional[float]
    train_metrics: Dict[str, float] = field(default_factory=dict)
    test_metrics: Dict[str, float] = field(default_factory=dict)
    per_tree_loss: List[float] = field(default_factory=list)


class GBSTTrainer:
    """Boosted soft-tree trainer for gbmlr/gbsdt/gbhmlr/gbhsdt."""

    def __init__(
        self,
        params: CommonParams,
        variant: str,
        mesh=None,
        fs: Optional[FileSystem] = None,
    ):
        self.params = params
        self.variant = variant
        self.mesh = mesh
        self.fs = fs or LocalFileSystem()

    def _put(self, arr):
        """Row-shard dim 0; multi-process: `arr` is this process's shard."""
        if self.mesh is None:
            return jax.device_put(arr)
        from .parallel.mesh import put_row_sharded

        return put_row_sharded(arr, self.mesh)

    def _put_rep(self, arr):
        return jax.device_put(arr)

    _guard = None  # PreemptionGuard while train() runs (resilience/preempt.py)

    def train(self, ingest: Optional[IngestResult] = None) -> BoostResult:
        # preemption-safe: SIGTERM/SIGINT defer to the next tree boundary;
        # every finished tree is already dumped (tree-%05d + tree-info), so
        # the boundary just exits via Preempted and `--resume auto`
        # continues at the last finished tree (docs/fault_tolerance.md)
        with trainer_guard(self):
            return self._train_impl(ingest)

    def _train_impl(self, ingest: Optional[IngestResult] = None) -> BoostResult:
        p = self.params
        t0 = time.time()
        if ingest is None:
            ingest = DataIngest(p, fs=self.fs).load()
        ds_train = ingest.train
        ds_test = ingest.test
        if self.mesh is not None:
            from .parallel.mesh import equal_row_target

            ds_train = ds_train.pad_rows_to(equal_row_target(ds_train.n, self.mesh))
            ds_test = (
                ds_test.pad_rows_to(equal_row_target(ds_test.n, self.mesh))
                if ds_test else None
            )

        model = GBSTModel(p, ingest.train.dim, self.variant)
        loss_fn = model.loss
        base_score = float(loss_fn.pred2score(p.uniform_base_prediction))
        lr = p.learning_rate
        tree_num = p.tree_num
        g_weight = float(np.sum(ds_train.weight))
        g_weight_test = float(np.sum(ds_test.weight)) if ds_test else 0.0
        if jax.process_count() > 1:
            from .parallel.collectives import host_allgather_objects

            g_weight = float(sum(host_allgather_objects(g_weight)))
            g_weight_test = float(sum(host_allgather_objects(g_weight_test)))

        idx = self._put(ds_train.idx)
        val = self._put(ds_train.val)
        y = self._put(ds_train.y)
        weight = self._put(ds_train.weight)
        # padding rows keep weight 0; z starts at the base score
        z = self._put(np.full((ds_train.n,), base_score, np.float32))
        if ds_test is not None:
            idx_t = self._put(ds_test.idx)
            val_t = self._put(ds_test.val)
            y_t = self._put(ds_test.y)
            weight_t = self._put(ds_test.weight)
            z_t = self._put(np.full((ds_test.n,), base_score, np.float32))

        eval_set = EvalSet(p.loss.evaluate_metric) if p.loss.evaluate_metric else None
        cfg = LBFGSConfig.from_params(p.line_search)

        jit_tree_out = jax.jit(model.tree_output)
        jit_ens_loss = jax.jit(lambda s, yy, ww: _ensemble_loss(loss_fn, s, yy, ww))
        l1_vec, l2_vec = model.reg_vectors(p.loss.l1[0], p.loss.l2[0])

        # continue_train: replay finished trees into z
        # (reference: GBMLRDataFlow.loadModel + per-tree accumulate).
        # Rank0 reads the checkpoints, peers take its broadcast — dumps are
        # rank0-only so non-shared storage must not diverge on resume.
        from .parallel.collectives import load_on_rank0

        finished = 0
        info = load_on_rank0(lambda: model.load_tree_info(self.fs))
        if (p.model.continue_train or p.loss.just_evaluate) and info is not None:
            finished = int(info["finished_tree_num"])
            full_mask = self._put_rep(np.ones((model.n_features,), np.float32))
            trees_w = load_on_rank0(
                lambda: [
                    model.load_tree(self.fs, ingest.feature_map, t)
                    for t in range(finished)
                ]
            )
            for t, wt in enumerate(trees_w):
                if wt is None:
                    raise FileNotFoundError(f"tree-{t:05d} missing for continue_train")
                wt = self._put_rep(wt)
                z = z + lr * jit_tree_out(wt, idx, val, full_mask)
                if ds_test is not None:
                    z_t = z_t + lr * jit_tree_out(wt, idx_t, val_t, full_mask)
            log.info("continue_train: replayed %d finished trees", finished)

        # two rng streams: the feature stream draws fixed-size vectors so it
        # stays bitwise-identical across ranks; the instance stream folds in
        # the process index so per-shard sample masks are independent across
        # ranks instead of perfectly correlated (ADVICE r3; process 0 keeps
        # the seed unchanged, so single-process runs reproduce as before)
        rng_inst = np.random.RandomState(
            (p.random.seed + 7919 * jax.process_index()) % (2**32)
        )
        rng_feat = np.random.RandomState(p.random.seed + 104729)
        per_tree_loss: List[float] = []
        compensate = 1.0 / p.instance_sample_rate

        for tree in range(finished, tree_num):
            if self._guard is not None and self._guard.triggered:
                # trees [0, tree) are on disk (dump_tree + tree-info per
                # round) — the dump trail IS the checkpoint
                self._guard.preempt(
                    p.model.data_path, family=self.variant, trees=tree,
                )
            # per-tree Bernoulli masks (reference: randomNextSample)
            inst = (rng_inst.rand(ds_train.n) <= p.instance_sample_rate).astype(np.float32)
            inst[ds_train.n_real :] = 0.0
            gmask_np = (rng_feat.rand(model.n_features) <= p.feature_sample_rate).astype(
                np.float32
            )
            if p.model.need_bias:
                gmask_np[0] = 1.0
            gmask = self._put_rep(gmask_np)
            w_eff = self._put(np.asarray(ds_train.weight) * inst * compensate)

            w0 = model.init_weights(tree_seed=tree)
            batch = (idx, val, z, gmask, y, w_eff)
            row_chunk = model.suggest_row_chunk(
                int(idx.shape[0]), int(idx.shape[1]) if idx.ndim > 1 else 1,
                n_shards=(
                    int(self.mesh.devices.size) if self.mesh is not None else 1
                ),
            )
            res = minimize_lbfgs(
                model.pure_loss,
                self._put_rep(w0),
                cfg,
                batch=batch,
                l1_vec=l1_vec,
                l2_vec=l2_vec,
                g_weight=g_weight,
                callback=(lambda it, st: True) if p.loss.just_evaluate else None,
                row_chunk=row_chunk,
                row_mask=model.batch_row_mask,
                mesh=self.mesh if row_chunk is not None else None,
            )
            per_tree_loss.append(res.loss / g_weight)
            if p.loss.just_evaluate:
                break

            # accumulate (reference: GBMLRDataFlow.accumulate — lr-shrunk)
            w_tree = res.w
            z = z + lr * jit_tree_out(w_tree, idx, val, gmask)
            if ds_test is not None:
                z_t = z_t + lr * jit_tree_out(w_tree, idx_t, val_t, gmask)

            # dump tree + info, rank0-only (reference: dumpModel + dumpModelInfo)
            if jax.process_index() == 0:
                model.dump_tree(
                    self.fs, np.asarray(w_tree), gmask_np, ingest.feature_map, tree
                )
                model.dump_tree_info(self.fs, tree + 1, base_score)

            ens = self._ensemble_scores(z, tree + 1)
            tl = float(jit_ens_loss(ens, y, weight)) / g_weight
            msg = f"[tree={tree}] {time.time()-t0:.1f}s fit avg loss={per_tree_loss[-1]:.6f} ensemble avg loss={tl:.6f}"
            if ds_test is not None:
                ens_t = self._ensemble_scores(z_t, tree + 1)
                ttl = float(jit_ens_loss(ens_t, y_t, weight_t)) / max(
                    g_weight_test, 1e-12
                )
                msg += f" test={ttl:.6f}"
            log.info(msg)

        n_built = max(tree_num - finished, 0) + finished
        ens = self._ensemble_scores(z, max(n_built, 1))
        train_loss = float(jit_ens_loss(ens, y, weight)) / g_weight
        out = BoostResult(
            n_trees=n_built,
            train_loss=train_loss,
            test_loss=None,
            per_tree_loss=per_tree_loss,
        )
        if eval_set is not None:
            out.train_metrics = eval_set.evaluate(
                loss_fn.predict(ens), y, weight
            )
        if ds_test is not None:
            ens_t = self._ensemble_scores(z_t, max(n_built, 1))
            out.test_loss = float(jit_ens_loss(ens_t, y_t, weight_t)) / max(
                g_weight_test, 1e-12
            )
            if eval_set is not None:
                out.test_metrics = eval_set.evaluate(
                    loss_fn.predict(ens_t), y_t, weight_t
                )
        log.info(
            "boosting done: %d trees, train loss %.6f, metrics %s",
            out.n_trees,
            out.train_loss,
            out.train_metrics,
        )
        return out

    def _ensemble_scores(self, z, n_trees: int):
        """GB: z is the ensemble score; RF: averaged (reference (z)/treeNum
        at predict time)."""
        if self.params.gbst_type == "random_forest":
            return z / n_trees
        return z


def _ensemble_loss(loss_fn, scores, y, weight):
    per_row = jnp.where(weight > 0, loss_fn.loss(scores, y), 0.0)
    return jnp.sum(weight * per_row)
