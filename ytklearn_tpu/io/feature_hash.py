"""Feature hashing — murmur3-128 with the ±1 sign-bit trick.

Rebuild of reference feature/FeatureHash.java:94-118: each feature name is
murmur3_128-hashed (seeded); the low 31 bits of the first 64-bit word pick a
bucket, bit 40 picks a ±1 sign multiplied into the value so collisions cancel
in expectation (unbiased hashing). Colliding features *sum* their signed
values. The hash below is the standard MurmurHash3 x64 128-bit algorithm, the
same one Guava's murmur3_128 implements, so bucket assignments match the
reference for identical seeds and UTF-8 names.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

_MASK64 = (1 << 64) - 1


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK64


def _fmix64(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _MASK64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _MASK64
    k ^= k >> 33
    return k


_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AB90ED1F8779


def murmur3_x64_128(data: bytes, seed: int = 0) -> Tuple[int, int]:
    """Canonical MurmurHash3_x64_128; returns (h1, h2) as unsigned 64-bit."""
    length = len(data)
    h1 = seed & _MASK64
    h2 = seed & _MASK64
    nblocks = length // 16
    for b in range(nblocks):
        k1 = int.from_bytes(data[b * 16 : b * 16 + 8], "little")
        k2 = int.from_bytes(data[b * 16 + 8 : b * 16 + 16], "little")
        k1 = (k1 * _C1) & _MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * _C2) & _MASK64
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & _MASK64
        h1 = (h1 * 5 + 0x52DCE729) & _MASK64
        k2 = (k2 * _C2) & _MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * _C1) & _MASK64
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & _MASK64
        h2 = (h2 * 5 + 0x38495AB5) & _MASK64

    tail = data[nblocks * 16 :]
    k1 = k2 = 0
    t = len(tail)
    if t >= 9:
        k2 = int.from_bytes(tail[8:].ljust(8, b"\0"), "little")
        k2 = (k2 * _C2) & _MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * _C1) & _MASK64
        h2 ^= k2
    if t > 0:
        k1 = int.from_bytes(tail[:8][:min(t, 8)].ljust(8, b"\0"), "little")
        k1 = (k1 * _C1) & _MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * _C2) & _MASK64
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _MASK64
    h2 = (h2 + h1) & _MASK64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & _MASK64
    h2 = (h2 + h1) & _MASK64
    return h1, h2


def murmur3_x64_128_h1(data: bytes, seed: int = 0) -> int:
    """First 64-bit word as a *signed* Java long (Guava HashCode.asLong)."""
    h1, _ = murmur3_x64_128(data, seed)
    return h1 - (1 << 64) if h1 >= (1 << 63) else h1


class FeatureHash:
    """reference: feature/FeatureHash.java (hashMap2Map :94-118)."""

    def __init__(self, bucket_size: int, seed: int, prefix: str = "hash_"):
        self.bucket_size = int(bucket_size)
        self.seed = int(seed)
        self.prefix = prefix

    def hash_name(self, name: str) -> Tuple[str, float]:
        """name -> (hashed bucket name, ±1 sign)."""
        h = murmur3_x64_128_h1(name.encode("utf-8"), self.seed)
        bucket = (h & 0x7FFFFFFF) % self.bucket_size
        sign = 2.0 * ((h & 0x10000000000) >> 40) - 1.0
        return f"{self.prefix}{bucket}", sign

    def hash_features(self, feats: Iterable[Tuple[str, float]]) -> List[Tuple[str, float]]:
        """Hash (name,val) pairs; collisions accumulate signed values
        (reference: FeatureHash.hashMap2Map)."""
        out: Dict[str, float] = {}
        for name, val in feats:
            hname, sign = self.hash_name(name)
            out[hname] = out.get(hname, 0.0) + sign * val
        return list(out.items())
