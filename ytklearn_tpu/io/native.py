"""ctypes bindings for the native C++ ingest parser (native/ytk_parse.cpp).

The .so is compiled on demand with g++ (cached by source mtime under
native/build/). Callers use `native_available()` and fall back to the pure
Python parser when the toolchain is missing — the native path is an exact
drop-in (same rows, same errors, same first-seen feature-name order; parity
enforced by tests/test_native_ingest.py).

TPU-native framing: this is the runtime's data-loader component — the
reference parallelizes ingest across Java reader threads
(dataflow/DataFlow.java:483-534 readQueues + per-thread CoreData.readData);
here the same row-range parallelism is std::thread workers over one byte
buffer, feeding numpy columnar arrays that are a single device_put away
from the mesh.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..config import knobs

log = logging.getLogger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "ytk_parse.cpp")
_SO = os.path.join(_REPO, "native", "build", "libytkparse.so")

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # per-process temp name: concurrent builders (multi-host JAX on one
    # machine, parallel pytest) each compile privately, then atomically
    # promote — last os.replace wins, never a torn .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-march=native", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, OSError) as e:  # toolchain missing / compile error -> fallback
        err = getattr(e, "stderr", b"")
        log.warning("native parser build failed (%s); using python parser: %s",
                    e, err.decode()[:500] if err else "")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    os.replace(tmp, _SO)
    return True


def _load():
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if knobs.get_bool("YTK_NO_NATIVE"):
            _lib_failed = True
            return None
        try:
            stale = (not os.path.exists(_SO)
                     or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        except OSError:
            stale = True
        # ytklint: allow(blocking-call-under-lock) reason=first-touch build serialization is the point — every ingest thread must wait for the ONE compiler run instead of racing N compiles of the same .so
        if stale and not _build():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native parser load failed: %s", e)
            _lib_failed = True
            return None
        lib.ytk_parse.restype = ctypes.c_void_p
        lib.ytk_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64,
        ]
        for name in ("ytk_n_rows", "ytk_nnz", "ytk_n_label_vals",
                     "ytk_n_names", "ytk_name_bytes", "ytk_n_errors"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p]
        lib.ytk_fill.restype = None
        lib.ytk_fill.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 7
        lib.ytk_free.restype = None
        lib.ytk_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


@dataclass
class ParsedBlock:
    """Columnar parse result for a block of lines.

    Rows appear in input-line order. `labels` is ragged via label_ptr
    (1 entry for scalar losses, K for explicit multiclass vectors).
    `feat_ids` index into `names` (first-seen order across kept lines).
    """

    weights: np.ndarray  # (n,) f32
    label_ptr: np.ndarray  # (n+1,) i64
    labels: np.ndarray  # (L,) f32
    row_ptr: np.ndarray  # (n+1,) i64
    feat_ids: np.ndarray  # (nnz,) i32 -> names
    feat_vals: np.ndarray  # (nnz,) f32
    names: List[str]
    n_errors: int

    @property
    def n(self) -> int:
        return len(self.weights)


def parse_block(
    data: bytes,
    x_delim: str = "###",
    y_delim: str = ",",
    features_delim: str = ",",
    feature_name_val_delim: str = ":",
    n_threads: int = 0,
    divisor: int = 1,
    remainder: int = 0,
) -> ParsedBlock:
    """Parse a byte buffer of ytklearn-format lines natively.

    divisor/remainder implement the global line-modulo shard selection
    (fs.select_read_lines / reference IFileSystem.selectRead).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native parser unavailable")
    if len(y_delim) != 1 or len(features_delim) != 1 or len(feature_name_val_delim) != 1:
        raise ValueError("native parser requires single-char y/features/name-val delims")
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 1, 32)
    h = lib.ytk_parse(
        data, len(data), x_delim.encode(), y_delim.encode(),
        features_delim.encode(), feature_name_val_delim.encode(),
        n_threads, divisor, remainder,
    )
    try:
        n = lib.ytk_n_rows(h)
        nnz = lib.ytk_nnz(h)
        nlab = lib.ytk_n_label_vals(h)
        nnames = lib.ytk_n_names(h)
        nbytes = lib.ytk_name_bytes(h)
        weights = np.empty(n, np.float32)
        label_ptr = np.empty(n + 1, np.int64)
        labels = np.empty(nlab, np.float32)
        row_ptr = np.empty(n + 1, np.int64)
        feat_ids = np.empty(nnz, np.int32)
        feat_vals = np.empty(nnz, np.float32)
        name_buf = ctypes.create_string_buffer(max(int(nbytes), 1))
        lib.ytk_fill(
            h,
            weights.ctypes.data_as(ctypes.c_void_p),
            label_ptr.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(ctypes.c_void_p),
            row_ptr.ctypes.data_as(ctypes.c_void_p),
            feat_ids.ctypes.data_as(ctypes.c_void_p),
            feat_vals.ctypes.data_as(ctypes.c_void_p),
            ctypes.cast(name_buf, ctypes.c_void_p),
        )
        names = (
            name_buf.raw[: int(nbytes)].decode("utf-8").split("\n")[:-1]
            if nnames else []
        )
        return ParsedBlock(
            weights=weights, label_ptr=label_ptr, labels=labels,
            row_ptr=row_ptr, feat_ids=feat_ids, feat_vals=feat_vals,
            names=names, n_errors=int(lib.ytk_n_errors(h)),
        )
    finally:
        lib.ytk_free(h)


def parse_paths(
    fs,
    paths: Sequence[str],
    x_delim: str = "###",
    y_delim: str = ",",
    features_delim: str = ",",
    feature_name_val_delim: str = ":",
    n_threads: int = 0,
    divisor: int = 1,
    remainder: int = 0,
) -> ParsedBlock:
    """Parse files one at a time and merge the columnar outputs.

    Identical result to one parse_block call over the newline-normalized
    concatenation of all files in sorted-path order (same rows, errors,
    first-seen name order), but peak memory holds
    one file's raw bytes instead of the whole dataset (ADVICE r3: the
    reference ingest streams per reader thread, DataFlow.java:483-534).
    The line-modulo shard phase carries across file boundaries: every
    physical line counts, and each file is newline-normalized, so file k
    starts at global line sum(lines of files < k)."""
    from ..resilience import chaos_point, retry_call

    blocks: List[ParsedBlock] = []
    line0 = 0
    for p in sorted(fs.recur_get_paths(paths)):
        # same `io.read` retry/chaos seam as FileSystem.read_lines: a
        # transient fault rereads this one file, never kills the run
        def _read(path=p) -> bytes:
            chaos_point("io.read")
            with fs.open(path, "rb") as f:
                return f.read()

        b = retry_call(_read, site="io.read")
        if not b:
            continue
        if not b.endswith(b"\n"):
            b += b"\n"
        rem = (remainder - line0) % divisor if divisor > 1 else 0
        blocks.append(
            parse_block(
                b, x_delim, y_delim, features_delim, feature_name_val_delim,
                n_threads=n_threads, divisor=divisor, remainder=rem,
            )
        )
        line0 += b.count(b"\n")
        del b
    return merge_blocks(blocks)


def merge_blocks(blocks: Sequence[ParsedBlock]) -> ParsedBlock:
    """Concatenate ParsedBlocks row-wise, keeping the first-seen feature-name
    order across blocks (block order = file order = line order)."""
    if not blocks:
        return ParsedBlock(
            weights=np.empty(0, np.float32),
            label_ptr=np.zeros(1, np.int64),
            labels=np.empty(0, np.float32),
            row_ptr=np.zeros(1, np.int64),
            feat_ids=np.empty(0, np.int32),
            feat_vals=np.empty(0, np.float32),
            names=[], n_errors=0,
        )
    if len(blocks) == 1:
        return blocks[0]
    uniq: dict = {}
    remapped_ids: List[np.ndarray] = []
    for blk in blocks:
        remap = np.asarray(
            [uniq.setdefault(nm, len(uniq)) for nm in blk.names], np.int32
        )
        remapped_ids.append(
            remap[blk.feat_ids] if len(blk.names) else blk.feat_ids
        )
    label_ptr = [np.zeros(1, np.int64)]
    row_ptr = [np.zeros(1, np.int64)]
    loff = roff = 0
    for blk in blocks:
        label_ptr.append(blk.label_ptr[1:] + loff)
        row_ptr.append(blk.row_ptr[1:] + roff)
        loff += int(blk.label_ptr[-1])
        roff += int(blk.row_ptr[-1])
    return ParsedBlock(
        weights=np.concatenate([b.weights for b in blocks]),
        label_ptr=np.concatenate(label_ptr),
        labels=np.concatenate([b.labels for b in blocks]),
        row_ptr=np.concatenate(row_ptr),
        feat_ids=np.concatenate(remapped_ids),
        feat_vals=np.concatenate([b.feat_vals for b in blocks]),
        names=list(uniq),
        n_errors=sum(b.n_errors for b in blocks),
    )


def expand_labels_columnar(
    label_ptr: np.ndarray, labels: np.ndarray, n: int, K: int
):
    """Vectorized python-float() label expansion shared by the GBDT and
    convex fast paths: width-K vectors pass through; width-1 is an int()-
    truncated class index where a negative in-range value wraps (python
    list indexing) and anything outside [-K, K-1] is an error line.

    Returns (bad, y): bad (n,) bool error-row mask; y (n,) f32 for K==1
    (first label, extras ignored) or (n, K) f32 one-hot/verbatim, zero
    rows where bad."""
    firsts = labels[label_ptr[:-1]] if n else np.zeros(0, np.float32)
    if K == 1:
        return np.zeros(n, bool), firsts.astype(np.float32)
    widths = np.diff(label_ptr)
    bad = (widths != 1) & (widths != K)
    cls = np.trunc(firsts).astype(np.int64)
    is_cls = widths == 1
    bad |= is_cls & ((cls >= K) | (cls < -K))
    y = np.zeros((n, K), np.float32)
    fullm = ~bad & (widths == K)
    if fullm.any():
        src = label_ptr[:-1][fullm][:, None] + np.arange(K)
        y[fullm] = labels[src]
    onem = ~bad & is_cls
    if onem.any():
        ck = cls[onem]
        ck = np.where(ck < 0, ck + K, ck)
        y[np.where(onem)[0], ck] = 1.0
    return bad, y


def supports_delims(delim) -> bool:
    """The C parser handles multi-char x_delim but single-char y/features/
    name-val delims; other configs use the python path."""
    return (
        len(delim.x_delim) >= 1
        and len(delim.y_delim) == 1
        and len(delim.features_delim) == 1
        and len(delim.feature_name_val_delim) == 1
    )
