"""Data ingest — the TPU rebuild of the reference DataFlow/CoreData load path.

The reference parses text lines into per-thread blocked-CSR int arrays
(reference: dataflow/CoreData.java:536-645, dataflow/DataFlow.java:468-765).
Here the terminal format is *padded ELL* arrays — `(n, width)` feature-index
and value matrices — because static shapes are what XLA wants: Xv becomes a
gather+reduce, XTv a segment-sum, both jit-able with no ragged rows.

Pipeline (mirrors DataFlow.loadFlow):
    lines -> (py transform hook) -> parse (weight###label###f:v,...)
          -> y-sampling / error tolerance
          -> feature count map + transform stats        [train only]
          -> feature dict build (sorted names) or load  [train only]
          -> transform value rewrite
          -> ELL arrays (bias at index 0 when need_bias)
"""

from __future__ import annotations

import dataclasses
import math
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..config.params import CommonParams, DelimParams
from ..obs import (
    health,
    heartbeat as obs_heartbeat,
    inc as obs_inc,
    span as obs_span,
)
from .feature_hash import FeatureHash
from .fs import FileSystem, LocalFileSystem


# ---------------------------------------------------------------------------
# Line parsing
# ---------------------------------------------------------------------------


@dataclass
class ParsedLine:
    weight: float
    labels: List[float]  # 1 entry for scalar losses; K for multiclass
    feats: List[Tuple[str, float]]


def parse_line(line: str, delim: DelimParams) -> ParsedLine:
    """`weight###label[,label...]###name:val,name:val` (reference:
    CoreData.trainDataSplit/weightExtract/yExtract/line2FeatureMap)."""
    info = line.strip().split(delim.x_delim)
    weight = float(info[0])
    labels = [float(v) for v in info[1].split(delim.y_delim)]
    feats: List[Tuple[str, float]] = []
    ftext = info[2].strip()
    if ftext:
        for f in ftext.split(delim.features_delim):
            name, _, val = f.partition(delim.feature_name_val_delim)
            feats.append((name.strip(), float(val)))
    return ParsedLine(weight, labels, feats)


def load_transform_hook(path: str) -> Callable[[bytes], List[str]]:
    """Load the user data-transform hook: a python file defining
    `transform(line: bytes) -> list[str]`. The reference embeds Jython for
    this (reference: dataflow/DataUtils.java:142, bin/transform.py); here it
    is plain Python."""
    ns: Dict = {}
    with LocalFileSystem().open(path) as f:
        exec(compile(f.read(), path, "exec"), ns)
    if "transform" not in ns:
        raise ValueError(f"{path} does not define transform(bytearray) -> [lines]")
    return ns["transform"]


# ---------------------------------------------------------------------------
# Feature statistics / transform
# ---------------------------------------------------------------------------


@dataclass
class FeatureStat:
    """Running (cnt, sum, sum2, min, max) (reference: CoreData.FeatureStat:107)."""

    cnt: int = 0
    sum: float = 0.0
    sum2: float = 0.0
    max: float = -math.inf
    min: float = math.inf

    def update(self, v: float) -> None:
        self.cnt += 1
        self.sum += v
        self.sum2 += v * v
        if v > self.max:
            self.max = v
        if v < self.min:
            self.min = v

    def merge(self, o: "FeatureStat") -> None:
        self.cnt += o.cnt
        self.sum += o.sum
        self.sum2 += o.sum2
        self.max = max(self.max, o.max)
        self.min = min(self.min, o.min)


@dataclass
class TransformNode:
    """Standardization / range-scaling of one feature
    (reference: CoreData.TransformNode:155; sidecar text format kept
    byte-compatible so reference predictors can read it)."""

    mode: str  # standardization | scale_range
    mean: float = 0.0
    stdvar: float = 0.0
    max: float = 0.0
    min: float = 0.0
    range_max: float = 1.0
    range_min: float = -1.0

    def transform(self, val: float) -> float:
        if self.mode == "standardization":
            if self.stdvar < 1e-6:
                return val
            return (val - self.mean) / self.stdvar
        if abs(self.max - self.min) < 1e-6:
            return 1.0
        return self.range_min + (self.range_max - self.range_min) * (
            (val - self.min) / (self.max - self.min)
        )

    def __str__(self) -> str:  # sidecar line payload
        return (
            f"mode={self.mode}, mean={self.mean}, stdvar={self.stdvar}, "
            f"max={self.max}, min={self.min}, rangeMax={self.range_max}, "
            f"rangeMin={self.range_min}"
        )

    @classmethod
    def from_string(cls, s: str) -> "TransformNode":
        info = [kv.split("=")[1].strip() for kv in s.split(",")]
        return cls(
            mode=info[0].lower(),
            mean=float(info[1]),
            stdvar=float(info[2]),
            max=float(info[3]),
            min=float(info[4]),
            range_max=float(info[5]),
            range_min=float(info[6]),
        )

    @classmethod
    def from_stat(
        cls, stat: FeatureStat, mode: str, range_max: float, range_min: float
    ) -> "TransformNode":
        mean = stat.sum / stat.cnt
        mean2 = stat.sum2 / stat.cnt
        return cls(
            mode=mode,
            mean=mean,
            stdvar=math.sqrt(max(mean2 - mean * mean, 0.0)),
            max=stat.max,
            min=stat.min,
            range_max=range_max,
            range_min=range_min,
        )


# ---------------------------------------------------------------------------
# The dataset container
# ---------------------------------------------------------------------------


@dataclass
class SparseDataset:
    """Padded ELL sparse rows, host side (numpy), jit-ready.

    idx[i, j] / val[i, j] hold the j-th (feature, value) of row i; padding
    entries have idx=0, val=0.0 (harmless: they add 0·w[0] to scores and 0 to
    grads). When need_bias, every row's first slot is (0, 1.0) — index 0 *is*
    the bias feature, as in the reference dict layout
    (reference: DataFlow.reduceFeature fName2IndexMap bias at 0).
    """

    idx: np.ndarray  # (n, width) int32
    val: np.ndarray  # (n, width) float32
    y: np.ndarray  # (n,) or (n, K) float32
    weight: np.ndarray  # (n,) float32
    n_real: int  # rows before padding
    dim: int  # feature dimension (dict size)
    field: Optional[np.ndarray] = None  # (n, width) int32, FFM only

    @property
    def n(self) -> int:
        return self.idx.shape[0]

    def pad_rows(self, multiple: int) -> "SparseDataset":
        """Pad row count to a multiple (mesh divisibility). Padding rows have
        weight 0 so every weighted reduction ignores them — the static-shape
        replacement for the reference's ragged per-worker row counts."""
        n = self.idx.shape[0]
        target = (n + multiple - 1) // multiple * multiple
        return self.pad_rows_to(target)

    def pad_rows_to(self, target: int) -> "SparseDataset":
        """Pad to an exact row count (multi-process shard equalization —
        an empty shard still pads up to the group-agreed target)."""
        n = self.idx.shape[0]
        if target <= n:
            return self
        pad = target - n
        return dataclasses.replace(
            self,
            idx=np.pad(self.idx, ((0, pad), (0, 0))),
            val=np.pad(self.val, ((0, pad), (0, 0))),
            y=np.pad(self.y, ((0, pad),) + ((0, 0),) * (self.y.ndim - 1)),
            weight=np.pad(self.weight, (0, pad)),
            field=None if self.field is None else np.pad(self.field, ((0, pad), (0, 0))),
        )


@dataclass
class _Cols:
    """Columnar rows from the native parser (post label-expansion, hashing,
    y-sampling): the fast-path replacement for List[ParsedLine]."""

    weight: np.ndarray  # (n,) f32
    y: np.ndarray  # (n,) or (n, K) f32
    occ_row: np.ndarray  # (nnz,) i64 row of each feature occurrence
    occ_name: np.ndarray  # (nnz,) i64 -> names
    occ_val: np.ndarray  # (nnz,) f64
    names: List[str]


def _counts_from_rows(rows: Sequence[ParsedLine]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for r in rows:
        for name, _ in r.feats:
            counts[name] = counts.get(name, 0) + 1
    return counts


# ---------------------------------------------------------------------------
# The ingest driver (DataFlow equivalent)
# ---------------------------------------------------------------------------


@dataclass
class IngestResult:
    train: SparseDataset
    test: Optional[SparseDataset]
    feature_map: Dict[str, int]  # name -> global index
    transform_nodes: Dict[int, TransformNode] = field(default_factory=dict)
    # global label stats (reference: CoreData.globalSync y stats)
    y_real_stat: Optional[np.ndarray] = None
    y_weight_stat: Optional[np.ndarray] = None


def shard_plan(fs, data_params, paths) -> Tuple[Sequence[str], int, int]:
    """This process's read plan: (paths, divisor, remainder). The single
    source of truth for the assigned / files_avg / lines_avg dispatch
    (reference: DataFlow.java:391-410) — shared by the python line reader
    and the native parser so both always read the same shard."""
    import jax

    n_proc = jax.process_count()
    proc = jax.process_index()
    if data_params.assigned or n_proc == 1:
        return paths, 1, 0
    if data_params.unassigned_mode == "files_avg":
        files = sorted(fs.recur_get_paths(paths))
        return files[proc::n_proc], 1, 0
    return paths, n_proc, proc


def shard_read_lines(fs, data_params, paths):
    """This process's line shard (assigned mode reads everything; unassigned
    splits by files_avg or line-modulo lines_avg across processes)."""
    paths, divisor, remainder = shard_plan(fs, data_params, paths)
    if divisor == 1:
        return fs.read_lines(paths)
    return fs.select_read_lines(paths, divisor, remainder)


class DataIngest:
    """Single-host ingest (the TPU host driver replaces per-thread CoreData
    shards: one process parses, the mesh shards rows on device). Multi-host
    processes each parse their line-modulo shard and merge dict/stats via
    host collectives (parallel.collectives.host_allgather_objects)."""

    def __init__(
        self,
        params: CommonParams,
        fs: Optional[FileSystem] = None,
        n_labels: int = 1,
        label_as_class_index: bool = False,
        transform_hook: Optional[Callable[[bytes], List[str]]] = None,
        field_map: Optional[Dict[str, int]] = None,
    ):
        self.params = params
        self.fs = fs or LocalFileSystem()
        self.n_labels = n_labels  # K for multiclass losses, else 1
        self.label_as_class_index = label_as_class_index
        self.transform_hook = transform_hook
        # FFM: field = feature-name prefix before field_delim, mapped through
        # the field dict; features with unknown fields are dropped
        # (reference: FFMModelDataFlow.updateX)
        self.field_map = field_map
        p = params
        self.hash = (
            FeatureHash(
                p.feature.feature_hash.bucket_size,
                p.feature.feature_hash.seed,
                p.feature.feature_hash.feature_prefix,
            )
            if p.feature.feature_hash.need_feature_hash
            else None
        )
        self.rng = random.Random(20170425)

    # -- parsing --------------------------------------------------------

    def _expand_labels(self, labels: List[float], line: str) -> List[float]:
        K = self.n_labels
        if K == 1:
            return labels[:1]
        if len(labels) == K:
            return labels
        if len(labels) == 1:
            clazz = int(labels[0])
            if clazz >= K:
                raise ValueError(f"label must be in [0,{K-1}]: {line}")
            out = [0.0] * K
            out[clazz] = 1.0
            return out
        raise ValueError(f"label num must be {K} or 1: {line}")

    def parse_rows(
        self, lines: Iterable[str], max_error_tol: int, is_train: bool
    ) -> List[ParsedLine]:
        delim = self.params.data.delim
        ys = dict(self.params.data.y_sampling)
        rows: List[ParsedLine] = []
        errors = 0
        subsampled = 0  # parse-valid lines dropped by y_sampling
        hb = obs_heartbeat("ingest.parse", every_s=30.0)
        for raw in lines:
            if len(rows) & 0xFFFF == 0 and rows:
                hb.beat(rows=len(rows), errors=errors)
            if not raw.strip():
                continue
            for line in (
                self.transform_hook(raw.encode("utf-8")) if self.transform_hook else [raw]
            ):
                try:
                    pl = parse_line(line, delim)
                    pl.labels = self._expand_labels(pl.labels, line)
                    if self.hash is not None:
                        pl.feats = self.hash.hash_features(pl.feats)
                    if is_train and ys:
                        # label-dependent subsampling with inverse-probability
                        # weight correction (reference: CoreData.yExtract) —
                        # inside the try so a label vector without an exact
                        # 1.0 counts toward max_error_tol like any bad line
                        label_idx = (
                            pl.labels.index(1.0)
                            if len(pl.labels) > 1
                            else int(pl.labels[0])
                        )
                        rate = ys.get(str(label_idx))
                        if rate is not None:
                            pl.weight *= (1.0 / rate) if rate <= 1.0 else rate
                            if self.rng.random() > rate:
                                subsampled += 1
                                continue
                except Exception:
                    errors += 1
                    if errors > max_error_tol:
                        raise
                    continue
                rows.append(pl)
        obs_inc("ingest.rows_parsed", len(rows))
        obs_inc("ingest.error_lines", errors)
        # rate sentinel under the absolute max_error_tol cap: a feed that is
        # mostly garbage but below the cap should still raise a flag. The
        # denominator counts parse-valid lines BEFORE y_sampling drops so
        # heavy subsampling can't inflate the rate.
        health.check_ingest(
            "ingest.parse", errors, len(rows) + subsampled, is_train=is_train
        )
        return rows

    # -- dict -----------------------------------------------------------

    def build_feature_map(self, rows: Sequence[ParsedLine]) -> Dict[str, int]:
        """Count -> filter(threshold) -> sorted names -> indices, bias at 0
        (reference: DataFlow.reduceFeature:294)."""
        return self.finalize_feature_map(_counts_from_rows(rows))

    def finalize_feature_map(self, counts: Dict[str, int]) -> Dict[str, int]:
        """Shared dict finalization: cross-process count merge, threshold
        filter, sorted names, bias at 0."""
        p = self.params
        counts = self._merge_counts(counts)
        thr = p.feature.filter_threshold
        names = sorted(n for n, c in counts.items() if c >= thr)
        fmap: Dict[str, int] = {}
        delta = 0
        if p.model.need_bias:
            fmap[p.model.bias_feature_name] = 0
            delta = 1
            if p.model.bias_feature_name in names:
                names.remove(p.model.bias_feature_name)
        for i, n in enumerate(names):
            fmap[n] = i + delta
        return fmap

    def _merge_counts(self, counts: Dict[str, int]) -> Dict[str, int]:
        """Across processes (multi-host): union-sum the count maps — the
        allreduceMap equivalent (reference: CoreData.globalSync:628)."""
        from ..parallel.collectives import host_allgather_objects

        all_counts = host_allgather_objects(counts)
        if len(all_counts) == 1:
            return counts
        merged: Dict[str, int] = {}
        for c in all_counts:
            for k, v in c.items():
                merged[k] = merged.get(k, 0) + v
        return merged

    def load_feature_map(self, dict_paths: Sequence[str]) -> Dict[str, int]:
        """reference: DataFlow.loadDict:244 — bias at 0, then dict file lines
        in sorted-path order. Rank0 reads, peers take its broadcast — dict
        sidecars are rank0-only dumps, so on non-shared storage other ranks
        must not read (or miss) a divergent copy (ADVICE r3)."""
        from ..parallel.collectives import load_on_rank0

        def read_names():
            out: List[str] = []
            for path in sorted(self.fs.recur_get_paths(dict_paths)):
                with self.fs.open(path) as f:
                    out.extend(line.strip() for line in f)
            return out

        names = load_on_rank0(read_names)
        p = self.params
        fmap: Dict[str, int] = {}
        if p.model.need_bias:
            fmap[p.model.bias_feature_name] = 0
        for name in names:
            if name and name not in fmap:
                fmap[name] = len(fmap)
        return fmap

    # -- transform ------------------------------------------------------

    def compute_transform_nodes(
        self, rows: Sequence[ParsedLine], fmap: Dict[str, int]
    ) -> Dict[int, TransformNode]:
        if not self.params.feature.transform.switch_on:
            return {}
        stats: Dict[str, FeatureStat] = {}
        for r in rows:
            for name, v in r.feats:
                s = stats.get(name)
                if s is None:
                    stats[name] = s = FeatureStat()
                s.update(v)
        return self.nodes_from_stats(stats, fmap)

    def nodes_from_stats(
        self, stats: Dict[str, FeatureStat], fmap: Dict[str, int]
    ) -> Dict[int, TransformNode]:
        """Cross-process stat merge + include/exclude selection -> nodes."""
        p = self.params
        t = p.feature.transform
        # multi-host merge
        from ..parallel.collectives import host_allgather_objects

        all_stats = host_allgather_objects(stats)
        if len(all_stats) > 1:
            merged: Dict[str, FeatureStat] = {}
            for st in all_stats:
                for k, v in st.items():
                    if k in merged:
                        merged[k].merge(v)
                    else:
                        merged[k] = dataclasses.replace(v)
            stats = merged

        include, exclude = set(t.include_features), set(t.exclude_features)
        names = set(fmap) - {p.model.bias_feature_name}
        chosen = include or (names - exclude if exclude else names)
        nodes: Dict[int, TransformNode] = {}
        for name in chosen:
            if name in stats and name in fmap:
                nodes[fmap[name]] = TransformNode.from_stat(
                    stats[name], t.mode, t.scale_max, t.scale_min
                )
        return nodes

    def write_transform_sidecar(
        self, nodes: Dict[int, TransformNode], fmap: Dict[str, int]
    ) -> None:
        """`<model>_feature_transform_stat` sidecar, reference text format
        (reference: DataFlow.reduceFeature stat writer, FEATURE_TRANSFORM_STAT)."""
        if not nodes:
            return
        inv = {i: n for n, i in fmap.items()}
        path = self.params.model.data_path + "_feature_transform_stat"
        with self.fs.atomic_open(path) as f:
            for i, node in sorted(nodes.items()):
                f.write(f"{inv[i]}###{node}\n")

    def load_transform_sidecar(self, fmap: Dict[str, int]) -> Dict[int, TransformNode]:
        path = self.params.model.data_path + "_feature_transform_stat"
        nodes: Dict[int, TransformNode] = {}
        if not self.fs.exists(path):
            return nodes
        from ..transform.sidecar import read_sidecar

        named, _digest = read_sidecar(self.fs, path)  # '#' header skipped
        for name, node in named.items():
            if name in fmap:
                nodes[fmap[name]] = node
        return nodes

    # -- materialization -------------------------------------------------

    def to_dataset(
        self,
        rows: Sequence[ParsedLine],
        fmap: Dict[str, int],
        nodes: Optional[Dict[int, TransformNode]] = None,
    ) -> SparseDataset:
        p = self.params
        nodes = nodes or {}
        need_bias = p.model.need_bias
        n = len(rows)
        K = self.n_labels
        fm = self.field_map
        fdelim = p.data.delim.field_delim
        mapped: List[List[Tuple[int, float, int]]] = []
        width = 1 if need_bias else 0
        for r in rows:
            entries: List[Tuple[int, float, int]] = []
            if need_bias:
                entries.append((0, 1.0, 0))  # bias field 0 (FFMModelDataFlow)
            for name, v in r.feats:
                gi = fmap.get(name)
                if gi is None:
                    continue  # filtered feature — dropped like handleLocalIdx
                fi = 0
                if fm is not None:
                    fi = fm.get(name.split(fdelim)[0], -1)
                    if fi < 0:
                        continue  # unknown field — dropped
                entries.append((gi, v, fi))
            mapped.append(entries)
            width = max(width, len(entries))
        width = max(width, 1)
        tv = None
        if nodes:
            # one vectorized replay over every kept entry — the same
            # apply_nodes kernel ingest's columnar path, the offline
            # predictors, and the serving pipeline share (transform/).
            # The bias entry has no node (nodes_from_stats excludes the
            # bias name), so replaying it is the identity.
            from ..transform.pipeline import TransformTable, apply_nodes

            flat_gi = np.fromiter(
                (e[0] for es in mapped for e in es),
                np.int64,
                sum(len(es) for es in mapped),
            )
            flat_v = np.fromiter(
                (e[1] for es in mapped for e in es), np.float64, len(flat_gi)
            )
            table = TransformTable.from_indexed(nodes, len(fmap))
            tv = apply_nodes(table, flat_gi, flat_v) if len(flat_gi) else flat_v
        idx = np.zeros((n, width), np.int32)
        val = np.zeros((n, width), np.float32)
        field = np.zeros((n, width), np.int32) if fm is not None else None
        k = 0
        for i, entries in enumerate(mapped):
            for j, (gi, v, fi) in enumerate(entries):
                idx[i, j] = gi
                val[i, j] = tv[k] if tv is not None else v
                k += 1
                if field is not None:
                    field[i, j] = fi
        y = np.asarray(
            [r.labels for r in rows], np.float32
        ).reshape((n, K)) if K > 1 else np.asarray([r.labels[0] for r in rows], np.float32)
        weight = np.asarray([r.weight for r in rows], np.float32)
        return SparseDataset(idx, val, y, weight, n_real=n, dim=len(fmap), field=field)

    # -- the whole flow ---------------------------------------------------

    def _resolve_feature_map(self, counts_fn) -> Dict[str, int]:
        """The dict branch shared by both load paths: load when just_evaluate
        / need_dict / continue_train finds a sidecar, else build from counts.

        Rank0 decides which branch applies (the sidecar existence check is a
        rank0-local fs fact — dumps are rank0-only), then every rank enters
        the same path: divergent branch picks would leave rank0 inside
        load_feature_map while peers enter finalize_feature_map's
        host_allgather collective, hanging the group (ADVICE r3)."""
        p = self.params
        model_dict_path = p.model.data_path + "_dict"
        from ..parallel.collectives import load_on_rank0

        def pick_dict_source():
            if p.loss.just_evaluate and self.fs.exists(model_dict_path):
                return [model_dict_path]
            if p.model.need_dict and p.model.dict_path:
                return [p.model.dict_path]
            if p.model.continue_train and self.fs.exists(model_dict_path):
                return [model_dict_path]
            return None

        src = load_on_rank0(pick_dict_source)
        if src is not None:
            return self.load_feature_map(src)  # rank0-read + broadcast inside
        return self.finalize_feature_map(counts_fn())

    def load(self) -> IngestResult:
        """The loadFlow equivalent (reference: dataflow/DataFlow.java:468).

        Dispatches to the columnar native-parser path when available (exact
        parity with the python path, tests/test_native_ingest.py); the python
        path remains for transform-hook / exotic-delimiter configs."""
        from . import native

        if (self.transform_hook is None
                and native.native_available()
                and native.supports_delims(self.params.data.delim)):
            return self._load_fast()
        return self._load_python()

    def _load_python(self) -> IngestResult:
        p = self.params

        def read(paths: Sequence[str]) -> Iterator[str]:
            return shard_read_lines(self.fs, p.data, paths)

        with obs_span("ingest.parse", split="train", path="python"):
            train_rows = self.parse_rows(
                read(p.data.train_paths), p.data.train_max_error_tol, is_train=True
            )
        with obs_span("ingest.dict"):
            fmap = self._resolve_feature_map(lambda: _counts_from_rows(train_rows))
        with obs_span("ingest.transform"):
            nodes = self.compute_transform_nodes(train_rows, fmap)
            if nodes:
                self.write_transform_sidecar(nodes, fmap)

        with obs_span("ingest.materialize", split="train"):
            train = self.to_dataset(train_rows, fmap, nodes)
        obs_inc("ingest.rows", train.n_real)
        test = None
        if p.data.test_paths:
            with obs_span("ingest.parse", split="test", path="python"):
                test_rows = self.parse_rows(
                    read(p.data.test_paths), p.data.test_max_error_tol, is_train=False
                )
            with obs_span("ingest.materialize", split="test"):
                test = self.to_dataset(test_rows, fmap, nodes)
            obs_inc("ingest.rows", test.n_real)

        # global label stats (reference: CoreData.globalSync y stats)
        K = max(self.n_labels, 2)
        y_real = np.zeros(K, np.int64)
        y_weight = np.zeros(K, np.float64)
        for r in train_rows:
            if len(r.labels) > 1:
                if 1.0 not in r.labels:
                    continue  # soft K-vector label: no class slot to count
                li = r.labels.index(1.0)
            else:
                li = int(r.labels[0])
            if 0 <= li < K:
                y_real[li] += 1
                y_weight[li] += r.weight
        return IngestResult(
            train=train,
            test=test,
            feature_map=fmap,
            transform_nodes=nodes,
            y_real_stat=y_real,
            y_weight_stat=y_weight,
        )

    # -- columnar fast path (native parser) -------------------------------

    def _parse_cols(self, paths, max_error_tol: int, is_train: bool) -> "_Cols":
        """Native parse + vectorized label expansion / hashing / y-sampling.
        Row and occurrence arrays come back in input order, matching the
        python path row-for-row (errors, dict order, rng consumption)."""
        from . import native

        p = self.params
        d = p.data.delim
        paths2, divisor, remainder = shard_plan(self.fs, p.data, paths)
        blk = native.parse_paths(
            self.fs, paths2, d.x_delim, d.y_delim, d.features_delim,
            d.feature_name_val_delim, divisor=divisor, remainder=remainder,
        )
        n_errors = blk.n_errors
        n = blk.n
        K = self.n_labels
        bad, y = native.expand_labels_columnar(blk.label_ptr, blk.labels, n, K)

        occ_row = np.repeat(np.arange(n), np.diff(blk.row_ptr))
        occ_name = blk.feat_ids.astype(np.int64)
        occ_val = blk.feat_vals.astype(np.float64)
        names: List[str] = blk.names

        if self.hash is not None and len(names):
            # hash per unique raw name, then per-row dedup-sum of signed
            # values in first-occurrence order (FeatureHash.hash_features)
            uniq: Dict[str, int] = {}
            hid_of = np.empty(len(names), np.int64)
            sign_of = np.empty(len(names), np.float64)
            for i, nm in enumerate(names):
                hn, sg = self.hash.hash_name(nm)
                hid_of[i] = uniq.setdefault(hn, len(uniq))
                sign_of[i] = sg
            signed = occ_val * sign_of[occ_name]
            hids = hid_of[occ_name]
            key = occ_row * np.int64(len(uniq)) + hids
            _, first_ix, inv = np.unique(key, return_index=True, return_inverse=True)
            sums = np.bincount(inv, weights=signed)
            order = np.argsort(first_ix, kind="stable")
            sel = first_ix[order]
            occ_row = occ_row[sel]
            occ_name = hids[sel]
            occ_val = sums[order]
            names = list(uniq)

        keep = ~bad
        weight = blk.weights.astype(np.float64)
        n_good = int(keep.sum())  # parse-valid lines, pre-subsample
        if is_train and p.data.y_sampling:
            # label-dependent subsampling with inverse-probability weight
            # correction (CoreData.yExtract). The host loop preserves the
            # python path's rng consumption order exactly: one rng.random()
            # per kept row whose label has a configured rate.
            ys = {k: float(v) for k, v in dict(p.data.y_sampling).items()}
            if K == 1:
                lidx = np.trunc(y).astype(np.int64)
                has1 = np.ones(n, bool)
            else:
                has1 = (y == 1.0).any(axis=1)
                lidx = np.argmax(y == 1.0, axis=1)
                # a K-vector label without an exact 1.0 cannot be sampled —
                # error line, like the python path's labels.index(1.0) raise
                newly_bad = keep & ~has1
                n_errors += int(newly_bad.sum())
                n_good -= int(newly_bad.sum())
                keep &= has1
            for i in np.flatnonzero(keep):
                rate = ys.get(str(int(lidx[i])))
                if rate is None:
                    continue
                weight[i] *= (1.0 / rate) if rate <= 1.0 else rate
                if self.rng.random() > rate:
                    keep[i] = False

        if n_errors > max_error_tol:
            raise ValueError(
                f"data error lines ({n_errors}) exceed max_error_tol "
                f"({max_error_tol})"
            )

        obs_inc("ingest.rows_parsed", float(keep.sum()))
        obs_inc("ingest.error_lines", float(n_errors))
        # rate over parse-valid lines BEFORE y_sampling drops: subsampling
        # a 99%-discarded majority class must not inflate the error rate
        health.check_ingest(
            "ingest.parse_native", int(n_errors), n_good, is_train=is_train
        )
        new_row = np.cumsum(keep) - 1
        occ_keep = keep[occ_row]
        return _Cols(
            weight=weight[keep].astype(np.float32),
            y=y[keep],
            occ_row=new_row[occ_row[occ_keep]],
            occ_name=occ_name[occ_keep],
            occ_val=occ_val[occ_keep],
            names=names,
        )

    def _cols_to_dataset(
        self,
        cols: "_Cols",
        fmap: Dict[str, int],
        nodes: Optional[Dict[int, TransformNode]] = None,
    ) -> SparseDataset:
        """Vectorized to_dataset: dict/field filtering, value transform,
        padded-ELL assembly."""
        p = self.params
        nodes = nodes or {}
        need_bias = p.model.need_bias
        n = len(cols.weight)
        gi_of = np.asarray([fmap.get(nm, -1) for nm in cols.names], np.int64)
        gi = gi_of[cols.occ_name] if len(cols.occ_name) else np.zeros(0, np.int64)
        keep = gi >= 0
        f = None
        if self.field_map is not None:
            fdelim = p.data.delim.field_delim
            fid_of = np.asarray(
                [self.field_map.get(nm.split(fdelim)[0], -1) for nm in cols.names],
                np.int64,
            )
            f = fid_of[cols.occ_name] if len(cols.occ_name) else np.zeros(0, np.int64)
            keep &= f >= 0
            f = f[keep]
        occ_row = cols.occ_row[keep]
        gi = gi[keep]
        val = cols.occ_val[keep].astype(np.float64)

        if nodes and len(gi):
            # the shared vectorized TransformNode replay (transform/) —
            # the identical kernel the serving pipeline executes, so the
            # trained values and the served values cannot drift
            from ..transform.pipeline import TransformTable, apply_nodes

            table = TransformTable.from_indexed(nodes, len(fmap))
            val = apply_nodes(table, gi, val)

        cnt = np.bincount(occ_row, minlength=n) if n else np.zeros(0, np.int64)
        delta = 1 if need_bias else 0
        width = max((int(cnt.max()) if n and len(cnt) else 0) + delta, 1)
        idx = np.zeros((n, width), np.int32)
        vmat = np.zeros((n, width), np.float32)
        fmat = np.zeros((n, width), np.int32) if self.field_map is not None else None
        if need_bias and n:
            vmat[:, 0] = 1.0  # bias index 0, field 0 (FFMModelDataFlow)
        rp = np.zeros(n + 1, np.int64)
        np.cumsum(cnt, out=rp[1:])
        j = np.arange(len(occ_row)) - rp[occ_row] + delta
        idx[occ_row, j] = gi
        vmat[occ_row, j] = val
        if fmat is not None:
            fmat[occ_row, j] = f
        K = self.n_labels
        y = cols.y if K > 1 else cols.y.reshape(-1)
        return SparseDataset(
            idx, vmat, y.astype(np.float32), cols.weight, n_real=n,
            dim=len(fmap), field=fmat,
        )

    def _load_fast(self) -> IngestResult:
        """Columnar loadFlow over the native parser — same pipeline, same
        results as _load_python, numpy-vectorized end to end."""
        p = self.params
        with obs_span("ingest.parse", split="train", path="native"):
            train = self._parse_cols(
                p.data.train_paths, p.data.train_max_error_tol, is_train=True
            )

        def counts() -> Dict[str, int]:
            c = np.bincount(train.occ_name, minlength=len(train.names))
            return {nm: int(c[i]) for i, nm in enumerate(train.names) if c[i] > 0}

        with obs_span("ingest.dict"):
            fmap = self._resolve_feature_map(counts)

        nodes: Dict[int, TransformNode] = {}
        if p.feature.transform.switch_on:
            nn = len(train.names)
            cnt = np.bincount(train.occ_name, minlength=nn).astype(np.int64)
            s1 = np.bincount(train.occ_name, weights=train.occ_val, minlength=nn)
            s2 = np.bincount(train.occ_name, weights=train.occ_val**2, minlength=nn)
            mn = np.full(nn, math.inf)
            mx = np.full(nn, -math.inf)
            if len(train.occ_name):
                np.minimum.at(mn, train.occ_name, train.occ_val)
                np.maximum.at(mx, train.occ_name, train.occ_val)
            stats = {
                nm: FeatureStat(cnt=int(cnt[i]), sum=float(s1[i]),
                                sum2=float(s2[i]), max=float(mx[i]), min=float(mn[i]))
                for i, nm in enumerate(train.names) if cnt[i] > 0
            }
            nodes = self.nodes_from_stats(stats, fmap)
            if nodes:
                self.write_transform_sidecar(nodes, fmap)

        with obs_span("ingest.materialize", split="train"):
            train_ds = self._cols_to_dataset(train, fmap, nodes)
        obs_inc("ingest.rows", train_ds.n_real)
        test_ds = None
        if p.data.test_paths:
            with obs_span("ingest.parse", split="test", path="native"):
                test = self._parse_cols(
                    p.data.test_paths, p.data.test_max_error_tol, is_train=False
                )
            with obs_span("ingest.materialize", split="test"):
                test_ds = self._cols_to_dataset(test, fmap, nodes)
            obs_inc("ingest.rows", test_ds.n_real)

        # global label stats (CoreData.globalSync y stats)
        K = max(self.n_labels, 2)
        y_real = np.zeros(K, np.int64)
        y_weight = np.zeros(K, np.float64)
        if self.n_labels == 1:
            li = np.trunc(train.y).astype(np.int64)
            valid = (li >= 0) & (li < K)
        else:
            has1 = (train.y == 1.0).any(axis=1)
            li = np.argmax(train.y == 1.0, axis=1)
            valid = has1 & (li >= 0) & (li < K)
        np.add.at(y_real, li[valid], 1)
        np.add.at(y_weight, li[valid], train.weight[valid].astype(np.float64))
        return IngestResult(
            train=train_ds,
            test=test_ds,
            feature_map=fmap,
            transform_nodes=nodes,
            y_real_stat=y_real,
            y_weight_stat=y_weight,
        )
