"""Storage seam — the rebuild of the reference `fs/` package.

All framework I/O (data readers, model dump/load, dict/transform sidecars)
routes through a FileSystem so remote schemes can slot in without touching
callers (reference: fs/IFileSystem.java:35-46, fs/LocalFileSystem.java:39,
factory fs/FileSystemFactory.java:54).
"""

from __future__ import annotations

import contextlib
import os
import glob as _glob
from typing import IO, Iterable, Iterator, List, Sequence


#: marker in the names atomic_open writes before the replace; loaders and
#: the serving fingerprint watcher skip such paths so a tmp file left by a
#: crashed writer is never parsed as model content
TMP_MARKER = ".tmp-"


def is_tmp_path(path: str) -> bool:
    """True for in-flight atomic_open temp files (skip when walking a
    model tree)."""
    return TMP_MARKER in path.rsplit("/", 1)[-1]


class FileSystem:
    """Interface (reference: fs/IFileSystem.java:35-46)."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def open(self, path: str, mode: str = "r") -> IO:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def replace(self, src: str, dst: str) -> None:
        """Move `src` over `dst`, replacing it. Atomic on the local
        filesystem (os.replace); remote schemes degrade to delete+move,
        which is the strongest those stores offer."""
        raise NotImplementedError

    @contextlib.contextmanager
    def atomic_open(self, path: str, mode: str = "w"):
        """Write-then-replace: the file at `path` either keeps its old
        content or carries the complete new content — a reader (e.g. the
        serving registry's fingerprint watcher) can never observe a
        half-written file. On error the temp file is removed and `path`
        is untouched. The commit (replace) rides the `io.dump` retry/
        chaos site: a transient fault at the rename costs a backoff, not
        the checkpoint."""
        from ..resilience import chaos_point, retry_call

        tmp = f"{path}{TMP_MARKER}{os.getpid()}"
        f = self.open(tmp, mode)
        try:
            yield f
        except BaseException:
            f.close()
            try:
                self.delete(tmp)
            # ytklint: allow(broad-except) reason=cleanup of the temp file is best-effort; the original exception below is the failure that matters
            except Exception:
                pass
            raise
        f.close()

        def _commit():
            chaos_point("io.dump")
            self.replace(tmp, path)

        try:
            retry_call(_commit, site="io.dump")
        except BaseException:
            try:
                self.delete(tmp)
            # ytklint: allow(broad-except) reason=cleanup of the temp file is best-effort; the commit failure below is what matters
            except Exception:
                pass
            raise

    def recur_get_paths(self, paths: Sequence[str]) -> List[str]:
        """Expand directories (recursively) and globs into a flat file list
        (reference: IFileSystem.recurGetPaths)."""
        raise NotImplementedError

    # -- line-oriented helpers used by the data layer --------------------

    def read_lines(self, paths: Sequence[str]) -> Iterator[str]:
        """All lines of all files, in sorted-path order. Streaming, with
        each file under the `io.read` retry/chaos site: a transient fault
        (at open or mid-read) reopens that one file and skips the
        already-yielded lines instead of killing the run — no line is
        ever yielded twice and peak memory stays O(one line)
        (resilience.retry.retry_lines)."""
        from ..resilience import chaos_point, retry_lines

        for p in sorted(self.recur_get_paths(paths)):

            def _open(path=p):
                chaos_point("io.read")
                return self.open(path)

            for line in retry_lines(_open, site="io.read"):
                yield line.rstrip("\n")

    def select_read_lines(
        self, paths: Sequence[str], divisor: int, remainder: int
    ) -> Iterator[str]:
        """Line-modulo sharding: keep global line i iff i % divisor == remainder
        — the `lines_avg` worker assignment (reference: IFileSystem.selectRead,
        dataflow/DataFlow.java:405)."""
        for i, line in enumerate(self.read_lines(paths)):
            if i % divisor == remainder:
                yield line


class LocalFileSystem(FileSystem):
    """reference: fs/LocalFileSystem.java:39."""

    def _strip(self, path: str) -> str:
        if path.startswith("file://"):
            path = path[len("file://"):]
        return path

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))

    def open(self, path: str, mode: str = "r") -> IO:
        path = self._strip(path)
        if any(m in mode for m in ("w", "a")):
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
        return open(path, mode)

    def mkdirs(self, path: str) -> None:
        os.makedirs(self._strip(path), exist_ok=True)

    def delete(self, path: str) -> None:
        path = self._strip(path)
        if os.path.isdir(path):
            import shutil

            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def replace(self, src: str, dst: str) -> None:
        dst = self._strip(dst)
        parent = os.path.dirname(os.path.abspath(dst))
        os.makedirs(parent, exist_ok=True)
        os.replace(self._strip(src), dst)

    def recur_get_paths(self, paths: Sequence[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            p = self._strip(p)
            if os.path.isdir(p):
                for root, _dirs, files in os.walk(p):
                    for f in files:
                        out.append(os.path.join(root, f))
            elif os.path.exists(p):
                out.append(p)
            else:
                hits = sorted(_glob.glob(p))
                if not hits:
                    raise FileNotFoundError(p)
                out.extend(hits)
        return out


class FsspecFileSystem(FileSystem):
    """Remote schemes (gs/s3/hdfs/memory/...) via fsspec — the TPU rebuild's
    stand-in for fs/HdfsFileSystem.java:41. Paths may carry the scheme
    prefix or be bare; fsspec normalizes either."""

    def __init__(self, scheme: str):
        import fsspec

        self.scheme = scheme
        self.fs = fsspec.filesystem(scheme)

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def open(self, path: str, mode: str = "r") -> IO:
        if any(m in mode for m in ("w", "a")):
            parent = path.rsplit("/", 1)[0]
            if parent and parent != path:
                try:
                    self.fs.makedirs(parent, exist_ok=True)
                # ytklint: allow(broad-except) reason=fsspec drivers raise driver-specific errors; flat namespaces need no parent dirs and open() surfaces real failures
                except Exception:
                    pass
        return self.fs.open(path, mode)

    def mkdirs(self, path: str) -> None:
        self.fs.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if self.fs.exists(path):
            self.fs.rm(path, recursive=True)

    def replace(self, src: str, dst: str) -> None:
        # remote object stores have no atomic rename; delete+move is the
        # closest equivalent (readers racing this see missing-then-new,
        # never a half-written file, because `src` was written in full)
        if self.fs.exists(dst):
            self.fs.rm(dst)
        self.fs.mv(src, dst)

    def recur_get_paths(self, paths: Sequence[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            if self.fs.isdir(p):
                out.extend(self.fs.find(p))
            elif self.fs.exists(p):
                out.append(p)
            else:
                hits = sorted(self.fs.glob(p))
                if not hits:
                    raise FileNotFoundError(p)
                out.extend(hits)
        return out


def create_filesystem(scheme_or_uri: str = "local") -> FileSystem:
    """Scheme -> FileSystem (reference: fs/FileSystemFactory.java:54).

    `local` / `file` map to LocalFileSystem; any other scheme (gs, s3,
    hdfs, memory, ...) resolves through fsspec when installed."""
    scheme = scheme_or_uri.split("://")[0] if "://" in scheme_or_uri else scheme_or_uri
    scheme = (scheme or "local").lower()
    if scheme in ("local", "file", ""):
        return LocalFileSystem()
    try:
        return FsspecFileSystem(scheme)
    except ImportError as e:
        raise NotImplementedError(
            f"filesystem scheme {scheme!r} needs fsspec (not installed)"
        ) from e
    except ValueError as e:
        raise NotImplementedError(
            f"filesystem scheme {scheme!r} not known to fsspec: {e}"
        ) from e
