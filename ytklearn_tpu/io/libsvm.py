"""libsvm -> ytklearn text converter.

Rebuild of reference utils/LibsvmConvertTool.java:43-155 (+ the
bin/libsvm_convert_2_ytklearn.sh surface): every reference demo dataset
ships as libsvm, so this is the on-ramp for demo-parity runs.

mode: "binary_classification@l0,l1" | "multi_classification@l0,l1,..."
      | "regression"
Lines become `1<x_delim><label><x_delim>name:val,...`; unlabeled lines
(first token contains ':') keep an empty label column.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from ..obs import heartbeat as obs_heartbeat, inc as obs_inc, span as obs_span
from .fs import FileSystem, LocalFileSystem

log = logging.getLogger("ytklearn_tpu.libsvm")


def convert_libsvm(
    mode: str,
    input_path: str,
    output_path: str,
    x_delim: str = "###",
    y_delim: str = ",",
    features_delim: str = ",",
    feature_name_val_delim: str = ":",
    fs: Optional[FileSystem] = None,
) -> int:
    """Convert one libsvm file; returns the number of lines written."""
    fs = fs or LocalFileSystem()
    label_map: Dict[str, int] = {}
    if "classification" in mode:
        head, _, labels = mode.partition("@")
        if not labels:
            raise ValueError(
                f"{head} mode needs labels, e.g. binary_classification@0,1"
            )
        for i, name in enumerate(s.strip() for s in labels.split(y_delim)):
            label_map[name] = i
        if head == "binary_classification" and len(label_map) != 2:
            raise ValueError(f"binary_classification needs 2 labels: {mode}")
    elif not mode.startswith("regression"):
        raise ValueError(f"unsupported mode: {mode}")

    cnt = 0
    kcnt = [0] * max(len(label_map), 1)
    hb = obs_heartbeat("libsvm.convert", every_s=30.0)
    with obs_span("ingest.convert", input=input_path), fs.open(
        output_path, "w"
    ) as out:
        for line in fs.read_lines([input_path]):
            if cnt and cnt & 0xFFFF == 0:
                hb.beat(lines=cnt)
            line = line.strip()
            if not line:
                continue
            info = line.split()
            has_label = ":" not in info[0]
            parts = ["1", ""]
            if has_label:
                if label_map:
                    label = label_map.get(info[0])
                    if label is None:
                        raise ValueError(f"unknown label: {info[0]!r} in {line!r}")
                    parts[1] = str(label)
                    kcnt[label] += 1
                else:
                    parts[1] = str(float(info[0]))
            feats = info[1:] if has_label else info
            kvs = []
            for kv in feats:
                name, _, val = kv.partition(":")
                kvs.append(f"{name}{feature_name_val_delim}{val}")
            out.write(x_delim.join(parts + [features_delim.join(kvs)]) + "\n")
            cnt += 1
    obs_inc("ingest.converted_lines", cnt)
    if label_map:
        log.info("converted %d lines, per-label counts: %s", cnt, kcnt)
    else:
        log.info("converted %d lines", cnt)
    return cnt
