from .fs import FileSystem, LocalFileSystem, create_filesystem
from .feature_hash import FeatureHash, murmur3_x64_128_h1
