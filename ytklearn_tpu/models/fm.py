"""Factorization machine.

Rebuild of reference optimizer/FMHoagOptimizer.java:88 (the O(nk)
sum/sum-of-squares trick) + dataflow/FMModelDataFlow.java (layout
[w1 (n_features)] ++ [V (n_features*k)], V random-init, bias latent zeroed;
model text `name,w,v1,...,vk`).

fx = x·w1 + 0.5 Σ_f [(Σ_j v_jf x_j)^2 - Σ_j (v_jf x_j)^2]; the gradient
falls out of autodiff identically to the reference's closed form. Gradient
masks (first/second order switches, bias latent) are applied by masking the
*weights inside the score*: masked slots start at 0 and their chain-rule
gradient is 0, which reproduces the reference's g[i]=0 zeroing exactly.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..config.params import CommonParams
from ..io.reader import SparseDataset
from .base import ConvexModel, random_init


class FMModel(ConvexModel):
    name = "fm"

    def __init__(self, params: CommonParams, n_features: int):
        super().__init__(params, n_features)
        k = params.k
        if not (isinstance(k, (list, tuple)) and len(k) == 2):
            raise ValueError(f"fm config k must be [first_order(0/1), latent_dim]: {k!r}")
        self.need_first_order = int(k[0]) >= 1
        self.sok = int(k[1])
        self.need_second_order = self.sok > 0
        self.v_start = n_features  # secondOrderIndexStart

    @property
    def dim(self) -> int:
        return self.n_features * (1 + self.sok)

    def regular_blocks(self):
        """Two blocks: first-order (bias excluded) and latent
        (reference: FMHoagOptimizer.getRegularStart/End)."""
        fo_start = 1 if self.params.model.need_bias else 0
        return [(fo_start, self.v_start), (self.v_start, self.dim)]

    def init_weights(self) -> np.ndarray:
        w = np.zeros((self.dim,), np.float32)
        w[self.v_start:] = random_init(self.params, self.dim - self.v_start)
        if self.params.model.need_bias:
            w[self.v_start : self.v_start + self.sok] = 0.0  # bias latent
        return w

    def _apply_mask(self, w):
        """Zero masked weight slices in-graph (static slice bounds, no big
        captured constants); masked slots init at 0 and get 0 gradient via
        the chain rule — reproducing the reference's g[i]=0 zeroing."""
        if not self.need_first_order:
            fo_start = 1 if self.params.model.need_bias else 0
            w = w.at[fo_start : self.v_start].set(0.0)
        if not self.need_second_order:
            w = w.at[self.v_start :].set(0.0)
        elif self.params.model.need_bias and not self.params.bias_need_latent_factor:
            w = w.at[self.v_start : self.v_start + self.sok].set(0.0)
        return w

    def scores(self, w, *xargs):
        idx, val = xargs
        w = self._apply_mask(w)
        wx = jnp.sum(val * w[: self.v_start][idx], axis=-1)
        if not self.need_second_order:
            return wx
        # k-major latent gather: the (k, n, width) intermediate keeps width
        # on the 128-lane axis (pad e.g. 39->128, ~3.3x) instead of k
        # (8->128, 16x) — the k-minor layout is what OOM'd BENCH_r04
        # (f32[2M*39,8] lane-padded to 39.9 GB)
        Vt = w[self.v_start :].reshape(self.n_features, self.sok).T  # (k, nf)
        vx = Vt[:, idx] * val[None]  # (k, n, width)
        S = jnp.sum(vx, axis=-1)  # Σ v x            (k, n)
        S2 = jnp.sum(vx * vx, axis=-1)  # Σ (v x)^2  (k, n)
        return wx + 0.5 * jnp.sum(S * S - S2, axis=0)

    def score_bytes_per_row(self, width: int) -> int:
        wp = -(-width // 128) * 128
        return max(self.sok, 1) * wp * 4

    # -- model text I/O: name,w,v1,...,vk --------------------------------

    def model_line(self, name, i, w, precision, is_bias):
        w = np.asarray(w)
        d = self.params.model.delim
        V = w[self.v_start :].reshape(self.n_features, self.sok)
        lat = d.join(repr(float(v)) for v in V[i])
        return f"{name}{d}{w[i]:f}{d}{lat}"

    def apply_model_line(self, w, gidx, info: Sequence[str]):
        w[gidx] = float(info[1])
        start = self.v_start + gidx * self.sok
        for f in range(self.sok):
            w[start + f] = float(info[2 + f])
