"""Multiclass linear model (softmax / multiclass hinge family).

Rebuild of reference optimizer/MulticlassLinearHoagOptimizer.java:82 +
dataflow/MulticlassLinearModelDataFlow.java (dim = n_features*(K-1), w laid
out feature-major with stride K-1; the K-th class score is implicitly 0).

TPU shape: W viewed as (n_features, K-1); sparse scores are one gather +
einsum over the ELL width, dense scores a single (n, F) @ (F, K-1) MXU
matmul.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..config.params import CommonParams
from ..io.reader import SparseDataset
from .base import ConvexModel


class MulticlassLinearModel(ConvexModel):
    name = "multiclass_linear"

    def __init__(self, params: CommonParams, n_features: int, dense: Optional[bool] = None):
        super().__init__(params, n_features)
        self.K = int(params.k)
        if not self.loss.is_multiclass:
            raise ValueError(
                f"multiclass_linear needs a multiclass loss, got {self.loss.name!r}"
            )
        self.n_labels = self.K
        self.dense = dense if dense is not None else n_features <= 4096

    @property
    def dim(self) -> int:
        return self.n_features * (self.K - 1)

    def regular_blocks(self):
        """Bias block (feature 0's K-1 weights) excluded when need_bias."""
        start = (self.K - 1) if self.params.model.need_bias else 0
        return [(start, self.dim)]

    def make_batch(self, ds: SparseDataset) -> Tuple[np.ndarray, ...]:
        if self.dense:
            X = np.zeros((ds.n, self.n_features), np.float32)
            rows = np.arange(ds.n)[:, None]
            X[rows, ds.idx[:, ::-1]] = ds.val[:, ::-1]
            return (X, ds.y, ds.weight)
        return (ds.idx, ds.val, ds.y, ds.weight)

    def scores(self, w, *xargs):
        """(n, K) scores, last class fixed at 0 (reference keeps wx[K-1]=0)."""
        W = w.reshape(self.n_features, self.K - 1)
        if self.dense:
            (X,) = xargs
            s = X @ W  # (n, K-1)
        else:
            idx, val = xargs
            s = jnp.einsum("nw,nwk->nk", val, W[idx])
        return jnp.concatenate([s, jnp.zeros_like(s[:, :1])], axis=1)

    # -- model text I/O: name,w_0,...,w_{K-2} ----------------------------

    def model_line(self, name, i, w, precision, is_bias):
        W = np.asarray(w).reshape(self.n_features, self.K - 1)
        d = self.params.model.delim
        return name + d + d.join(repr(float(v)) for v in W[i])

    def apply_model_line(self, w, gidx, info: Sequence[str]):
        W = w.reshape(self.n_features, self.K - 1)
        for j in range(self.K - 1):
            W[gidx, j] = float(info[1 + j])
