"""Linear model family — score kernels, Laplace precision, text model I/O.

Rebuild of reference optimizer/LinearHoagOptimizer.java:76-209 (Xv/XTv loss
and grad) + dataflow/LinearModelDataFlow.java:68-199 (model text format).

Two data layouts, chosen by density:
  dense  — X (n, dim) f32: scores = X @ w, an MXU matmul; right for
           low-dim/dense data (Higgs 28 cols, agaricus one-hot).
  sparse — padded ELL idx/val (n, width): scores = Σ_j val·w[idx] (gather);
           right for high-dim CTR-style data where densifying is impossible.
Rows shard over the mesh data axis in both; w stays replicated. All kernels
take data as explicit arguments (never closures) so jitted programs stay
small and cacheable.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.params import CommonParams
from ..io.fs import FileSystem
from ..io.reader import SparseDataset
from ..losses import LossFunction, create_loss
from .base import ConvexModel


def ell_scores(w, idx, val):
    """Xv for padded-ELL rows (reference: LinearHoagOptimizer.Xv:76-87).
    Padding slots (idx=0, val=0) contribute nothing."""
    return jnp.sum(val * w[idx], axis=-1)


class LinearModel(ConvexModel):
    """score = x·w (bias folded in as feature 0)."""

    name = "linear"

    def __init__(
        self,
        params: CommonParams,
        dim: int,
        loss: Optional[LossFunction] = None,
        dense: Optional[bool] = None,
    ):
        super().__init__(params, dim)
        if loss is not None:
            self.loss = loss
        # densify when the matrix is small enough to be an MXU win
        self.dense = dense if dense is not None else dim <= 4096

    @property
    def dim(self) -> int:
        return self.n_features

    def regular_blocks(self):
        start, end = self.regular_range()
        return [(start, end)]

    # -- batches ---------------------------------------------------------

    def make_batch(self, ds: SparseDataset) -> Tuple[np.ndarray, ...]:
        """Host arrays for this model's kernels; all shard on rows (dim 0)."""
        if self.dense:
            X = np.zeros((ds.n, self.dim), np.float32)
            rows = np.arange(ds.n)[:, None]
            # reversed slot order: trailing ELL padding (idx 0, val 0) is
            # written before the real slot-0 entry, so it can't clobber it
            X[rows, ds.idx[:, ::-1]] = ds.val[:, ::-1]
            return (X, ds.y, ds.weight)
        return (ds.idx, ds.val, ds.y, ds.weight)

    # -- optimization surface -------------------------------------------

    def regular_range(self) -> Tuple[int, int]:
        """L1/L2 apply to [start, dim): bias excluded
        (reference: LinearHoagOptimizer.getRegularStart/End)."""
        return (1 if self.params.model.need_bias else 0), self.dim

    def scores(self, w, *xargs):
        if self.dense:
            (X,) = xargs
            return X @ w
        idx, val = xargs
        return ell_scores(w, idx, val)

    def precision(self, w, *batch, l2_vec, g_weight):
        """Laplace diagonal precision for Thompson-sampling predictors
        (reference: LinearHoagOptimizer.calPrecision:179 — bias slot skipped,
        + total_weight * l2)."""
        *xargs, y, weight = batch
        score = self.scores(w, *xargs)
        D = self.loss.second_derivative(score, y)
        if self.dense:
            (X,) = xargs
            prec = (weight * D) @ (X * X)
            if self.params.model.need_bias:
                prec = prec.at[0].set(0.0)
        else:
            idx, val = xargs
            contrib = (weight * D)[:, None] * (val * val)  # (n, width)
            if self.params.model.need_bias:
                contrib = jnp.where(idx == 0, 0.0, contrib)
            prec = jnp.zeros((self.dim,), jnp.float32).at[idx].add(contrib)
        return prec + g_weight * l2_vec

    # -- model text I/O --------------------------------------------------

    def dump_model(
        self,
        fs: FileSystem,
        w: np.ndarray,
        precision: Optional[np.ndarray],
        feature_map: Dict[str, int],
        rank: int = 0,
        n_parts: int = 1,
    ) -> None:
        """`<model_dir>/model-%05d` + `<model_dir>_dict/dict-%05d`
        (reference: LinearModelDataFlow.dumpModel:133-199). Nonzero weights
        only; bias always written with precision "null"."""
        p = self.params.model
        w = np.asarray(w)
        avg = self.dim // n_parts
        start = rank * avg
        end = self.dim if rank == n_parts - 1 else (rank + 1) * avg
        d = p.delim
        model_path = f"{p.data_path}/model-{rank:05d}"
        dict_path = f"{p.data_path}_dict/dict-{rank:05d}"
        model_lines = []
        dict_lines = []
        for name, i in feature_map.items():
            if not (start <= i < end):
                continue
            if name.lower() == p.bias_feature_name.lower():
                model_lines.append(f"{name}{d}{w[i]:f}{d}null\n")
                continue
            if abs(w[i]) <= 0.0:
                continue
            prec = precision[i] if precision is not None else 0.0
            model_lines.append(f"{name}{d}{w[i]:f}{d}{prec:f}\n")
            dict_lines.append(f"{name}\n")
        # sidecar digest stamp BEFORE the model text lands (models/base.py)
        self._stamp_transform_sidecar(fs, "".join(model_lines), rank, n_parts)
        with fs.atomic_open(model_path) as mf, fs.atomic_open(dict_path) as df:
            mf.writelines(model_lines)
            df.writelines(dict_lines)

    def load_model(
        self, fs: FileSystem, feature_map: Dict[str, int]
    ) -> Optional[np.ndarray]:
        """Read `name,weight[,precision]` lines from all model parts
        (reference: LinearModelDataFlow.loadModel:68-110). Unknown names are
        skipped; absent file -> None (fresh model)."""
        from ..io.fs import is_tmp_path

        p = self.params.model
        if not fs.exists(p.data_path):
            return None
        w = np.zeros((self.dim,), np.float32)
        for path in sorted(fs.recur_get_paths([p.data_path])):
            if is_tmp_path(path):
                continue  # in-flight atomic_open temp from a writer
            with fs.open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    info = line.split(p.delim)
                    if len(info) < 2:
                        continue
                    gidx = feature_map.get(info[0])
                    if gidx is not None:
                        w[gidx] = float(info[1])
        return w
