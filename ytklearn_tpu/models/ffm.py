"""Field-aware factorization machine.

Rebuild of reference optimizer/FFMHoagOptimizer.java:90 +
dataflow/FFMModelDataFlow.java (dim = n + n*F*k, V[feat, field, f]; x stores
(featIdx, val, fieldIdx) triples; field = feature-name prefix before
field_delim, mapped through model.field_dict_path).

TPU-first pairwise formulation: instead of the reference's O(width^2 * k)
per-row double loop, aggregate per *field pair*:
    T[a, b, :] = Σ_{p: field_p = a} val_p · V[feat_p, b, :]      (n, F, F, k)
    fx = x·w1 + 0.5 ( Σ_{a,b} T[a,b]·T[b,a]  -  Σ_p val_p² |V[feat_p, field_p]|² )
The T build is an einsum (MXU) over the one-hot field matrix; memory is
n·F²·k instead of n·width²·k, and F (field count) is small.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..config.params import CommonParams
from ..io.fs import FileSystem
from ..io.reader import SparseDataset
from .base import ConvexModel, random_init


def load_field_dict(fs: FileSystem, path: str) -> Dict[str, int]:
    """field name -> index, file line order (reference:
    FFMModelDataFlow.java:234-241)."""
    fmap: Dict[str, int] = {}
    with fs.open(path) as f:
        for line in f:
            name = line.strip()
            if name and name not in fmap:
                fmap[name] = len(fmap)
    return fmap


class FFMModel(ConvexModel):
    name = "ffm"

    def __init__(self, params: CommonParams, n_features: int, n_fields: int):
        super().__init__(params, n_features)
        k = params.k
        if not (isinstance(k, (list, tuple)) and len(k) == 2):
            raise ValueError(f"ffm config k must be [first_order(0/1), latent_dim]: {k!r}")
        self.need_first_order = int(k[0]) >= 1
        self.sok = int(k[1])
        self.need_second_order = self.sok > 0
        self.n_fields = n_fields
        self.v_start = n_features

    @property
    def dim(self) -> int:
        return self.n_features * (1 + self.n_fields * self.sok)

    def regular_blocks(self):
        fo_start = 1 if self.params.model.need_bias else 0
        return [(fo_start, self.v_start), (self.v_start, self.dim)]

    def init_weights(self) -> np.ndarray:
        w = np.zeros((self.dim,), np.float32)
        w[self.v_start:] = random_init(self.params, self.dim - self.v_start)
        if self.params.model.need_bias:
            stride = self.n_fields * self.sok
            w[self.v_start : self.v_start + stride] = 0.0
        return w

    def _apply_mask(self, w):
        """Zero masked weight slices in-graph (see FMModel._apply_mask)."""
        if not self.need_first_order:
            fo_start = 1 if self.params.model.need_bias else 0
            w = w.at[fo_start : self.v_start].set(0.0)
        if not self.need_second_order:
            w = w.at[self.v_start :].set(0.0)
        elif self.params.model.need_bias and not self.params.bias_need_latent_factor:
            stride = self.n_fields * self.sok
            w = w.at[self.v_start : self.v_start + stride].set(0.0)
        return w

    def make_batch(self, ds: SparseDataset) -> Tuple[np.ndarray, ...]:
        if ds.field is None:
            raise ValueError("FFM requires a dataset ingested with a field map")
        return (ds.idx, ds.val, ds.field, ds.y, ds.weight)

    def scores(self, w, *xargs):
        idx, val, field = xargs
        w = self._apply_mask(w)
        wx = jnp.sum(val * w[: self.v_start][idx], axis=-1)
        if not self.need_second_order:
            return wx
        F, k = self.n_fields, self.sok
        V = w[self.v_start :].reshape(self.n_features, F, k)
        Vr = V[idx]  # (n, width, F, k)
        onehot = jnp.asarray(field[..., None] == jnp.arange(F), val.dtype)  # (n, w, F)
        # T[a, b] = Σ_p [field_p = a] val_p Vr[p, b]
        T = jnp.einsum("nwa,nwbk->nabk", onehot * val[..., None], Vr)
        cross = jnp.einsum("nabk,nbak->n", T, T)
        # diagonal correction: p = q terms, each = val_p^2 |V[feat_p, field_p]|^2
        own = jnp.take_along_axis(
            Vr, field[..., None, None].astype(jnp.int32), axis=2
        )[:, :, 0, :]  # (n, width, k)
        diag = jnp.sum((val * val) * jnp.sum(own * own, axis=-1), axis=-1)
        return wx + 0.5 * (cross - diag)

    def score_bytes_per_row(self, width: int) -> int:
        """Dominant per-row intermediates: the latent gather (width, F, k)
        and the field-pair tensor (F, F, k), both k-minor (pad k->128)."""
        F, kp = self.n_fields, -(-max(self.sok, 1) // 128) * 128
        Fp = -(-F // 8) * 8
        return (width * Fp + F * Fp) * kp * 4

    # -- model text I/O: name,w,v[field0 k..],v[field1 k..],... ----------

    def model_line(self, name, i, w, precision, is_bias):
        w = np.asarray(w)
        d = self.params.model.delim
        stride = self.n_fields * self.sok
        lat = w[self.v_start + i * stride : self.v_start + (i + 1) * stride]
        return f"{name}{d}{w[i]:f}{d}" + d.join(repr(float(v)) for v in lat)

    def apply_model_line(self, w, gidx, info: Sequence[str]):
        w[gidx] = float(info[1])
        stride = self.n_fields * self.sok
        start = self.v_start + gidx * stride
        for f in range(min(stride, len(info) - 2)):
            w[start + f] = float(info[2 + f])
