from .base import ConvexModel, random_init
from .linear import LinearModel
from .multiclass import MulticlassLinearModel
from .fm import FMModel
from .ffm import FFMModel, load_field_dict
