from .linear import LinearModel
