"""Gradient-boosted soft trees — gbmlr / gbsdt / gbhmlr / gbhsdt.

Rebuild of reference optimizer/GBMLRHoagOptimizer.java:130,
GBSDTHoagOptimizer.java:135, GBHMLRHoagOptimizer.java:136,
GBHSDTHoagOptimizer.java:142 + dataflow/GBMLRDataFlow.java (z-accumulation,
per-tree random init, instance/feature Bernoulli masks, tree-%05d model
text) + operation/GBMLROperation.java:39-124 (boosting outer loop).

One "tree" = a soft mixture: K experts gated by either a flat softmax
(gbmlr/gbsdt) or a complete-binary-tree of sigmoids (gbhmlr/gbhsdt, heap
layout — leaf prob is the product of gate probs along the root path).
Experts are per-feature linear functions (gbmlr/gbhmlr; stride 2K-1 per
feature = K-1 gates + K experts) or K global scalars (gbsdt/gbhsdt;
dim = K + n_features*(K-1)).

fx = z + Σ_p π_p(x)·expert_p(x)   (z = accumulated previous trees; RF: 0)
All four gradients fall out of autodiff; the reference's feature-mask
g[i]=0 zeroing is reproduced by multiplying gate weights with the mask
inside the score (chain rule zeroes the same slots).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.params import CommonParams
from ..io.fs import FileSystem
from .base import ConvexModel, random_init


def heap_leaf_probs(sig):
    """Leaf probabilities from (n, K-1) heap-ordered internal sigmoid gates
    (P(left child) = sigmoid; reference: GBHMLRHoagOptimizer mu/gx loop,
    same heap convention as loss/HSoftmaxFunction.java)."""
    K = sig.shape[-1] + 1
    level = jnp.ones(sig.shape[:-1] + (1,), sig.dtype)
    for _ in range(int(math.log2(K))):
        n = level.shape[-1]
        gates = jax.lax.dynamic_slice_in_dim(sig, n - 1, n, axis=-1)
        level = jnp.stack([level * gates, level * (1.0 - gates)], axis=-1).reshape(
            sig.shape[:-1] + (2 * n,)
        )
    return level


class GBSTModel(ConvexModel):
    """All four GBST variants; `variant` picks layout + gating."""

    def __init__(self, params: CommonParams, n_features: int, variant: str):
        super().__init__(params, n_features)
        assert variant in ("gbmlr", "gbsdt", "gbhmlr", "gbhsdt")
        self.variant = variant
        self.K = int(params.k)
        self.hier = variant in ("gbhmlr", "gbhsdt")
        self.scalar_leaves = variant in ("gbsdt", "gbhsdt")
        if self.hier and (self.K & (self.K - 1)) != 0:
            raise ValueError(f"{variant} requires K a power of two, got {self.K}")
        self.is_rf = params.gbst_type == "random_forest"
        self.name = variant

    # -- layout ----------------------------------------------------------

    @property
    def dim(self) -> int:
        K = self.K
        if self.scalar_leaves:
            return K + self.n_features * (K - 1)
        return self.n_features * (2 * K - 1)

    def regular_blocks(self):
        K = self.K
        bias = self.params.model.need_bias
        if self.scalar_leaves:
            # leaf block + gates (bias feature's gates excluded)
            # (reference: GBSDTHoagOptimizer.getRegularStart/End)
            return [(0, K), ((2 * K - 1) if bias else K, self.dim)]
        return [((2 * K - 1) if bias else 0, self.dim)]

    def init_weights(self, tree_seed: int = 0) -> np.ndarray:
        """Per-tree random re-init (reference: GBMLRDataFlow.initW /
        GBSDTDataFlow.initW — bias blocks zeroed; gbsdt leaves uniform in
        leaf_random_init_range)."""
        p = self.params
        K = self.K
        rng_params = p.random
        seed = rng_params.seed + tree_seed
        rng = np.random.RandomState(seed)

        def rand(size):
            if rng_params.mode == "uniform":
                return rng.uniform(
                    rng_params.uniform_range_start, rng_params.uniform_range_end, size
                ).astype(np.float32)
            return (rng.randn(size) * rng_params.normal_std + rng_params.normal_mean).astype(
                np.float32
            )

        w = rand(self.dim)
        if self.scalar_leaves:
            lo, hi = p.leaf_random_init_range
            w[:K] = rng.uniform(lo, hi, K).astype(np.float32)
            if p.model.need_bias:
                w[K : 2 * K - 1] = 0.0  # bias feature's gates
        else:
            if p.model.need_bias:
                w[: 2 * K - 1] = 0.0  # bias feature's whole block
        return w

    #: boost.py batch layout (idx, val, z, gate_mask, y, weight) — the gate
    #: mask is per-feature, not per-row
    batch_row_mask = (True, True, True, False, True, True)

    def score_bytes_per_row(self, width: int) -> int:
        """Dominant per-row intermediate: the (width, 2K-1) weight gather
        (k-minor, pads 2K-1 -> 128)."""
        wp = -(-width // 8) * 8
        stride = 2 * self.K - 1 if not self.scalar_leaves else self.K - 1
        return wp * (-(-stride // 128) * 128) * 4

    # -- kernels ---------------------------------------------------------

    def tree_output(self, w, idx, val, gate_mask):
        """Current tree's output fx_tree(x) (no z). gate_mask is the
        per-feature Bernoulli mask (n_features,) f32 — multiplied into gate
        weights so masked features neither contribute nor get gradients."""
        K = self.K
        gm = gate_mask[idx]  # (n, width)
        if self.scalar_leaves:
            U = w[K:].reshape(self.n_features, K - 1)
            gate_in = jnp.einsum("nw,nwk->nk", val * gm, U[idx])
            experts = w[:K]  # scalar leaves, broadcast
            pi = self._gate_probs(gate_in)
            return pi @ experts
        W = w.reshape(self.n_features, 2 * K - 1)
        Wr = W[idx]  # (n, width, 2K-1)
        gate_in = jnp.einsum("nw,nwk->nk", val * gm, Wr[..., : K - 1])
        experts = jnp.einsum("nw,nwk->nk", val, Wr[..., K - 1 :])  # (n, K)
        pi = self._gate_probs(gate_in)
        return jnp.sum(pi * experts, axis=-1)

    def _gate_probs(self, gate_in):
        """(n, K-1) gate logits -> (n, K) mixture probabilities."""
        if self.hier:
            return heap_leaf_probs(jax.nn.sigmoid(gate_in))
        # softmax over [logits, 0] (reference appends implicit 0)
        z = jnp.concatenate([gate_in, jnp.zeros_like(gate_in[:, :1])], axis=1)
        return jax.nn.softmax(z, axis=-1)

    def scores(self, w, *xargs):
        idx, val, z, gate_mask = xargs
        fx = self.tree_output(w, idx, val, gate_mask)
        # GB: loss at z + tree; RF: tree alone (reference fx init)
        return fx if self.is_rf else z + fx

    def rf_predict_scores(self, w, idx, val, z, gate_mask, tree_num):
        """RF: averaged ensemble score (reference (z+fx)/treeNum)."""
        fx = self.tree_output(w, idx, val, gate_mask)
        return (z + fx) / tree_num

    # -- model text I/O (per tree) ---------------------------------------
    # reference: GBMLRDataFlow.dumpModel — tree-%05d/model-%05d with a
    # leading "k:K" line, per-feature `name,v0,...,v_{stride-1},` (trailing
    # delim), masked gate weights dumped as literal 0.0

    def dump_tree(
        self,
        fs: FileSystem,
        w: np.ndarray,
        gate_mask: np.ndarray,
        feature_map: Dict[str, int],
        tree_id: int,
        rank: int = 0,
    ) -> None:
        p = self.params.model
        K = self.K
        d = p.delim
        w = np.asarray(w)
        path = f"{p.data_path}/tree-{tree_id:05d}/model-{rank:05d}"
        dict_path = f"{p.data_path}_dict/dict-{rank:05d}"
        with fs.atomic_open(path) as mf, fs.atomic_open(dict_path) as df:
            mf.write(f"k:{K}\n")
            if self.scalar_leaves:
                # bare leaf-value line right after the header
                # (reference: GBSDTDataFlow.dumpModel leafsb)
                mf.write(d.join(repr(float(v)) for v in w[:K]) + "\n")
            for name, i in feature_map.items():
                is_bias = name.lower() == p.bias_feature_name.lower()
                if self.scalar_leaves:
                    vals = list(w[K + i * (K - 1) : K + (i + 1) * (K - 1)])
                    if not is_bias and gate_mask[i] == 0:
                        vals = [0.0] * (K - 1)
                else:
                    stride = 2 * K - 1
                    vals = list(w[i * stride : (i + 1) * stride])
                    if not is_bias and gate_mask[i] == 0:
                        vals[: K - 1] = [0.0] * (K - 1)
                mf.write(name + d + d.join(repr(float(v)) for v in vals) + d + "\n")
                if not is_bias:
                    df.write(name + "\n")

    def load_tree(
        self, fs: FileSystem, feature_map: Dict[str, int], tree_id: int
    ) -> Optional[np.ndarray]:
        p = self.params.model
        K = self.K
        tree_dir = f"{p.data_path}/tree-{tree_id:05d}"
        if not fs.exists(tree_dir):
            return None
        from ..io.fs import is_tmp_path

        w = np.zeros((self.dim,), np.float32)
        for path in sorted(fs.recur_get_paths([tree_dir])):
            if is_tmp_path(path):
                continue  # in-flight atomic_open temp from a writer
            with fs.open(path) as f:
                expect_leaves = False
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if line.startswith("k:"):
                        expect_leaves = self.scalar_leaves
                        continue
                    info = [s for s in line.split(p.delim) if s != ""]
                    if expect_leaves:
                        # bare leaf line follows the k: header (GBSDT family)
                        w[:K] = [float(v) for v in info[:K]]
                        expect_leaves = False
                        continue
                    gidx = feature_map.get(info[0])
                    if gidx is None:
                        continue
                    if self.scalar_leaves:
                        start = K + gidx * (K - 1)
                        for j in range(K - 1):
                            w[start + j] = float(info[1 + j])
                    else:
                        stride = 2 * K - 1
                        for j in range(stride):
                            w[gidx * stride + j] = float(info[1 + j])
        return w

    def dump_tree_info(self, fs: FileSystem, finished: int, base_score: float) -> None:
        """reference: GBMLRDataFlow.dumpModelInfo."""
        p = self.params
        with fs.atomic_open(f"{p.model.data_path}/tree-info") as f:
            f.write(f"K:{self.K}\n")
            f.write(f"tree_num:{p.tree_num}\n")
            f.write(f"finished_tree_num:{finished}\n")
            f.write(f"uniform_base_prediction:{base_score}\n")

    def load_tree_info(self, fs: FileSystem) -> Optional[Dict[str, float]]:
        path = f"{self.params.model.data_path}/tree-info"
        if not fs.exists(path):
            return None
        out: Dict[str, float] = {}
        with fs.open(path) as f:
            for line in f:
                if ":" in line:
                    k, v = line.strip().split(":", 1)
                    out[k] = float(v)
        return out
