"""Shared scaffolding for the convex model family.

Each model supplies: flat-weight layout (+ init / grad masks), a pure-jnp
weighted-sum loss over its batch arrays, predictions, reg-range vectors, and
reference-compatible text model I/O. The optimizer (optimize/lbfgs.py) and
trainer (train.py) are model-agnostic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..config.params import CommonParams
from ..io.fs import FileSystem
from ..io.reader import SparseDataset
from ..losses import create_loss


def random_init(params: CommonParams, size: int) -> np.ndarray:
    """Latent-factor init (reference: utils/RandomParamsUtils.java:37,
    param/RandomParams.java — normal(mean, std) or uniform[a, b))."""
    r = params.random
    rng = np.random.RandomState(r.seed)
    if r.mode == "uniform":
        return rng.uniform(
            r.uniform_range_start, r.uniform_range_end, size
        ).astype(np.float32)
    return (rng.randn(size) * r.normal_std + r.normal_mean).astype(np.float32)


class ConvexModel:
    """Base for L-BFGS-trained models."""

    name = "base"
    n_labels = 1  # K for multiclass families

    def __init__(self, params: CommonParams, n_features: int):
        self.params = params
        self.n_features = n_features
        self.loss = create_loss(params.loss.loss_function)

    # layout ------------------------------------------------------------
    @property
    def dim(self) -> int:
        raise NotImplementedError

    def init_weights(self) -> np.ndarray:
        return np.zeros((self.dim,), np.float32)

    def regular_blocks(self) -> List[Tuple[int, int]]:
        """[(start, end)] ranges regularized by l1[r]/l2[r]
        (reference: HoagOptimizer.getRegularStart/End overrides)."""
        raise NotImplementedError

    def reg_vectors(self, l1, l2) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Per-index reg coefficient vectors from the per-block l1/l2 lists
        (scalars broadcast to every block)."""
        blocks = self.regular_blocks()
        l1s = list(np.broadcast_to(np.atleast_1d(l1), (len(blocks),)))
        l2s = list(np.broadcast_to(np.atleast_1d(l2), (len(blocks),)))
        l1v = np.zeros((self.dim,), np.float32)
        l2v = np.zeros((self.dim,), np.float32)
        for (s, e), a, b in zip(blocks, l1s, l2s):
            l1v[s:e] = a
            l2v[s:e] = b
        return jnp.asarray(l1v), jnp.asarray(l2v)

    # batches ------------------------------------------------------------
    #: which make_batch elements are row-aligned (None = all); models with
    #: broadcast batch elements (e.g. the GBST gate mask) override this so
    #: blocked evaluation (optimize/blocked.py) chunks only row arrays
    batch_row_mask: Optional[Tuple[bool, ...]] = None

    def make_batch(self, ds: SparseDataset) -> Tuple[np.ndarray, ...]:
        """(idx, val, y, weight) padded-ELL by default; all arrays row-shard."""
        return (ds.idx, ds.val, ds.y, ds.weight)

    def score_bytes_per_row(self, width: int) -> int:
        """Approximate padded bytes of per-row score intermediates under the
        TPU (8,128) tiled layout — drives row-chunk selection. Subclasses
        with latent gathers (FM/FFM/GBST) override with their real cost."""
        return -(-width // 128) * 128 * 4

    def suggest_row_chunk(
        self, n_rows: int, width: int, n_shards: int = 1
    ) -> Optional[int]:
        """Row chunk for blocked loss/grad/score evaluation, or None when
        the whole batch fits the budget (the reference's blocked-CoreData
        contract, dataflow/CoreData.java:51-52; env overrides YTK_ROW_CHUNK
        / YTK_CHUNK_BUDGET_MB). `n_shards`: mesh shard count — the chunk
        decision is per-shard (each shard scans only its rows)."""
        from ..optimize.blocked import suggest_chunk

        # x4: forward intermediate + its backward cotangents/temps
        return suggest_chunk(
            n_rows, 4 * self.score_bytes_per_row(width), n_shards=n_shards
        )

    # kernels ------------------------------------------------------------
    def pure_loss(self, w, *batch):
        """Weighted-sum data loss; zero-weight padding rows masked via where
        (inf*0 from e.g. mape on padded labels must not NaN the sum)."""
        *xargs, y, weight = batch
        scores = self.scores(w, *xargs)
        # loss() reduces multiclass trailing axes, so per_row is always (n,)
        per_row = jnp.where(weight > 0, self.loss.loss(scores, y), 0.0)
        return jnp.sum(weight * per_row)

    def scores(self, w, *xargs):
        raise NotImplementedError

    def predicts(self, w, *batch):
        *xargs, _y, _w = batch
        return self.loss.predict(self.scores(w, *xargs))

    # model I/O ----------------------------------------------------------
    def _part_paths(self, rank: int) -> Tuple[str, str]:
        p = self.params.model
        return (
            f"{p.data_path}/model-{rank:05d}",
            f"{p.data_path}_dict/dict-{rank:05d}",
        )

    def _feature_slice(self, rank: int, n_parts: int) -> Tuple[int, int]:
        avg = self.n_features // n_parts
        start = rank * avg
        end = self.n_features if rank == n_parts - 1 else (rank + 1) * avg
        return start, end

    def dump_model(
        self,
        fs: FileSystem,
        w: np.ndarray,
        precision: Optional[np.ndarray],
        feature_map: Dict[str, int],
        rank: int = 0,
        n_parts: int = 1,
    ) -> None:
        """Per-feature text lines; subclasses supply model_line(). Both
        files land via atomic write-then-replace so the serving registry's
        fingerprint watcher never parses a half-written dump. The model
        text is built first so the transform-stat sidecar can be stamped
        with its digest BEFORE the model lands (transform/sidecar.py —
        a crash between the writes is detected at serve load)."""
        p = self.params.model
        start, end = self._feature_slice(rank, n_parts)
        model_path, dict_path = self._part_paths(rank)
        model_lines: List[str] = []
        dict_lines: List[str] = []
        for name, i in feature_map.items():
            if not (start <= i < end):
                continue
            is_bias = name.lower() == p.bias_feature_name.lower()
            line = self.model_line(name, i, w, precision, is_bias)
            if line is None:
                continue
            model_lines.append(line + "\n")
            if not is_bias:
                dict_lines.append(name + "\n")
        self._stamp_transform_sidecar(fs, "".join(model_lines), rank, n_parts)
        with fs.atomic_open(model_path) as mf, fs.atomic_open(dict_path) as df:
            mf.writelines(model_lines)
            df.writelines(dict_lines)

    def _stamp_transform_sidecar(
        self, fs: FileSystem, model_text: str, rank: int, n_parts: int
    ) -> None:
        """Embed a digest of the model text about to land in the
        transform-stat sidecar (single-part rank0 dumps only — the
        production convex path; multi-part digests would need text from
        every rank, so those sidecars stay digestless and load like
        legacy ones)."""
        if rank != 0 or n_parts != 1:
            return
        if not self.params.feature.transform.switch_on:
            return
        from ..transform.sidecar import model_text_digest, stamp_sidecar_digest

        side = self.params.model.data_path + "_feature_transform_stat"
        stamp_sidecar_digest(fs, side, model_text_digest(model_text))

    def model_line(
        self, name: str, i: int, w: np.ndarray, precision, is_bias: bool
    ) -> Optional[str]:
        raise NotImplementedError

    def load_model(
        self, fs: FileSystem, feature_map: Dict[str, int]
    ) -> Optional[np.ndarray]:
        from ..io.fs import is_tmp_path

        p = self.params.model
        if not fs.exists(p.data_path):
            return None
        w = self.init_weights()
        for path in sorted(fs.recur_get_paths([p.data_path])):
            if is_tmp_path(path):
                continue  # in-flight atomic_open temp from a writer
            with fs.open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    info = line.split(p.delim)
                    if len(info) < 2:
                        continue
                    gidx = feature_map.get(info[0])
                    if gidx is not None:
                        self.apply_model_line(w, gidx, info)
        return w

    def apply_model_line(self, w: np.ndarray, gidx: int, info: Sequence[str]):
        raise NotImplementedError
