"""The one vectorized transform path for train, offline predict, and serve.

Rebuild of the reference front door — the feature-preprocessing layer
(reference: dataflow feature transform + FeatureHash, PAPER.md §L3/L7) —
as a single batched implementation shared by every consumer:

* ingest (`io/reader.py::to_dataset` / `_cols_to_dataset`) replays
  TransformNode normalization over the materialized columns,
* offline predictors (`predict/continuous.py::_prep`) route each row's
  bias-drop → murmur-hash → replay through `prep_row`,
* the serving ladder (`serve/scorer.py::featurize`) assembles raw
  named-feature dicts straight into the dense `(B, dim)` scoring matrix
  with `featurize` — vector assembly against the model vocab, signed
  hash-collision accumulation, missing-value fill, and normalization
  replay as one numpy batch stage instead of a per-scalar host loop.

Because all three call the same `apply_nodes` kernel, train/serve skew
is structurally impossible: there is no second implementation to drift.

Semantics pinned bit-for-bit against the scalar reference
(`TransformNode.transform`, `ContinuousPredictor._transform`) by
tests/test_transform.py:

* standardization: ``(val - mean) / stdvar`` unless ``stdvar < 1e-6``
  (identity);
* scale_range: ``rmin + (rmax - rmin) * ((val - min) / (max - min))``,
  or ``1.0`` when ``|max - min| < 1e-6``;
* predict/serve only (``nodeless_zero``): when the transform switch is
  on, a present feature WITHOUT a stat node maps to 0.0 (reference:
  ContinuousOnlinePredictor.transform:135-143). Ingest keeps raw values
  for node-less (e.g. excluded) features — reference DataFlow behavior.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import knobs
from ..io.feature_hash import FeatureHash

__all__ = ["TransformTable", "apply_nodes", "TransformPipeline"]


@dataclass
class TransformTable:
    """TransformNode fields as dense per-index lookup arrays.

    Row semantics depend on the builder: per-global-feature-index for
    ingest (`from_indexed`), per-vocab-column for serve (`from_vocab`),
    or per-node with a row-0 no-node sentinel for the predictors'
    name-keyed path (`from_named`)."""

    has: np.ndarray  # bool — a stat node exists for this index
    is_std: np.ndarray  # bool — mode == standardization
    mean: np.ndarray
    std: np.ndarray
    mn: np.ndarray
    mx: np.ndarray
    rmin: np.ndarray
    rmax: np.ndarray

    @classmethod
    def zeros(cls, dim: int) -> "TransformTable":
        return cls(
            has=np.zeros(dim, bool),
            is_std=np.zeros(dim, bool),
            mean=np.zeros(dim),
            std=np.zeros(dim),
            mn=np.zeros(dim),
            mx=np.zeros(dim),
            rmin=np.zeros(dim),
            rmax=np.zeros(dim),
        )

    def set_node(self, i: int, node) -> None:
        self.has[i] = True
        self.is_std[i] = node.mode == "standardization"
        self.mean[i], self.std[i] = node.mean, node.stdvar
        self.mn[i], self.mx[i] = node.min, node.max
        self.rmin[i], self.rmax[i] = node.range_min, node.range_max

    @classmethod
    def from_indexed(cls, nodes: Dict[int, object], dim: int) -> "TransformTable":
        """Ingest layout: one row per global feature index."""
        t = cls.zeros(dim)
        for g, node in nodes.items():
            t.set_node(g, node)
        return t

    @classmethod
    def from_named(
        cls, nodes: Dict[str, object]
    ) -> Tuple["TransformTable", Dict[str, int]]:
        """Predictor layout: one row per node plus a row-0 "no node"
        sentinel; the returned index maps name -> row (missing -> 0)."""
        t = cls.zeros(len(nodes) + 1)
        index: Dict[str, int] = {}
        for i, (name, node) in enumerate(nodes.items(), start=1):
            index[name] = i
            t.set_node(i, node)
        return t, index

    @classmethod
    def from_vocab(
        cls, nodes: Dict[str, object], vocab: Dict[str, int], dim: int
    ) -> "TransformTable":
        """Serve layout: one row per scoring column (model vocab order);
        sidecar names absent from the vocab are irrelevant (those
        features are dropped by assembly before replay)."""
        t = cls.zeros(max(dim, 1))
        for name, node in nodes.items():
            col = vocab.get(name)
            if col is not None:
                t.set_node(col, node)
        return t


def apply_nodes(
    table: TransformTable,
    gi: np.ndarray,
    val: np.ndarray,
    nodeless_zero: bool = False,
) -> np.ndarray:
    """Vectorized TransformNode replay — THE transform implementation.

    ``gi`` indexes rows of ``table``; ``val`` is float64. Returns the
    transformed values (float64). ``nodeless_zero`` selects the
    predict/serve semantic (no-node features -> 0.0); ingest passes
    False so excluded features keep their raw values."""
    h = table.has[gi]
    stdv = table.std[gi]
    std_ok = table.is_std[gi] & (stdv >= 1e-6)
    val = np.where(
        h & std_ok,
        (val - table.mean[gi]) / np.where(stdv == 0, 1, stdv),
        val,
    )
    span = table.mx[gi] - table.mn[gi]
    small = np.abs(span) < 1e-6
    # a * (b / c) association, matching the scalar TransformNode.transform
    # exactly (bit-equality pinned by tests/test_transform.py)
    scaled = np.where(
        small,
        1.0,
        table.rmin[gi]
        + (table.rmax[gi] - table.rmin[gi])
        * ((val - table.mn[gi]) / np.where(small, 1, span)),
    )
    val = np.where(h & ~table.is_std[gi], scaled, val)
    if nodeless_zero:
        val = np.where(h, val, 0.0)
    return val


class TransformPipeline:
    """Batched raw-features front door for one loaded model.

    Two modes share the class:

    * full (convex/GBST families): bias-name drop, murmur feature
      hashing with signed collision accumulation, vocab assembly,
      missing fill, TransformNode replay;
    * identity (GBDT): raw values scattered against the vocab with the
      missing fill (NaN routes a row to the split's default child) —
      no hashing, no replay.

    `featurize` (serve) and `prep_row` (offline predictors) reproduce
    the legacy per-scalar `_prep` results bit-for-bit; unknown features
    (no vocab column after hashing) drop exactly like the host walk,
    and a non-numeric value is tolerated only on a dropped feature — a
    kept feature's bad value still raises."""

    def __init__(
        self,
        *,
        vocab: Optional[Dict[str, int]] = None,
        dim: int = 0,
        bias_col: Optional[int] = None,
        fill: float = 0.0,
        bias_name: Optional[str] = None,
        feature_hash: Optional[FeatureHash] = None,
        nodes: Optional[Dict[str, object]] = None,
        transform_on: bool = False,
        identity: bool = False,
    ):
        self.vocab = vocab
        self.dim = dim
        self.bias_col = bias_col
        self.fill = fill
        self.bias_name = bias_name
        self.feature_hash = feature_hash
        self.nodes: Dict[str, object] = dict(nodes or {})
        self.transform_on = transform_on
        self.identity = identity
        # name-keyed replay table for prep_row (row 0 = no-node sentinel)
        self._name_table, self._name_index = TransformTable.from_named(self.nodes)
        # column-keyed replay table for featurize (built lazily: the
        # predictors construct a pipeline before any vocab exists)
        self._col_table: Optional[TransformTable] = None
        if vocab is not None and not identity:
            self._col_table = TransformTable.from_vocab(self.nodes, vocab, dim)
        # murmur results are pure per-name: cache raw name -> (col, sign)
        # so steady-state traffic hashes each distinct name once. Bounded
        # (YTK_TRANSFORM_CACHE); at the bound new names compute uncached,
        # so a client flooding fresh names costs cpu, never memory.
        self._hash_cache: Dict[str, Tuple[int, float]] = {}
        self._hash_cache_cap = max(int(knobs.get_int("YTK_TRANSFORM_CACHE")), 0)
        self._hash_lock = threading.Lock()

    @classmethod
    def for_identity(
        cls, vocab: Dict[str, int], dim: int, fill: float
    ) -> "TransformPipeline":
        return cls(vocab=vocab, dim=dim, fill=fill, identity=True)

    # -- offline predictor path ------------------------------------------

    def prep_row(self, features: Dict[str, float]) -> List[Tuple[str, float]]:
        """bias removal + optional hashing + vectorized transform replay
        (the `ContinuousPredictor._prep` contract, one row at a time)."""
        items = [(n, v) for n, v in features.items() if n != self.bias_name]
        if self.feature_hash is not None:
            items = self.feature_hash.hash_features(items)
        if not self.transform_on or not items:
            return items
        idx = np.fromiter(
            (self._name_index.get(n, 0) for n, _ in items),
            np.int64,
            len(items),
        )
        try:
            vals = np.fromiter((v for _, v in items), np.float64, len(items))
        except (ValueError, TypeError):
            # node-less features map to 0.0 without touching the value
            # (the scalar path never converted them); a noded feature's
            # bad value still raises, exactly like node.transform did
            vals = np.asarray(
                [float(v) if ix else 0.0 for (_, v), ix in zip(items, idx)],
                np.float64,
            )
        out = apply_nodes(self._name_table, idx, vals, nodeless_zero=True)
        return [(items[i][0], float(out[i])) for i in range(len(items))]

    def transform_scalar(self, name: str, val: float) -> float:
        """One-feature replay (the legacy `_transform(name, val)` API),
        routed through the same vectorized kernel."""
        if not self.transform_on:
            return val
        idx = np.asarray([self._name_index.get(name, 0)], np.int64)
        out = apply_nodes(
            self._name_table,
            idx,
            np.asarray([val], np.float64),
            nodeless_zero=True,
        )
        return float(out[0])

    # -- serve path -------------------------------------------------------

    def _resolve_hashed(self, keys: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Raw names -> (vocab column or -1, murmur sign), cached."""
        assert self.feature_hash is not None and self.vocab is not None
        cache = self._hash_cache
        vocab = self.vocab
        fh = self.feature_hash
        bias = self.bias_name
        cols = np.empty(len(keys), np.int64)
        signs = np.empty(len(keys), np.float64)
        misses: Dict[str, Tuple[int, float]] = {}
        for i, name in enumerate(keys):
            hit = cache.get(name)
            if hit is None:
                if name == bias:
                    hit = (-1, 1.0)
                else:
                    hashed, sign = fh.hash_name(name)
                    col = vocab.get(hashed)
                    hit = (col if col is not None else -1, sign)
                misses[name] = hit
            cols[i], signs[i] = hit
        if misses:
            with self._hash_lock:
                if len(cache) < self._hash_cache_cap:
                    cache.update(
                        itertools.islice(
                            misses.items(), self._hash_cache_cap - len(cache)
                        )
                    )
        return cols, signs

    def featurize(self, rows: Sequence[Dict[str, float]]) -> np.ndarray:
        """Request dicts -> dense (B, dim) float64 in one batched stage."""
        B = len(rows)
        X = np.full((B, self.dim), self.fill, np.float64)
        keys: List[str] = []
        vals: List[float] = []
        lens: List[int] = []
        ke, ve, la = keys.extend, vals.extend, lens.append
        for fmap in rows:
            ke(fmap.keys())
            ve(fmap.values())
            la(len(fmap))
        if not keys:
            if self.bias_col is not None:
                X[:, self.bias_col] = 1.0
            return X
        hashing = self.feature_hash is not None and not self.identity
        if hashing:
            jj, signs = self._resolve_hashed(keys)
        else:
            vocab = self.vocab or {}
            # the bias name never has a vocab column (it rides bias_col),
            # so the same lookup drops it like the per-scalar prep did
            jj = np.fromiter(
                map(vocab.get, keys, itertools.repeat(-1)), np.int64, len(keys)
            )
            signs = None
        m = jj >= 0  # unknown features drop, as in the host walk
        try:
            vv = np.asarray(vals, np.float64)
        except (ValueError, TypeError):
            # a non-numeric value on an UNKNOWN (dropped) feature must not
            # fail the request — the per-scalar path never converted it; a
            # known feature's bad value still raises, like the scatter would
            vv = np.asarray(
                [float(v) if k else 0.0 for v, k in zip(vals, m)], np.float64
            )
        ii = np.repeat(np.arange(B), lens)
        ii, jj, vv = ii[m], jj[m], vv[m]
        if hashing and len(ii):
            vv = vv * signs[m]
            # collisions SUM signed values, in request order — the same
            # float additions, in the same order, as hash_features' dict
            # accumulation (fill is 0.0 on every hashing family)
            np.add.at(X, (ii, jj), vv)
            flat = np.unique(ii * np.int64(self.dim) + jj)
            ui = flat // self.dim
            uj = flat % self.dim
        else:
            X[ii, jj] = vv  # one vectorized scatter, not len(ii) writes
            ui, uj = ii, jj
        if self.transform_on and not self.identity and len(ui):
            X[ui, uj] = apply_nodes(
                self._col_table, uj, X[ui, uj], nodeless_zero=True
            )
        if self.bias_col is not None:
            X[:, self.bias_col] = 1.0
        return X
