"""Compiled serve-time feature pipeline: one transform path for train,
offline predict, and serve (docs/transform.md)."""

from .pipeline import TransformPipeline, TransformTable, apply_nodes
from .sidecar import (
    DIGEST_PREFIX,
    model_parts_digest,
    model_text_digest,
    read_sidecar,
    stamp_sidecar_digest,
    verify_sidecar_digest,
)

__all__ = [
    "TransformPipeline",
    "TransformTable",
    "apply_nodes",
    "DIGEST_PREFIX",
    "model_parts_digest",
    "model_text_digest",
    "read_sidecar",
    "stamp_sidecar_digest",
    "verify_sidecar_digest",
]
