"""Transform-stat sidecar I/O + the `.bins.json` sha256 digest discipline.

The `<model>_feature_transform_stat` sidecar keeps the reference text
format for its data lines (`<name>###mode=..., mean=..., ...`) so
reference predictors still parse it. This module adds the same
crash-between-writes protection the bin-edge sidecar has
(gbdt/binning.py): at model-dump time the sidecar is re-stamped with a
sha256 digest of the model text about to land — as a `#`-prefixed
header line, atomically, BEFORE the model file — and serve load rejects
a sidecar whose digest names a different model text. A crash between
the two writes leaves new-sidecar/old-model, which the mismatch turns
into a loud load failure instead of silently skewed transforms. Legacy
digestless sidecars (and sidecars written at ingest, before any model
exists) load exactly as before.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional, Tuple

DIGEST_PREFIX = "#model_digest="


def model_text_digest(text: str) -> str:
    """sha256 hex of model text (same recipe as gbdt/binning.py)."""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def model_parts_digest(fs, model_path: str) -> Optional[str]:
    """Digest of the dumped model: part texts concatenated in sorted
    path order (the order every loader reads them). None when the model
    doesn't exist yet."""
    from ..io.fs import is_tmp_path

    if not fs.exists(model_path):
        return None
    h = hashlib.sha256()
    for part in sorted(fs.recur_get_paths([model_path])):
        if is_tmp_path(part):
            continue  # in-flight atomic_open temp from a writer
        with fs.open(part) as f:
            h.update(f.read().encode("utf-8"))
    return h.hexdigest()


def read_sidecar(fs, path: str) -> Tuple[Dict[str, object], Optional[str]]:
    """-> (name -> TransformNode, embedded digest or None).

    `#`-prefixed lines are header/comment lines (the digest stamp);
    data lines keep the reference `name###payload` format."""
    from ..io.reader import TransformNode

    nodes: Dict[str, object] = {}
    digest: Optional[str] = None
    with fs.open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith("#"):
                if line.startswith(DIGEST_PREFIX):
                    digest = line[len(DIGEST_PREFIX):].strip()
                continue
            name, _, payload = line.partition("###")
            nodes[name.strip()] = TransformNode.from_string(payload.strip())
    return nodes, digest


def verify_sidecar_digest(fs, model_path: str, digest: Optional[str]) -> None:
    """Raise when the sidecar's embedded digest names a DIFFERENT model
    text than what's on disk (the crash-between-writes window). A
    digestless sidecar (legacy, or ingest-time before the model exists)
    passes; so does a digest with no model yet (dump stamps the sidecar
    first, so a reader racing the very first dump sees exactly that)."""
    if digest is None:
        return
    actual = model_parts_digest(fs, model_path)
    if actual is not None and actual != digest:
        raise ValueError(
            f"transform sidecar digest mismatch for {model_path}: sidecar "
            f"was dumped with model text {digest[:12]}…, on-disk model is "
            f"{actual[:12]}… — refusing to replay stale transform stats "
            "(re-dump the model, or delete the sidecar to retrain stats)"
        )


def stamp_sidecar_digest(fs, sidecar_path: str, digest: str) -> None:
    """Atomically rewrite the sidecar with `#model_digest=<hex>` as its
    header line (replacing any previous header). Call BEFORE writing the
    model text the digest names — the same write order as the bin-edge
    sidecar, so the mismatch window is the detectable direction."""
    if not fs.exists(sidecar_path):
        return
    with fs.open(sidecar_path) as f:
        lines = [
            ln for ln in f.read().splitlines()
            if ln.strip() and not ln.lstrip().startswith("#")
        ]
    with fs.atomic_open(sidecar_path) as f:
        f.write(DIGEST_PREFIX + digest + "\n")
        for ln in lines:
            f.write(ln + "\n")
