"""The mp4j collective surface mapped onto XLA collectives.

The reference funnels every cross-worker exchange through ~10 ytk-mp4j verbs
(catalogued in SURVEY.md §1-L1 from grepping all call sites). This module is
the one-to-one TPU mapping; everything here is meant to run inside
`shard_map` over the mesh's data axis:

| mp4j verb (reference call site)                         | here               |
|---------------------------------------------------------|--------------------|
| allreduce scalar/array  (HoagOptimizer.java:1038)       | psum / pmax / pmin |
| reduceScatterArray      (HistogramBuilder.java:95)      | psum_scatter       |
| allgatherArray          (HoagOptimizer.java:916,928)    | all_gather         |
| object argmax allreduce (DataParallelTreeMaker.java:642)| pargmax_tuple      |
| allreduceMap (GK summaries, CoreData.java:628)          | host-side merge at |
|                                                         | load time (io/)    |

Object/map collectives carrying Kryo-serialized Java objects have no ICI
equivalent; the hot one (SplitInfo argmax) becomes a fixed-shape dense
reduction (`pargmax_tuple`), the cold ones (load-time quantile-sketch merges)
run on host via process_allgather.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import record_collective
from .mesh import DATA_AXIS

# Every verb below calls obs.record_collective before staging the XLA op:
# when obs is enabled, each *traced* collective is counted (calls + operand
# bytes per verb) and dropped into the trace as a zero-duration span — a
# static census of the program's collective surface (per compilation, not
# per execution; see record_collective's docstring).


def psum(x, axis_name: str = DATA_AXIS):
    record_collective("psum", x, axis_name)
    return lax.psum(x, axis_name)


def pmax(x, axis_name: str = DATA_AXIS):
    record_collective("pmax", x, axis_name)
    return lax.pmax(x, axis_name)


def pmin(x, axis_name: str = DATA_AXIS):
    record_collective("pmin", x, axis_name)
    return lax.pmin(x, axis_name)


def psum_scatter(
    x, axis_name: str = DATA_AXIS, tiled: bool = True, scatter_dimension: int = 0
):
    """reduceScatterArray equivalent: global sum, each rank keeps its slice.

    With tiled=True, input of shape (k*n_ranks, ...) returns (k, ...) — the
    same contiguous-slice ownership the reference's 2-D partition tables
    express (CommUtils.createThreadArrayFroms/Tos). scatter_dimension
    picks the sliced axis (the GBDT engine scatters node histograms over
    the feature axis, dimension 1)."""
    record_collective("psum_scatter", x, axis_name)
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


def all_gather(x, axis_name: str = DATA_AXIS, tiled: bool = True):
    """allgatherArray equivalent: concatenate each rank's slice along dim 0."""
    record_collective("all_gather", x, axis_name)
    return lax.all_gather(x, axis_name, tiled=tiled)


def pargmax_tuple(score, payload, axis_name: str = DATA_AXIS):
    """Global argmax with deterministic tie-break — the TPU replacement for
    the reference's object-allreduce of SplitInfo (best-split sync,
    optimizer/gbdt/DataParallelTreeMaker.java:640-653; tie-break semantics
    from data/gbdt/SplitInfo.needReplace:99: higher score wins, ties broken
    toward the lower rank index).

    score: scalar per rank; payload: pytree of scalars to carry along.
    Returns (best_score, best_payload) replicated on all ranks.
    """
    record_collective("pargmax", (score, payload), axis_name)
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    # NaN scores (split gains can be NaN from 0/0 hessian sums) are treated
    # as -inf so they can never win and never poison the pmax — HLO maximum
    # is NaN-propagating on some backends (VERDICT r1 Weak #4). All ranks
    # -inf/NaN degrades to rank 0 winning with score -inf, which callers see
    # as "no valid candidate".
    score = jnp.where(jnp.isnan(score), -jnp.inf, score)
    best = lax.pmax(score, axis_name)
    # Ranks holding the best score vote with their index; lowest rank wins.
    my_vote = jnp.where(score >= best, idx, n)
    winner = lax.pmin(my_vote, axis_name)
    is_winner = idx == winner

    def pick(leaf):
        leaf = jnp.asarray(leaf)
        # Select-then-psum instead of multiply-by-mask: a losing rank's ±inf
        # or NaN payload would otherwise poison the sum (0 * inf = NaN).
        return lax.psum(jnp.where(is_winner, leaf, jnp.zeros_like(leaf)), axis_name)

    return best, jax.tree_util.tree_map(pick, payload)


def axis_index(axis_name: str = DATA_AXIS):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str = DATA_AXIS):
    fn = getattr(lax, "axis_size", None)  # absent pre-0.5 jax
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)  # constant-folds to the axis size


# ---------------------------------------------------------------------------
# Host-side (load-time) small-object merges — replaces allreduceMap /
# allreduceMapSetUnion for feature dicts & sketches across processes.
# ---------------------------------------------------------------------------


def load_on_rank0(fn):
    """Run `fn()` on process 0 and broadcast its return value to every
    rank (rank0-only checkpoint dumps must not diverge on non-shared
    storage). Single-process: just `fn()`. All ranks MUST call this at the
    same point — it is a collective."""
    obj = fn() if jax.process_index() == 0 else None
    if jax.process_count() == 1:
        return obj
    return host_allgather_objects(obj)[0]


def host_allgather_objects(obj):
    """Gather a small python object from every process; returns a list with
    one entry per process, in rank order (multi-host only — single-process
    returns [obj]).

    multihost_utils.process_allgather stacks ARRAY leaves along a leading
    axis and cannot carry strings or ragged structures, so the object is
    pickled into a padded uint8 buffer first (two rounds: lengths, then
    bytes) — the Kryo-over-TCP objects of the reference's allreduceMap,
    done over DCN. Load-time only; never the hot path."""
    # `collective.host` fault site: the host-side verbs are the ones a
    # flaky DCN / dying peer actually breaks, and (unlike the traced ICI
    # verbs) a python-level injection here is observable. No retry — a
    # rank re-entering a collective alone would desync the group, so a
    # fault here is fatal by design and the flight event names it.
    from ..resilience import chaos_point

    chaos_point("collective.host")
    if jax.process_count() == 1:
        return [obj]
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    from ..obs import inc as obs_inc, span as obs_span

    blob = np.frombuffer(pickle.dumps(obj), np.uint8)
    obs_inc("collectives.host_allgather.calls", 1.0)
    obs_inc("collectives.host_allgather.bytes", float(blob.size))
    with obs_span("collectives.host_allgather", bytes=int(blob.size)):
        return _host_allgather_blob(blob)


def _host_allgather_blob(blob):
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    lens = np.asarray(
        multihost_utils.process_allgather(np.asarray([blob.size], np.int64))
    ).reshape(-1)
    padded = np.zeros((int(lens.max()),), np.uint8)
    padded[: blob.size] = blob
    allb = np.asarray(multihost_utils.process_allgather(padded)).reshape(
        len(lens), -1
    )
    return [
        pickle.loads(allb[i, : int(lens[i])].tobytes()) for i in range(len(lens))
    ]
