"""Device mesh construction — the TPU equivalent of ytk-mp4j topology.

The reference's communication world is `slaveNum × threadNum` ranks joined
through a CommMaster TCP rendezvous (reference: worker/TrainWorker.java:139,
bin/local_optimizer.sh:38-47). Here the world is a `jax.sharding.Mesh`:
devices are the ranks, `jax.distributed.initialize` is the rendezvous on
multi-host pods, and collectives ride ICI instead of ethernet.

One named axis, DATA_AXIS, carries row-sharded data parallelism (the
reference's only cross-worker axis). Model-parallel shardings (L-BFGS
history slices, GBDT histogram bin slices) reuse the same axis via
psum_scatter / all_gather, exactly mirroring how the reference overlays
slice ownership on the same rank grid (reference:
optimizer/HoagOptimizer.java:442-449, data/gbdt/HistogramBuilder.java:95).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=None):
    """jax.shard_map across jax versions: the top-level export (with its
    `check_vma` flag) landed after 0.4.x, where the API lives at
    jax.experimental.shard_map with the flag spelled `check_rep`."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as sm_exp

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def make_mesh(n_devices: Optional[int] = None, axis_name: str = DATA_AXIS) -> Mesh:
    """1-D mesh over (a prefix of) the available devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def local_device_count(mesh: Mesh) -> int:
    """This process's device count within the mesh."""
    me = jax.process_index()
    return sum(1 for d in mesh.devices.flat if d.process_index == me)


def put_row_sharded(arr, mesh: Mesh):
    """Row-shard dim 0 over the data axis. Single-process: a plain
    device_put. Multi-process: `arr` is THIS process's row shard and the
    global array is assembled from per-process shards (the TPU-native
    replacement for the reference's per-worker CoreData ownership —
    each worker's parsed rows become its device shard, no gather)."""
    sh = row_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    return jax.make_array_from_process_local_data(sh, arr)


def put_col_sharded(arr, mesh: Mesh):
    """Shard dim 1 (the sample axis of a transposed matrix) over data."""
    sh = NamedSharding(mesh, P(None, DATA_AXIS))
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    return jax.make_array_from_process_local_data(sh, arr)


def equal_row_target(n_local: int, mesh: Mesh, multiple: int = 1) -> int:
    """Local row count every process should pad to so the global row axis
    splits evenly across all mesh devices: max over processes, rounded up
    to a multiple of (local device count x `multiple`)."""
    ld = max(local_device_count(mesh), 1) * max(multiple, 1)
    if jax.process_count() == 1:
        return max(ld, -(-n_local // ld) * ld)
    from .collectives import host_allgather_objects

    counts = host_allgather_objects(int(n_local))
    return max(ld, -(-max(counts) // ld) * ld)


def distributed_initialize_if_needed(**kwargs) -> None:
    """Multi-host rendezvous: replaces the reference's CommMaster process
    (reference: worker/TrainWorker.java:139, bin/local_optimizer.sh:38-47).

    MUST run before any other JAX API touches the backend — querying
    `jax.process_count()` first would initialize the local backend and make
    distributed init a no-op (ADVICE r1). Set YTKLEARN_TPU_DISTRIBUTED=1 (or
    pass coordinator kwargs) in each process of a multi-host launch; on TPU
    pods coordinator discovery comes from the runtime metadata, on CPU/GPU
    clusters the standard jax.distributed env vars/kwargs apply.
    """
    if os.environ.get("YTKLEARN_TPU_DISTRIBUTED", "0") != "1" and not kwargs:
        return
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(**kwargs)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 (rows/samples) across the data axis; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_rows_to_multiple(n: int, k: int) -> int:
    """Rows must pad to a multiple of the mesh size for even sharding; the
    reference instead allowed ragged per-worker row counts
    (dataflow/DataFlow.java:391-410) — padding + weight-masking is the
    static-shape equivalent."""
    return (n + k - 1) // k * k


def shard_rows(arr, mesh: Mesh):
    """Device-put a host array with rows sharded over the data axis."""
    return jax.device_put(arr, row_sharding(mesh))
