from .mesh import (
    DATA_AXIS,
    distributed_initialize_if_needed,
    make_mesh,
    pad_rows_to_multiple,
    replicated,
    row_sharding,
    shard_rows,
)
from . import collectives

__all__ = [
    "DATA_AXIS",
    "collectives",
    "distributed_initialize_if_needed",
    "make_mesh",
    "pad_rows_to_multiple",
    "replicated",
    "row_sharding",
    "shard_rows",
]
