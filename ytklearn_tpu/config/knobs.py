"""Central registry of every ``YTK_*`` environment knob.

Before this module each subsystem read ``os.environ`` directly, so the
set of runtime knobs was only discoverable by grepping and half of them
never reached docs/running_guide.md. Now every knob is *declared* here —
name, type, default, one-line doc — and every read goes through the typed
accessors below. The ytklint ``undeclared-knob`` rule forbids YTK_*
``os.environ`` reads anywhere else in the tree, and ``check_doc_sync``
asserts this registry and the running-guide knob table match both ways
(scripts/check_lint.sh runs both on every change).

Accessors re-read ``os.environ`` on every call: tests and operators set
knobs at runtime and the previous call sites were all live reads too.
The handful of knobs consumed by shell launchers (bin/*.sh) are declared
with ``scope="shell"`` so the doc table stays the one complete inventory.

Regenerate the running-guide table after editing declarations:

    python -m ytklearn_tpu.config.knobs regen docs/running_guide.md
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = [
    "Knob",
    "KNOBS",
    "get_raw",
    "get_str",
    "get_int",
    "get_float",
    "get_bool",
    "names",
    "table_markdown",
    "check_doc_sync",
    "sync_doc",
]


@dataclass(frozen=True)
class Knob:
    name: str
    type: str  # "str" | "int" | "float" | "bool"
    default: object  # parsed value returned when the env var is unset
    doc: str  # one line; becomes the running-guide table row
    scope: str = "lib"  # "lib" | "bench" | "shell" (bin/*.sh) | "test"


KNOBS: Dict[str, Knob] = {}


def _knob(name: str, type_: str, default, doc: str, scope: str = "lib") -> None:
    if name in KNOBS:
        raise ValueError(f"duplicate knob declaration: {name}")
    KNOBS[name] = Knob(name, type_, default, doc, scope)


# -- platform / launcher ----------------------------------------------------
_knob("YTK_PLATFORM", "str", None,
      "force the JAX platform (e.g. `cpu`), even when a sitecustomize "
      "pre-imported jax and already captured JAX_PLATFORMS")
_knob("YTK_MASTER_LOG", "str", "log/master.log",
      "merged rank-labeled master-log path for `bin/cluster_optimizer.sh`",
      scope="shell")
_knob("YTK_SLAVE_HOSTS", "str", None,
      "space-separated hosts for ranks 1..N-1 (`bin/cluster_optimizer.sh` "
      "ssh fan-out; unset = all ranks fork locally)", scope="shell")
_knob("YTK_COORDINATOR_HOST", "str", "127.0.0.1",
      "jax.distributed coordinator host for multi-host launches",
      scope="shell")
_knob("YTK_COORDINATOR_PORT", "int", 29401,
      "jax.distributed coordinator port", scope="shell")

# -- ingest -----------------------------------------------------------------
_knob("YTK_NO_NATIVE", "bool", False,
      "disable the native C++ libsvm parser (python fallback)")
_knob("YTK_SKETCH_ROWS", "int", 1 << 25,
      "rows above which quantile binning streams through the GK sketch "
      "instead of the full-sort path")

# -- convex training (blocked evaluation) -----------------------------------
_knob("YTK_ROW_CHUNK", "int", None,
      "fixed row-chunk override for blocked convex evaluation "
      "(see [models.md](models.md) \"Memory\")")
_knob("YTK_CHUNK_BUDGET_MB", "int", 1024,
      "score-intermediate memory budget that sizes the automatic row chunk")

# -- gbdt engine ------------------------------------------------------------
_knob("YTK_PARTITION", "bool", True,
      "leaf-partitioned GBDT histogram phases (default on since r6; "
      "`0` turns them off)")
_knob("YTK_NO_PARTITION", "bool", False,
      "hard-disable leaf-partitioned histograms everywhere "
      "(wins over `YTK_PARTITION`)")
_knob("YTK_PARTITION_STRICT", "bool", False,
      "fail loud instead of downgrading when a partitioned/fused round "
      "program fails to compile (equivalence runs)")
_knob("YTK_LADDER", "str", None,
      "comma-separated budget-ladder divisors for partitioned histogram "
      "passes (default: `64,256` fused on TPU, `8,32` on CPU)")
_knob("YTK_FUSED", "bool", True,
      "fused compact+gather+histogram Pallas kernel for partitioned "
      "passes (`0` falls back to XLA gather)")
_knob("YTK_FUSED_MAX_ROWS", "int", 1 << 18,
      "max gathered rows per fused-kernel call (VMEM sizing)")
_knob("YTK_PROFILE_DIR", "str", None,
      "write a jax.profiler trace of the training loop for xprof")
_knob("YTK_GOSS_A", "float", 1.0,
      "GOSS top-gradient-magnitude keep fraction per tree in the device "
      "GBDT engine; a value < 1 enables gradient-based one-side sampling")
_knob("YTK_GOSS_B", "float", 0.1,
      "GOSS sample rate on the non-top remainder (sampled rows carry the "
      "1/b gradient-amplification correction); active only when "
      "`YTK_GOSS_A` < 1")
_knob("YTK_EFB", "bool", True,
      "exclusive feature bundling at GBDT binning time: merge mutually-"
      "exclusive sparse columns into offset-binned bundles (no-op when "
      "no such columns exist)")
_knob("YTK_EFB_CONFLICT", "int", 0,
      "max conflicting rows tolerated per EFB bundle (0 = strictly "
      "exclusive, lossless; >0 trades exactness for wider bundles)")

# -- observability ----------------------------------------------------------
_knob("YTK_OBS", "str", None,
      "`1` enables obs collection without export; `0` force-disables "
      "(wins over the trace-path knobs)")
_knob("YTK_OBS_JAX", "bool", False,
      "wrap obs spans in jax.profiler.TraceAnnotation so they show up "
      "inside XLA/xprof traces")
_knob("YTK_TRACE", "str", None,
      "enable obs + write a Chrome-trace/Perfetto JSON to this path at exit")
_knob("YTK_TRACE_JSONL", "str", None,
      "enable obs + write the JSONL event stream to this path at exit")
_knob("YTK_TRACE_SAMPLE", "float", 0.01,
      "serve-side request-tracing head-sample rate: the fraction of "
      "/predict requests whose per-hop spans are recorded and kept as "
      "exemplars (deterministic counter-hashed draws; `0` disables the "
      "tracing plane, `1` = always-on — see "
      "[observability.md](observability.md))")
_knob("YTK_TRACE_SEED", "int", 0,
      "seed for the deterministic trace head sampler (same seed + same "
      "request order = same kept set)")
_knob("YTK_TRACE_EXEMPLARS", "int", 256,
      "per-process exemplar-ring capacity (kept request traces), exported "
      "at `/admin/traces`; shed/504/SLO-violating requests are always "
      "retained, head-sampled ones ride the ring too")
_knob("YTK_OBS_HISTORY_N", "int", 256,
      "per-metric time-series ring length for the metrics history plane "
      "(`/metrics?history=1`); `0` disables history sampling")
_knob("YTK_OBS_HISTORY_S", "float", 1.0,
      "metrics-history sampling interval in seconds (the obs heartbeat "
      "sampler thread snapshots every counter/gauge this often)")
_knob("YTK_PROF", "str", None,
      "profiling plane (ytkprof): `1` arms phase accounting, the compile "
      "ledger, and the memory-watermark sampler; a *path* additionally "
      "captures `jax.profiler.trace` output for capture-opted phases into "
      "that directory (Perfetto-loadable); unset/`0` = off with zero new "
      "per-call work on the span hot path — see "
      "[observability.md](observability.md) \"Profiling plane\"")
_knob("YTK_PROF_TOPK", "int", 10,
      "rows kept in the ytkprof top-k kernel table (per parsed capture "
      "and in the `ytkprof` report schema)")
_knob("YTK_PROF_MEM_S", "float", 0.5,
      "memory-watermark sampler interval in seconds (device bytes-in-use "
      "+ host RSS into bounded rings, peaks attributed to the enclosing "
      "profiler phase)")
_knob("YTK_PROF_LEDGER_N", "int", 512,
      "compile-ledger ring capacity: the newest N jit compiles kept with "
      "program label, abstract arg signature, and compile ms")
_knob("YTK_QUALITY_SAMPLE", "float", 0.05,
      "model-quality plane row-sample rate: the fraction of served rows "
      "whose feature values and scores feed the per-model drift sketches "
      "(deterministic counter-hashed draws; `0` disables the plane, `1` "
      "= every row — see [observability.md](observability.md) "
      "\"Model-quality plane\")")
_knob("YTK_QUALITY_SEED", "int", 0,
      "seed for the deterministic quality row sampler (same seed + same "
      "row order = same sampled set)")
_knob("YTK_QUALITY_B", "int", 64,
      "entry budget per weighted-GK quality sketch (training sidecar and "
      "serve-side streaming sketches; bounds both memory and the "
      "/metrics?quality=1 export size)")
_knob("YTK_QUALITY_EVAL_S", "float", 5.0,
      "quality-evaluator tick interval in seconds: each tick drains the "
      "sampled-row buffers into the sketches, recomputes PSI/KS and "
      "calibration drift, and feeds the drift sentinels")
_knob("YTK_MODEL_METRICS_MAX", "int", 32,
      "named per-model metric-family budget for the mesh-obs accounting "
      "plane (`serve.model.<name>.*` counters, latency rings, burn "
      "sentinels); names past the budget — and 404 name floods — land "
      "in the shared `__overflow__` bucket, so label cardinality is "
      "bounded by construction — see "
      "[observability.md](observability.md) \"Per-model accounting\"")

# -- run health -------------------------------------------------------------
_knob("YTK_HEALTH", "bool", True,
      "run-health sentinels (NaN/divergence/ingest-rate); `0` reduces every "
      "check to one attribute load")
_knob("YTK_HEALTH_STRICT", "bool", False,
      "escalate sentinel hits to HealthError naming the flight dump "
      "(unattended production runs)")
_knob("YTK_HEALTH_INGEST_TOL", "float", 0.01,
      "ingest error-rate threshold (fraction) for the parse sentinel")
_knob("YTK_SLO_BURN_WINDOW", "int", 256,
      "requests per SLO burn-rate window: the `health.slo_burn` sentinel "
      "judges the violation rate once per full window")
_knob("YTK_SLO_BURN_BUDGET", "float", 0.1,
      "SLO error budget as a windowed violation-rate fraction: when more "
      "than this fraction of a window's requests exceed the SLO (or are "
      "shed/504'd), `health.slo_burn` fires (strict mode escalates)")
_knob("YTK_HEALTH_DRIFT_PSI", "float", 0.25,
      "per-feature population-stability-index threshold for the serving "
      "drift sentinel: consecutive quality-evaluator ticks with any "
      "feature's PSI above it fire `health.drift` (0.1/0.25 are the "
      "conventional watch/act levels)")
_knob("YTK_HEALTH_DRIFT_KS", "float", 0.35,
      "per-feature Kolmogorov-Smirnov distance threshold for the serving "
      "drift sentinel (fires `health.drift` alongside the PSI test)")
_knob("YTK_HEALTH_DRIFT_WINDOWS", "int", 2,
      "consecutive over-threshold quality-evaluator ticks required before "
      "`health.drift` / `health.calibration` fire (one noisy tick cannot "
      "page anyone); the streak re-arms after each fire")
_knob("YTK_HEALTH_DRIFT_MIN_ROWS", "int", 200,
      "minimum sampled rows before the drift/calibration sentinels judge "
      "a model (a two-request warmup is not a distribution)")
_knob("YTK_HEALTH_CALIBRATION_TOL", "float", 0.1,
      "calibration-drift tolerance: absolute |mean predicted score - "
      "training-sidecar mean| (on the prediction scale) above which "
      "`health.calibration` fires")
_knob("YTK_FLIGHT", "bool", True,
      "flight-recorder auto-install in trainers; `0` opts out")
_knob("YTK_FLIGHT_N", "int", 4096,
      "flight-recorder event-ring capacity")
_knob("YTK_FLIGHT_DIR", "str", "flight_dumps",
      "flight-dump directory (default: `flight_dumps/`, which is "
      "gitignored — a crash dump must never end up committed)")

# -- resilience (docs/fault_tolerance.md) -----------------------------------
_knob("YTK_CHAOS", "str", None,
      "deterministic fault injection spec `site:kind:rate:seed[,...]` "
      "(kinds: oserror|error|sigterm|kill); counter-based draws make "
      "every injected fault reproducible — see "
      "[fault_tolerance.md](fault_tolerance.md)")
_knob("YTK_RETRY_MAX", "int", 4,
      "attempt budget per `resilience.retry` site (1 = no retries)")
_knob("YTK_RETRY_BASE_S", "float", 0.05,
      "first-retry backoff in seconds (doubles per attempt, "
      "deterministically jittered into [0.5, 1.0)x)")
_knob("YTK_RETRY_MAX_S", "float", 2.0,
      "backoff ceiling in seconds for the retry exponential")
_knob("YTK_PREEMPT", "bool", True,
      "preemption guard in trainers: SIGTERM/SIGINT deferred to the next "
      "round/iteration boundary, emergency checkpoint, exit 128+signum "
      "(`--resume auto` re-enters training); `0` keeps raw signal "
      "semantics")
_knob("YTK_RETRAIN_LOCK_TTL_S", "float", 900.0,
      "retrain lockfile heartbeat staleness (seconds) after which a new "
      "retrain auto-reclaims the lock; same-host dead owners are "
      "reclaimed immediately")

# -- continual training -----------------------------------------------------
_knob("YTK_GATE_COMPILED", "bool", True,
      "route the continual gate's held-out eval through CompiledScorer "
      "(batched jit scoring); `0` falls back to the host row walk")
_knob("YTK_CONTINUAL_BAND", "float", 0.0,
      "relative held-out-loss tolerance for retrain promotion: a candidate "
      "passes the metric gate when loss <= incumbent * (1 + band); 0 = "
      "must be no worse (config `continual.band` overrides per run)")
_knob("YTK_CONTINUAL_KEEP", "int", 2,
      "archived incumbent versions kept next to the model path for "
      "`ytklearn-tpu retrain --rollback`")
_knob("YTK_CONTINUAL_STRICT", "bool", False,
      "escalate a rejected retrain candidate to a non-zero exit "
      "(unattended freshness pipelines; default records the rejection "
      "and keeps the incumbent)")
_knob("YTK_CONTINUAL_DRIFT_URL", "str", None,
      "serving base URL (e.g. `http://127.0.0.1:8080`) the retrain "
      "driver fetches `/metrics?quality=1` from: the serve-side drift "
      "snapshot is recorded as an ADVISORY gate input (never pass/fail) "
      "in the gate report and result JSON — the hook drift-gated "
      "retraining hardens later")

# -- serving ----------------------------------------------------------------
_knob("YTK_SERVE_LADDER", "str", None,
      "serving batch-shape ladder, e.g. `1,8,64,512` "
      "(see [serving.md](serving.md))")
_knob("YTK_SERVE_WATCH_S", "float", 5.0,
      "serving hot-reload fingerprint poll interval in seconds "
      "(`0` disables the watcher)")
_knob("YTK_SERVE_REPLICAS", "int", 0,
      "serving fleet size: replica worker processes behind the front "
      "(`0` = single-process serving, `-1` = one per device, or per core "
      "on CPU; CLI `--replicas` overrides — see [serving.md](serving.md))")
_knob("YTK_SERVE_SLO_MS", "float", 100.0,
      "serving p99 latency SLO in ms — the target the AIMD batch-size "
      "controller searches under (`0` disables the controller and "
      "restores the fixed `--max-batch`/`--max-wait-ms` knobs)")
_knob("YTK_SERVE_SLO_MODELS", "str", None,
      "per-model SLO overrides for the mesh-obs burn sentinels, "
      "`name:ms,name2:ms` (e.g. `ctr:25,ranker:100`); listed models get "
      "their own `health.slo_burn` budget at that SLO, unlisted models "
      "inherit the app-wide `--slo-ms` default — see "
      "[observability.md](observability.md) \"Per-model accounting\"")
_knob("YTK_SERVE_CACHE_ROWS", "int", 0,
      "bounded LRU prediction-cache capacity in rows, keyed on (model "
      "fingerprint, feature-row hash); hits bypass the batcher queue and "
      "are bit-identical to the scored path (`0` disables)")
_knob("YTK_SERVE_AIMD_INC", "int", 8,
      "AIMD additive-increase step in rows per clean adjustment window "
      "(the raw target then snaps DOWN to a compiled ladder rung)")
_knob("YTK_SERVE_AIMD_BACKOFF", "float", 0.5,
      "AIMD multiplicative backoff factor applied to the raw batch "
      "target on a p99-SLO violation (must be in (0, 1))")
_knob("YTK_SERVE_FUSED", "bool", False,
      "serve-side fused Pallas GBDT traversal kernel (bit-identical "
      "math, heap node layout resident in VMEM); falls back to the "
      "stacked XLA path with a `serve.downgrade.*` counter when Mosaic "
      "cannot compile it — see [serving.md](serving.md)")
_knob("YTK_SERVE_BINNED", "bool", False,
      "binned GBDT scoring rung: bin request rows once per batch "
      "(dumped `<model>.bins.json` training edges, else ensemble-derived "
      "thresholds — the latter bit-identical) and traverse on "
      "uint8/uint16 bin indices via the fastest backend (Pallas on TPU, "
      "native C++ on CPU, XLA fallback)")
_knob("YTK_SERVE_PRECISION", "str", "f64",
      "serving precision rung for the convex/FM/FFM einsum scorers: "
      "`bf16` = bf16 operands with f32 accumulation (quality band "
      "measured in scripts/serve_bench.py); GBDT/GBST scoring ignores it")
_knob("YTK_SERVE_KERNEL_THREADS", "int", 0,
      "row-parallel threads for the native serve kernel "
      "(0 = min(8, cores); batches under 64 rows stay single-threaded)")
_knob("YTK_SERVE_AIMD_WINDOW", "int", 16,
      "batches per AIMD adjustment window: the controller judges the "
      "window's worst observed request latency against the SLO once per "
      "window, so one straggler cannot collapse the batch size")
_knob("YTK_SERVE_REPLICAS_MIN", "int", 0,
      "fleet autoscaler floor: minimum replica slots the autoscaler may "
      "reap down to (`0` = follow `--replicas`; CLI `--replicas-min` "
      "overrides — see [serving.md](serving.md) autoscaling)")
_knob("YTK_SERVE_REPLICAS_MAX", "int", 0,
      "fleet autoscaler ceiling: maximum replica slots the autoscaler "
      "may grow to (`0` = follow `--replicas`, which disarms "
      "autoscaling; CLI `--replicas-max` overrides)")
_knob("YTK_SERVE_SCALE_INTERVAL_S", "float", 1.0,
      "autoscaler decision-tick interval in seconds (each tick samples "
      "the windowed load signals and advances the hysteresis streaks)")
_knob("YTK_SERVE_SCALE_UP_BACKLOG", "float", 256.0,
      "scale-up backlog threshold in queued+in-flight rows PER READY "
      "REPLICA: a tick above it (or any shed / p99-over-SLO / slo-burn "
      "fire) counts as overloaded")
_knob("YTK_SERVE_SCALE_DOWN_BACKLOG", "float", 16.0,
      "scale-down backlog threshold in rows per ready replica: a tick "
      "below it with zero sheds and p99 comfortably inside the SLO "
      "counts as idle (the gap up to the scale-up threshold is the "
      "hysteresis band)")
_knob("YTK_SERVE_SCALE_UP_WINDOWS", "int", 3,
      "consecutive overloaded ticks required before the autoscaler "
      "grows the fleet (one bursty tick cannot spawn a replica)")
_knob("YTK_SERVE_SCALE_DOWN_WINDOWS", "int", 10,
      "consecutive idle ticks required before the autoscaler reaps a "
      "replica (drain-based: fenced, completed/rerouted, then SIGTERM)")
_knob("YTK_SERVE_SCALE_UP_COOLDOWN_S", "float", 5.0,
      "seconds after a scale-up before the next scale-up may fire (new "
      "capacity must land and be judged before growing again)")
_knob("YTK_SERVE_SCALE_DOWN_COOLDOWN_S", "float", 30.0,
      "seconds after ANY scale decision before a scale-down may fire "
      "(capacity a spike just paid for is never reaped immediately)")

# -- transform pipeline -----------------------------------------------------
_knob("YTK_TRANSFORM_CACHE", "int", 1_000_000,
      "bound on the serve-time feature-hash resolution cache (raw name "
      "-> scoring column + murmur sign, per loaded model); at the bound "
      "new names compute uncached, so a fresh-name flood costs cpu, "
      "never memory")

# -- bench ------------------------------------------------------------------
_knob("YTK_CHIP", "str", "v5e",
      "chip key for bench roofline peaks (MXU/HBM utilization fields)",
      scope="bench")
_knob("YTK_HIGGS_DIR", "str", None,
      "directory holding the real Higgs split for bench.py "
      "(default: `experiment/higgs/`)", scope="bench")
_knob("YTK_REF", "str", "/root/reference",
      "path to the reference checkout used by reference-gated tests and "
      "benches", scope="test")
_knob("YTK_LOCKWATCH_HOLD_MS", "float", 1000.0,
      "lock hold-time budget (ms) for `pytest --ytk-lockwatch`: a "
      "watched lock held longer fails the `@pytest.mark.threaded` test "
      "(the runtime twin of ytklint blocking-call-under-lock)",
      scope="test")


# ---------------------------------------------------------------------------
# Typed accessors — the only sanctioned YTK_* environ reads in the tree.
# ---------------------------------------------------------------------------

_FALSY = ("0", "false", "no", "off")


def _declared(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"undeclared knob {name!r}: declare it in "
            "ytklearn_tpu/config/knobs.py (the ytklint undeclared-knob "
            "rule enforces this statically too)"
        ) from None


def get_raw(name: str) -> Optional[str]:
    """The raw env string, or None when unset (tri-state knobs: YTK_OBS)."""
    _declared(name)
    return os.environ.get(name)


def get_str(name: str) -> Optional[str]:
    knob = _declared(name)
    raw = os.environ.get(name)
    return raw if raw not in (None, "") else knob.default


def get_int(name: str) -> Optional[int]:
    knob = _declared(name)
    raw = os.environ.get(name)
    return int(raw) if raw not in (None, "") else knob.default


def get_float(name: str) -> Optional[float]:
    knob = _declared(name)
    raw = os.environ.get(name)
    return float(raw) if raw not in (None, "") else knob.default


def get_bool(name: str) -> bool:
    """Unset or empty -> declared default (an empty export is "cleared",
    same as the str/int/float accessors); `0`/`false`/`no`/`off` (any
    case) -> False; anything else -> True."""
    knob = _declared(name)
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return bool(knob.default)
    return raw.strip().lower() not in _FALSY


def names() -> list:
    return sorted(KNOBS)


# ---------------------------------------------------------------------------
# Doc sync: the running-guide knob table is generated from this registry.
# ---------------------------------------------------------------------------

DOC_BEGIN = "<!-- knob-table:begin -->"
DOC_END = "<!-- knob-table:end -->"
_NAME_RE = re.compile(r"`(YTK_[A-Z0-9_]+)")


def _fmt_default(knob: Knob) -> str:
    if knob.default is None:
        return "unset"
    if knob.type == "bool":
        return "on" if knob.default else "off"
    return f"`{knob.default}`"


def table_markdown() -> str:
    """The complete knob table as a markdown block (with sync markers)."""
    lines = [DOC_BEGIN, "| knob | default | effect |", "|---|---|---|"]
    for name in sorted(KNOBS):
        knob = KNOBS[name]
        suffix = {"shell": " *(shell launchers)*", "bench": " *(bench.py)*",
                  "test": " *(tests)*"}.get(knob.scope, "")
        lines.append(f"| `{name}` | {_fmt_default(knob)} | {knob.doc}{suffix} |")
    lines.append(DOC_END)
    return "\n".join(lines)


def _doc_block(text: str, path: str) -> str:
    try:
        start = text.index(DOC_BEGIN)
        end = text.index(DOC_END)
    except ValueError:
        raise ValueError(
            f"{path}: knob-table markers not found — the knob table must "
            f"live between {DOC_BEGIN.split(' ')[0]}… and {DOC_END}"
        ) from None
    return text[start:end]


def check_doc_sync(doc_path: str = "docs/running_guide.md") -> list:
    """Both-way registry<->doc check; returns a list of problem strings
    (empty = in sync). Every declared knob must appear in the doc table,
    and every YTK_* name in the table must be declared here."""
    # ytklint: allow(unseamed-io) reason=dev-time doc tooling on the checked-in markdown; not a runtime data path
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    block = _doc_block(text, doc_path)
    documented = set(_NAME_RE.findall(block))
    declared = set(KNOBS)
    problems = []
    for name in sorted(declared - documented):
        problems.append(
            f"{doc_path}: knob {name} is declared in the registry but "
            "missing from the knob table (regen the table)"
        )
    for name in sorted(documented - declared):
        problems.append(
            f"{doc_path}: knob {name} appears in the knob table but is not "
            "declared in ytklearn_tpu/config/knobs.py"
        )
    if block.strip() != table_markdown().replace(DOC_END, "").strip():
        if not problems:
            problems.append(
                f"{doc_path}: knob table text drifted from the registry "
                "(regen the table)"
            )
    return problems


def sync_doc(doc_path: str = "docs/running_guide.md") -> bool:
    """Rewrite the doc's knob-table block from the registry. True = changed."""
    # ytklint: allow(unseamed-io) reason=dev-time doc tooling on the checked-in markdown; not a runtime data path
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    _doc_block(text, doc_path)  # raises when markers are missing
    start = text.index(DOC_BEGIN)
    end = text.index(DOC_END) + len(DOC_END)
    new = text[:start] + table_markdown() + text[end:]
    if new == text:
        return False
    # ytklint: allow(unseamed-io) reason=dev-time doc tooling on the checked-in markdown; not a runtime data path
    with open(doc_path, "w", encoding="utf-8") as f:
        f.write(new)
    return True


def _main(argv) -> int:
    import sys

    if not argv or argv[0] not in ("table", "check", "regen"):
        sys.stderr.write(
            "usage: python -m ytklearn_tpu.config.knobs "
            "{table | check [doc] | regen [doc]}\n"
        )
        return 2
    cmd, rest = argv[0], argv[1:]
    doc = rest[0] if rest else "docs/running_guide.md"
    if cmd == "table":
        sys.stdout.write(table_markdown() + "\n")
        return 0
    if cmd == "regen":
        changed = sync_doc(doc)
        sys.stderr.write(f"{doc}: {'rewrote' if changed else 'unchanged'}\n")
        return 0
    problems = check_doc_sync(doc)
    for p in problems:
        sys.stderr.write(p + "\n")
    if problems:
        return 1
    sys.stderr.write(f"knob doc sync: OK ({len(KNOBS)} knobs)\n")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main(sys.argv[1:]))
