"""Minimal HOCON parser for ytk-learn config files.

The reference parses HOCON via typesafe-config (reference: pom.xml:63-67) and
reads `config/model/*.conf`. This module implements the HOCON subset those
files actually use, so unchanged reference configs drive this framework:

- `#` and `//` comments
- `key : value`, `key = value`, `key value` for objects
- newline OR comma as element separator; trailing commas
- nested objects `{}`, arrays `[]`
- quoted and unquoted strings; ints/floats/bools/null
- `???` placeholder (typesafe-config "required but unset") -> MISSING sentinel
- dotted keys (`a.b.c : v`) -> nested objects
- duplicate object keys merge (later wins for scalars, deep-merge for objects)

Substitutions (`${...}`) and `include` are not used by any reference config
and raise a clear error.
"""

from __future__ import annotations

from typing import Any


class _Missing:
    """Sentinel for `???` values (required-but-unset in typesafe-config)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "???"

    def __bool__(self):
        return False


MISSING = _Missing()


class HoconError(ValueError):
    pass


_DELIMS = set("{}[],:=")
_WS = set(" \t\r")


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.n = len(text)

    # --- low level -------------------------------------------------------
    def _peek(self) -> str:
        return self.text[self.pos] if self.pos < self.n else ""

    def _skip_ws_and_comments(self, skip_newlines: bool = True) -> None:
        while self.pos < self.n:
            c = self.text[self.pos]
            if c in _WS:
                self.pos += 1
            elif c == "\n":
                if not skip_newlines:
                    return
                self.pos += 1
            elif c == "#" or self.text.startswith("//", self.pos):
                while self.pos < self.n and self.text[self.pos] != "\n":
                    self.pos += 1
            else:
                return

    def _error(self, msg: str) -> HoconError:
        line = self.text.count("\n", 0, self.pos) + 1
        return HoconError(f"line {line}: {msg}")

    # --- values ----------------------------------------------------------
    def parse_root(self) -> dict:
        self._skip_ws_and_comments()
        if self._peek() == "{":
            obj = self.parse_object()
        else:
            obj = self.parse_object_body(root=True)
        self._skip_ws_and_comments()
        if self.pos < self.n:
            raise self._error(f"trailing content: {self.text[self.pos:self.pos+20]!r}")
        return obj

    def parse_object(self) -> dict:
        assert self._peek() == "{"
        self.pos += 1
        obj = self.parse_object_body(root=False)
        if self._peek() != "}":
            raise self._error("expected '}'")
        self.pos += 1
        return obj

    def parse_object_body(self, root: bool) -> dict:
        obj: dict = {}
        while True:
            self._skip_ws_and_comments()
            c = self._peek()
            if c == "" and root:
                return obj
            if c == "}" and not root:
                return obj
            if c == "":
                raise self._error("unexpected end of input in object")
            if c == ",":
                self.pos += 1
                continue
            key = self.parse_key()
            self._skip_ws_and_comments(skip_newlines=False)
            c = self._peek()
            if c in (":", "="):
                self.pos += 1
                self._skip_ws_and_comments()
                value = self.parse_value()
            elif c == "{":
                value = self.parse_object()
            else:
                raise self._error(f"expected ':', '=' or '{{' after key {key!r}")
            _set_dotted(obj, key, value)

    def parse_key(self) -> str:
        c = self._peek()
        if c == '"':
            return self.parse_quoted_string()
        start = self.pos
        while self.pos < self.n:
            c = self.text[self.pos]
            if c in _DELIMS or c in _WS or c == "\n" or c == "#" or self.text.startswith("//", self.pos):
                break
            self.pos += 1
        key = self.text[start : self.pos]
        if not key:
            raise self._error("empty key")
        return key

    def parse_value(self) -> Any:
        c = self._peek()
        if c == "{":
            return self.parse_object()
        if c == "[":
            return self.parse_array()
        if c == '"':
            s = self.parse_quoted_string()
            # HOCON value concatenation of adjacent strings is not needed by
            # the reference configs; a bare quoted string is the value.
            return s
        if c == "$":
            raise self._error("HOCON substitutions ${...} are not supported")
        return self.parse_unquoted()

    def parse_array(self) -> list:
        assert self._peek() == "["
        self.pos += 1
        items: list = []
        while True:
            self._skip_ws_and_comments()
            c = self._peek()
            if c == "]":
                self.pos += 1
                return items
            if c == ",":
                self.pos += 1
                continue
            if c == "":
                raise self._error("unexpected end of input in array")
            items.append(self.parse_value())

    def parse_quoted_string(self) -> str:
        assert self._peek() == '"'
        self.pos += 1
        out = []
        while self.pos < self.n:
            c = self.text[self.pos]
            if c == '"':
                self.pos += 1
                return "".join(out)
            if c == "\\":
                self.pos += 1
                esc = self.text[self.pos] if self.pos < self.n else ""
                mapping = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "/": "/"}
                if esc in mapping:
                    out.append(mapping[esc])
                    self.pos += 1
                elif esc == "u":
                    out.append(chr(int(self.text[self.pos + 1 : self.pos + 5], 16)))
                    self.pos += 5
                else:
                    raise self._error(f"bad escape \\{esc}")
            else:
                out.append(c)
                self.pos += 1
        raise self._error("unterminated string")

    def parse_unquoted(self) -> Any:
        start = self.pos
        while self.pos < self.n:
            c = self.text[self.pos]
            if c in "{}[]," or c == "\n" or c == "#" or self.text.startswith("//", self.pos):
                break
            self.pos += 1
        raw = self.text[start : self.pos].strip()
        if not raw:
            raise self._error("empty value")
        return _coerce(raw)


def _coerce(raw: str) -> Any:
    if raw == "???":
        return MISSING
    low = raw.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low == "null":
        # typesafe-config treats only `null` as null; an unquoted `none`
        # stays a string (ADVICE r1).
        return None
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


def _set_dotted(obj: dict, key: str, value: Any, merge: bool = True) -> None:
    parts = key.split(".")
    cur = obj
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    last = parts[-1]
    old = cur.get(last)
    if merge and isinstance(old, dict) and isinstance(value, dict):
        _deep_merge(old, value)
    else:
        cur[last] = value


def _deep_merge(dst: dict, src: dict) -> dict:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
    return dst


# --- public API ----------------------------------------------------------


def loads(text: str) -> dict:
    """Parse a HOCON document into a plain nested dict."""
    return _Parser(text).parse_root()


def load(path: str) -> dict:
    # ytklint: allow(unseamed-io) reason=startup config parse; runs once before any obs/retry plumbing exists, a missing config must fail loudly not retry
    with open(path, "r", encoding="utf-8") as f:
        return loads(f.read())


def get_path(cfg: dict, path: str, default: Any = None) -> Any:
    """`config.getX("a.b.c")` equivalent. Returns `default` when absent."""
    cur: Any = cfg
    for p in path.split("."):
        if not isinstance(cur, dict) or p not in cur:
            return default
        cur = cur[p]
    return cur


def set_path(cfg: dict, path: str, value: Any) -> dict:
    """`config.withValue` equivalent (reference: worker/TrainWorker.java:118-131),
    used for programmatic/custom-param overrides. Mutates and returns cfg.

    Values keep the type they are given (`withValue` semantics) — a string
    "2024" stays a string; callers wanting coercion parse before calling.
    Dict values *replace* the subtree (withValue replaces; only the HOCON
    parser's duplicate-key handling deep-merges)."""
    _set_dotted(cfg, path, value, merge=False)
    return cfg


def require(cfg: dict, path: str) -> Any:
    v = get_path(cfg, path, MISSING)
    if v is MISSING:
        raise HoconError(f"config value {path!r} is required (??? or absent)")
    return v
