"""Typed parameter beans parsed from HOCON configs.

Mirrors the reference `param/` package semantics (reference:
param/CommonParams.java:40-45, param/DataParams.java:41, param/FeatureParams.java:38,
param/ModelParams.java:38, param/LossParams.java:41, param/LineSearchParams.java:43,
param/HyperParams.java:41, param/RandomParams.java:40, param/FeatureHashParams.java:38,
param/gbdt/GBDTCommonParams.java:46 and friends) so unchanged
`config/model/*.conf` files drive the TPU framework.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import hocon
from .hocon import MISSING, get_path


# The reference's stock configs use ??? (sometimes quoted "???") as a
# fill-me-in placeholder (config/model/gbdt.conf:41). Unquoted ??? already
# coerces to MISSING in the hocon parser; quoted "???" arrives as a string,
# so treat it as unset here too — for every field, not just paths —
# rather than e.g. writing a model to a file literally named ???.


def _req(cfg: dict, path: str):
    v = get_path(cfg, path, MISSING)
    if v is MISSING or v == "???":
        raise ValueError(f"config value {path!r} is required but unset (???)")
    return v


def _opt(cfg: dict, path: str, default):
    v = get_path(cfg, path, default)
    return default if v is MISSING or v == "???" else v


def _opt_path(cfg: dict, path: str) -> str:
    return str(_opt(cfg, path, "") or "")


def _as_paths(v) -> List[str]:
    """data_path may be a single string or a list; comma-split like the
    reference's multi-path handling. "???" placeholders drop out."""
    if v is None or v is MISSING or v == "":
        return []
    if isinstance(v, (list, tuple)):
        out: List[str] = []
        for x in v:
            out.extend(_as_paths(x))
        return out
    return [p for p in str(v).split(",") if p and p != "???"]


@dataclass
class DelimParams:
    """reference: param/DataParams.java (delim block)."""

    x_delim: str = "###"
    y_delim: str = ","
    features_delim: str = ","
    feature_name_val_delim: str = ":"
    field_delim: str = "@"  # FFM only (config/model/ffm.conf)

    @classmethod
    def from_config(cls, cfg: dict) -> "DelimParams":
        d = get_path(cfg, "data.delim", {}) or {}
        return cls(
            x_delim=d.get("x_delim", "###"),
            y_delim=d.get("y_delim", ","),
            features_delim=d.get("features_delim", ","),
            feature_name_val_delim=d.get("feature_name_val_delim", ":"),
            field_delim=d.get("field_delim", "@"),
        )


@dataclass
class DataParams:
    train_paths: List[str] = field(default_factory=list)
    train_max_error_tol: int = 0
    test_paths: List[str] = field(default_factory=list)
    test_max_error_tol: int = 0
    delim: DelimParams = field(default_factory=DelimParams)
    # ["0@0.1", "1@0.5"] -> keep label 0 w.p. 0.1 (reference: dataflow/CoreData.java label sampling)
    y_sampling: List[Tuple[str, float]] = field(default_factory=list)
    assigned: bool = False
    unassigned_mode: str = "lines_avg"  # lines_avg | files_avg
    max_feature_dim: int = -1  # GBDT only

    @classmethod
    def from_config(cls, cfg: dict) -> "DataParams":
        ys = []
        for s in _opt(cfg, "data.y_sampling", []) or []:
            label, rate = str(s).split("@")
            ys.append((label, float(rate)))
        return cls(
            train_paths=_as_paths(_opt(cfg, "data.train.data_path", "")),
            train_max_error_tol=int(_opt(cfg, "data.train.max_error_tol", 0)),
            test_paths=_as_paths(_opt(cfg, "data.test.data_path", "")),
            test_max_error_tol=int(_opt(cfg, "data.test.max_error_tol", 0)),
            delim=DelimParams.from_config(cfg),
            y_sampling=ys,
            assigned=bool(_opt(cfg, "data.assigned", False)),
            unassigned_mode=str(_opt(cfg, "data.unassigned_mode", "lines_avg")),
            max_feature_dim=int(_opt(cfg, "data.max_feature_dim", -1)),
        )


@dataclass
class FeatureHashParams:
    """reference: param/FeatureHashParams.java:38, feature/FeatureHash.java."""

    need_feature_hash: bool = False
    bucket_size: int = 1_000_000
    seed: int = 39916801
    feature_prefix: str = "hash_"

    @classmethod
    def from_config(cls, cfg: dict) -> "FeatureHashParams":
        return cls(
            need_feature_hash=bool(_opt(cfg, "feature.feature_hash.need_feature_hash", False)),
            bucket_size=int(_opt(cfg, "feature.feature_hash.bucket_size", 1_000_000)),
            seed=int(_opt(cfg, "feature.feature_hash.seed", 39916801)),
            feature_prefix=str(_opt(cfg, "feature.feature_hash.feature_prefix", "hash_")),
        )


@dataclass
class TransformParams:
    """Feature standardization / range scaling (reference: param/TransformParams.java:41)."""

    switch_on: bool = False
    mode: str = "standardization"  # standardization | scale_range
    scale_min: float = -1.0
    scale_max: float = 1.0
    include_features: List[str] = field(default_factory=list)
    exclude_features: List[str] = field(default_factory=list)

    @classmethod
    def from_config(cls, cfg: dict) -> "TransformParams":
        return cls(
            switch_on=bool(_opt(cfg, "feature.transform.switch_on", False)),
            mode=str(_opt(cfg, "feature.transform.mode", "standardization")),
            scale_min=float(_opt(cfg, "feature.transform.scale_range.min", -1.0)),
            scale_max=float(_opt(cfg, "feature.transform.scale_range.max", 1.0)),
            include_features=list(_opt(cfg, "feature.transform.include_features", []) or []),
            exclude_features=list(_opt(cfg, "feature.transform.exclude_features", []) or []),
        )


@dataclass
class FeatureParams:
    feature_hash: FeatureHashParams = field(default_factory=FeatureHashParams)
    transform: TransformParams = field(default_factory=TransformParams)
    filter_threshold: int = 0

    @classmethod
    def from_config(cls, cfg: dict) -> "FeatureParams":
        return cls(
            feature_hash=FeatureHashParams.from_config(cfg),
            transform=TransformParams.from_config(cfg),
            filter_threshold=int(_opt(cfg, "feature.filter_threshold", 0)),
        )


@dataclass
class ModelParams:
    """reference: param/ModelParams.java:38."""

    data_path: str = ""
    delim: str = ","
    need_dict: bool = False
    dict_path: str = ""
    dump_freq: int = 50
    need_bias: bool = True
    bias_feature_name: str = "_bias_"
    continue_train: bool = False
    field_dict_path: str = ""  # FFM (reference: dataflow/FFMModelDataFlow.java:234-241)
    feature_importance_path: str = ""  # GBDT

    @classmethod
    def from_config(cls, cfg: dict) -> "ModelParams":
        return cls(
            data_path=str(_req(cfg, "model.data_path")),
            delim=str(_opt(cfg, "model.delim", ",")),
            need_dict=bool(_opt(cfg, "model.need_dict", False)),
            dict_path=_opt_path(cfg, "model.dict_path"),
            dump_freq=int(_opt(cfg, "model.dump_freq", 50)),
            need_bias=bool(_opt(cfg, "model.need_bias", True)),
            bias_feature_name=str(_opt(cfg, "model.bias_feature_name", "_bias_")),
            continue_train=bool(_opt(cfg, "model.continue_train", False)),
            field_dict_path=_opt_path(cfg, "model.field_dict_path"),
            feature_importance_path=_opt_path(cfg, "model.feature_importance_path"),
        )


@dataclass
class LossParams:
    """reference: param/LossParams.java:41."""

    loss_function: str = "sigmoid"
    evaluate_metric: List[str] = field(default_factory=lambda: ["auc"])
    just_evaluate: bool = False
    l1: List[float] = field(default_factory=lambda: [0.0])
    l2: List[float] = field(default_factory=lambda: [0.0])

    @classmethod
    def from_config(cls, cfg: dict) -> "LossParams":
        return cls(
            loss_function=str(_opt(cfg, "loss.loss_function", "sigmoid")),
            evaluate_metric=list(_opt(cfg, "loss.evaluate_metric", ["auc"]) or []),
            just_evaluate=bool(_opt(cfg, "loss.just_evaluate", False)),
            l1=[float(x) for x in _opt(cfg, "loss.regularization.l1", [0.0])],
            l2=[float(x) for x in _opt(cfg, "loss.regularization.l2", [0.0])],
        )


@dataclass
class LineSearchParams:
    """reference: param/LineSearchParams.java:43."""

    mode: str = "wolfe"  # sufficient_decrease | wolfe | strong_wolfe
    step_decr: float = 0.5
    step_incr: float = 2.1
    max_iter: int = 55
    min_step: float = 1e-16
    max_step: float = 1e18
    c1: float = 1e-4
    c2: float = 0.9
    lbfgs_m: int = 8
    lbfgs_max_iter: int = 60
    lbfgs_eps: float = 1e-3

    @classmethod
    def from_config(cls, cfg: dict) -> "LineSearchParams":
        base = "optimization.line_search"
        return cls(
            mode=str(_opt(cfg, f"{base}.mode", "wolfe")),
            step_decr=float(_opt(cfg, f"{base}.backtracking.step_decr", 0.5)),
            step_incr=float(_opt(cfg, f"{base}.backtracking.step_incr", 2.1)),
            max_iter=int(_opt(cfg, f"{base}.backtracking.max_iter", 55)),
            min_step=float(_opt(cfg, f"{base}.backtracking.min_step", 1e-16)),
            max_step=float(_opt(cfg, f"{base}.backtracking.max_step", 1e18)),
            c1=float(_opt(cfg, f"{base}.backtracking.c1", 1e-4)),
            c2=float(_opt(cfg, f"{base}.backtracking.c2", 0.9)),
            lbfgs_m=int(_opt(cfg, f"{base}.lbfgs.m", 8)),
            lbfgs_max_iter=int(_opt(cfg, f"{base}.lbfgs.convergence.max_iter", 60)),
            lbfgs_eps=float(_opt(cfg, f"{base}.lbfgs.convergence.eps", 1e-3)),
        )


@dataclass
class HyperParams:
    """reference: param/HyperParams.java:41 (grid + HOAG hyper search)."""

    switch_on: bool = False
    restart: bool = False
    mode: str = "hoag"  # hoag | grid
    hoag_init_step: float = 1.0
    hoag_step_decr_factor: float = 0.7
    hoag_test_loss_reduce_limit: float = 1e-5
    hoag_outer_iter: int = 10
    hoag_l1: List[float] = field(default_factory=lambda: [0.0])
    hoag_l2: List[float] = field(default_factory=lambda: [0.0])
    grid_l1: List[float] = field(default_factory=list)
    grid_l2: List[float] = field(default_factory=list)

    @classmethod
    def from_config(cls, cfg: dict) -> "HyperParams":
        return cls(
            switch_on=bool(_opt(cfg, "hyper.switch_on", False)),
            restart=bool(_opt(cfg, "hyper.restart", False)),
            mode=str(_opt(cfg, "hyper.mode", "hoag")),
            hoag_init_step=float(_opt(cfg, "hyper.hoag.init_step", 1.0)),
            hoag_step_decr_factor=float(_opt(cfg, "hyper.hoag.step_decr_factor", 0.7)),
            hoag_test_loss_reduce_limit=float(_opt(cfg, "hyper.hoag.test_loss_reduce_limit", 1e-5)),
            hoag_outer_iter=int(_opt(cfg, "hyper.hoag.outer_iter", 10)),
            hoag_l1=[float(x) for x in _opt(cfg, "hyper.hoag.l1", [0.0])],
            hoag_l2=[float(x) for x in _opt(cfg, "hyper.hoag.l2", [0.0])],
            grid_l1=[float(x) for x in _opt(cfg, "hyper.grid.l1", [])],
            grid_l2=[float(x) for x in _opt(cfg, "hyper.grid.l2", [])],
        )


@dataclass
class ContinualParams:
    """Continuous-training block (`continual.*`; no reference counterpart —
    the reference's serving story was retrain-offline + restart). Drives
    the `ytklearn-tpu retrain` driver (docs/continual.md)."""

    mode: str = "warm"  # warm (full warm-start refit) | ftrl (online pass)
    extra_rounds: int = 10  # extra boosting rounds per GBDT/GBST retrain
    band: float = -1.0  # held-out loss tolerance; < 0 -> YTK_CONTINUAL_BAND
    # FTRL-proximal hyperparameters (McMahan et al., KDD 2013 — PAPERS.md)
    ftrl_alpha: float = 0.1
    ftrl_beta: float = 1.0
    ftrl_l1: float = 0.0
    ftrl_l2: float = 0.0
    batch_rows: int = 8192  # streaming minibatch rows for the FTRL pass

    @classmethod
    def from_config(cls, cfg: dict) -> "ContinualParams":
        return cls(
            mode=str(_opt(cfg, "continual.mode", "warm")),
            extra_rounds=int(_opt(cfg, "continual.extra_rounds", 10)),
            band=float(_opt(cfg, "continual.band", -1.0)),
            ftrl_alpha=float(_opt(cfg, "continual.ftrl.alpha", 0.1)),
            ftrl_beta=float(_opt(cfg, "continual.ftrl.beta", 1.0)),
            ftrl_l1=float(_opt(cfg, "continual.ftrl.l1", 0.0)),
            ftrl_l2=float(_opt(cfg, "continual.ftrl.l2", 0.0)),
            batch_rows=int(_opt(cfg, "continual.batch_rows", 8192)),
        )


@dataclass
class RandomParams:
    """Latent-factor init distributions (reference: param/RandomParams.java:40)."""

    mode: str = "normal"  # normal | uniform
    seed: int = 111111
    normal_mean: float = 0.0
    normal_std: float = 0.01
    uniform_range_start: float = -0.01
    uniform_range_end: float = 0.01

    @classmethod
    def from_config(cls, cfg: dict) -> "RandomParams":
        return cls(
            mode=str(_opt(cfg, "random.mode", "normal")),
            seed=int(_opt(cfg, "random.seed", 111111)),
            normal_mean=float(_opt(cfg, "random.normal.mean", 0.0)),
            normal_std=float(_opt(cfg, "random.normal.std", 0.01)),
            uniform_range_start=float(_opt(cfg, "random.uniform.range_start", -0.01)),
            uniform_range_end=float(_opt(cfg, "random.uniform.range_end", 0.01)),
        )


@dataclass
class CommonParams:
    """Aggregate of the shared blocks (reference: param/CommonParams.java:40-45)
    plus the model-specific top-level scalars that live at root in the configs."""

    fs_scheme: str = "local"
    verbose: bool = False
    data: DataParams = field(default_factory=DataParams)
    feature: FeatureParams = field(default_factory=FeatureParams)
    model: ModelParams = field(default_factory=ModelParams)
    loss: LossParams = field(default_factory=LossParams)
    line_search: LineSearchParams = field(default_factory=LineSearchParams)
    hyper: HyperParams = field(default_factory=HyperParams)
    random: RandomParams = field(default_factory=RandomParams)
    continual: ContinualParams = field(default_factory=ContinualParams)

    # model-specific root-level scalars
    k: Any = None  # int (multiclass/gbst) or [use_first_order, dim] (fm/ffm)
    bias_need_latent_factor: bool = False
    instance_sample_rate: float = 1.0
    feature_sample_rate: float = 1.0
    uniform_base_prediction: float = 0.5
    sample_dependent_base_prediction: bool = False
    tree_num: int = 1
    learning_rate: float = 1.0
    gbst_type: str = "gradient_boosting"  # gradient_boosting | random_forest
    leaf_random_init_range: List[float] = field(default_factory=lambda: [-2.0, 2.0])

    raw: dict = field(default_factory=dict, repr=False)

    @classmethod
    def from_config(cls, cfg: dict) -> "CommonParams":
        return cls(
            fs_scheme=str(_opt(cfg, "fs_scheme", "local")),
            verbose=bool(_opt(cfg, "verbose", False)),
            data=DataParams.from_config(cfg),
            feature=FeatureParams.from_config(cfg),
            model=ModelParams.from_config(cfg),
            loss=LossParams.from_config(cfg),
            line_search=LineSearchParams.from_config(cfg),
            hyper=HyperParams.from_config(cfg),
            random=RandomParams.from_config(cfg),
            continual=ContinualParams.from_config(cfg),
            k=_opt(cfg, "k", None),
            bias_need_latent_factor=bool(_opt(cfg, "bias_need_latent_factor", False)),
            instance_sample_rate=float(_opt(cfg, "instance_sample_rate", 1.0)),
            feature_sample_rate=float(_opt(cfg, "feature_sample_rate", 1.0)),
            uniform_base_prediction=float(_opt(cfg, "uniform_base_prediction", 0.5)),
            sample_dependent_base_prediction=bool(
                _opt(cfg, "sample_dependent_base_prediction", False)
            ),
            tree_num=int(_opt(cfg, "tree_num", 1)),
            learning_rate=float(_opt(cfg, "learning_rate", 1.0)),
            gbst_type=str(_opt(cfg, "type", "gradient_boosting")),
            leaf_random_init_range=[
                float(x) for x in _opt(cfg, "leaf_random_init_range", [-2.0, 2.0])
            ],
            raw=cfg,
        )

    @classmethod
    def from_file(cls, path: str) -> "CommonParams":
        return cls.from_config(hocon.load(path))


# ---------------------------------------------------------------------------
# GBDT params (reference: param/gbdt/*)
# ---------------------------------------------------------------------------


@dataclass
class ApproximateSpec:
    """One entry of feature.approximate (reference: param/gbdt/GBDTFeatureParams.java:45,
    feature/gbdt/approximate/sampler/SamplerFactory.java)."""

    cols: str = "default"
    type: str = "sample_by_quantile"
    max_cnt: int = 255
    quantile_approximate_bin_factor: int = 8
    use_sample_weight: bool = False
    alpha: float = 1.0
    sample_rate: float = 1.0
    min_cnt: int = 0
    dot_precision: int = 5
    use_log: bool = False
    use_min_max: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "ApproximateSpec":
        return cls(
            cols=str(d.get("cols", "default")),
            type=str(d.get("type", "sample_by_quantile")),
            max_cnt=int(d.get("max_cnt", 255)),
            quantile_approximate_bin_factor=int(d.get("quantile_approximate_bin_factor", 8)),
            use_sample_weight=bool(d.get("use_sample_weight", False)),
            alpha=float(d.get("alpha", 1.0)),
            sample_rate=float(d.get("sample_rate", 1.0)),
            min_cnt=int(d.get("min_cnt", 0)),
            dot_precision=int(d.get("dot_precision", 5)),
            use_log=bool(d.get("use_log", False)),
            use_min_max=bool(d.get("use_min_max", False)),
        )


@dataclass
class GBDTParams:
    """reference: param/gbdt/GBDTCommonParams.java:46, GBDTOptimizationParams.java:46,
    GBDTFeatureParams.java:45, GBDTDataParams.java:39, GBDTModelParams.java:38."""

    fs_scheme: str = "local"
    verbose: bool = False
    gbdt_type: str = "gradient_boosting"  # gradient_boosting | random_forest
    data: DataParams = field(default_factory=DataParams)
    model: ModelParams = field(default_factory=ModelParams)
    continual: ContinualParams = field(default_factory=ContinualParams)

    # optimization block
    tree_maker: str = "data"  # data | feature
    tree_grow_policy: str = "level"  # level | loss
    round_num: int = 50
    max_depth: int = 5
    min_child_hessian_sum: float = 1e-8
    max_abs_leaf_val: float = -1.0
    min_split_loss: float = 0.0
    min_split_samples: int = 2
    max_leaf_cnt: int = 128
    histogram_pool_capacity: int = -1
    loss_function: str = "sigmoid"
    sigmoid_zmax: float = 0.0
    lad_refine_appr: bool = True
    learning_rate: float = 0.09
    l1: float = 0.0
    l2: float = 1.0
    uniform_base_prediction: float = 0.5
    sample_dependent_base_prediction: bool = False
    instance_sample_rate: float = 1.0
    feature_sample_rate: float = 1.0
    class_num: int = 1
    just_evaluate: bool = False
    eval_metric: List[str] = field(default_factory=lambda: ["auc"])
    watch_train: bool = False
    watch_test: bool = False

    # feature block
    split_type: str = "mean"  # mean | median
    approximate: List[ApproximateSpec] = field(default_factory=list)
    missing_value: str = "value"  # mean | quantile[@q] | value[@v]
    filter_threshold: int = 0

    raw: dict = field(default_factory=dict, repr=False)

    @classmethod
    def from_config(cls, cfg: dict) -> "GBDTParams":
        appr = [
            ApproximateSpec.from_dict(d)
            for d in (_opt(cfg, "feature.approximate", []) or [])
            if isinstance(d, dict)
        ]
        if not appr:
            appr = [ApproximateSpec()]
        o = "optimization"
        return cls(
            fs_scheme=str(_opt(cfg, "fs_scheme", "local")),
            verbose=bool(_opt(cfg, "verbose", False)),
            gbdt_type=str(_opt(cfg, "type", "gradient_boosting")),
            data=DataParams.from_config(cfg),
            model=ModelParams.from_config(cfg),
            continual=ContinualParams.from_config(cfg),
            tree_maker=str(_opt(cfg, f"{o}.tree_maker", "data")),
            tree_grow_policy=str(_opt(cfg, f"{o}.tree_grow_policy", "level")),
            round_num=int(_opt(cfg, f"{o}.round_num", 50)),
            max_depth=int(_opt(cfg, f"{o}.max_depth", 5)),
            min_child_hessian_sum=float(_opt(cfg, f"{o}.min_child_hessian_sum", 1e-8)),
            max_abs_leaf_val=float(_opt(cfg, f"{o}.max_abs_leaf_val", -1.0)),
            min_split_loss=float(_opt(cfg, f"{o}.min_split_loss", 0.0)),
            min_split_samples=int(_opt(cfg, f"{o}.min_split_samples", 2)),
            max_leaf_cnt=int(_opt(cfg, f"{o}.max_leaf_cnt", 128)),
            histogram_pool_capacity=int(_opt(cfg, f"{o}.histogram_pool_capacity", -1)),
            loss_function=str(_opt(cfg, f"{o}.loss_function", "sigmoid")),
            sigmoid_zmax=float(_opt(cfg, f"{o}.sigmoid_zmax", 0.0)),
            lad_refine_appr=bool(_opt(cfg, f"{o}.lad_refine_appr", True)),
            learning_rate=float(_opt(cfg, f"{o}.regularization.learning_rate", 0.09)),
            l1=float(_opt(cfg, f"{o}.regularization.l1", 0.0)),
            l2=float(_opt(cfg, f"{o}.regularization.l2", 1.0)),
            uniform_base_prediction=float(_opt(cfg, f"{o}.uniform_base_prediction", 0.5)),
            sample_dependent_base_prediction=bool(
                _opt(cfg, f"{o}.sample_dependent_base_prediction", False)
            ),
            instance_sample_rate=float(_opt(cfg, f"{o}.instance_sample_rate", 1.0)),
            feature_sample_rate=float(_opt(cfg, f"{o}.feature_sample_rate", 1.0)),
            class_num=int(_opt(cfg, f"{o}.class_num", 1)),
            just_evaluate=bool(_opt(cfg, f"{o}.just_evaluate", False)),
            eval_metric=list(_opt(cfg, f"{o}.eval_metric", ["auc"]) or []),
            watch_train=bool(_opt(cfg, f"{o}.watch_train", False)),
            watch_test=bool(_opt(cfg, f"{o}.watch_test", False)),
            split_type=str(_opt(cfg, "feature.split_type", "mean")),
            approximate=appr,
            missing_value=str(_opt(cfg, "feature.missing_value", "value")),
            filter_threshold=int(_opt(cfg, "feature.filter_threshold", 0)),
            raw=cfg,
        )

    @classmethod
    def from_file(cls, path: str) -> "GBDTParams":
        return cls.from_config(hocon.load(path))

    @property
    def num_tree_in_group(self) -> int:
        """Trees per boosting round (reference: GBDTOptimizer numTreeInGroup):
        softmax multiclass grows class_num trees per round."""
        return self.class_num if self.loss_function == "softmax" and self.class_num > 1 else 1
