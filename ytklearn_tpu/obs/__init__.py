"""ytklearn_tpu.obs — unified tracing/metrics subsystem.

Public surface (see docs/observability.md):

  span(name, settle=None, **attrs)   nested wall-clock span (ctx manager)
  inc(name, value=1.0)               counter add
  gauge(name, value)                 gauge set
  event(name, **attrs)               instant trace marker
  heartbeat(name, every_s=30)        rate-limited structured progress logger
  enabled() / configure(...)         state; YTK_TRACE / YTK_OBS env knobs
  snapshot() / reset()               registry access
  flush()                            write configured exports now
  export_chrome_trace / export_jsonl / load_jsonl

Run-health layer (obs/health.py, obs/recorder.py — docs/observability.md):

  health                             NaN/divergence/ingest/tree sentinels,
                                     mem.* + compile.traces.* telemetry;
                                     YTK_HEALTH / YTK_HEALTH_STRICT knobs
  recorder                           flight recorder: bounded event ring +
                                     postmortem flight_<ts>.json dump on
                                     abnormal exit; YTK_FLIGHT_* knobs
  HealthError                        strict-mode sentinel escalation
  SLOBurnSentinel                    serving SLO burn-rate alarm
                                     (health.slo_burn)

Serve-side request tracing + metrics history (obs/trace.py,
Registry.history — docs/observability.md "Request tracing"):

  trace                              per-hop request tracing: deterministic
                                     head sampler, X-Ytk-Trace context
                                     propagation, tail-retained exemplar
                                     ring (/admin/traces);
                                     YTK_TRACE_SAMPLE / _SEED / _EXEMPLARS
  start_history_sampler              per-metric (ts, value) rings sampled
                                     by the obs heartbeat thread, exported
                                     at /metrics?history=1;
                                     YTK_OBS_HISTORY_{N,S}

Model-quality plane (obs/quality.py — docs/observability.md
"Model-quality plane"):

  quality                            train-time `<model>.sketch.json` GK
                                     baselines, serve-side drift/
                                     calibration monitor (deterministic
                                     row sampler, PSI/KS, health.drift /
                                     health.calibration sentinels),
                                     fleet merge of per-replica sketches;
                                     YTK_QUALITY_* / YTK_HEALTH_DRIFT_*

Profiling plane (obs/profiler.py — docs/observability.md "Profiling
plane"):

  profiler                           ytkprof: phase accounting with
                                     settled wall time + per-phase
                                     jax.profiler captures (device-time
                                     buckets per span, top-k kernel
                                     table), compile ledger (program
                                     label + abstract-signature diff →
                                     named retrace culprits), background
                                     memory-watermark sampler with
                                     phase-attributed peaks;
                                     YTK_PROF / YTK_PROF_* knobs
"""

from .core import (  # noqa: F401
    NOOP_SPAN,
    REGISTRY,
    Registry,
    Span,
    configure,
    enabled,
    event,
    flush,
    gauge,
    inc,
    record_collective,
    reset,
    set_identity,
    snapshot,
    span,
)
from .export import (  # noqa: F401
    chrome_trace_events,
    exemplar_trace_events,
    export_chrome_trace,
    export_jsonl,
    load_jsonl,
)
from .heartbeat import (  # noqa: F401
    Heartbeat,
    heartbeat,
    start_history_sampler,
    stop_history_sampler,
)
from . import health, profiler, recorder, trace  # noqa: F401
from .health import HealthError, SLOBurnSentinel  # noqa: F401
from .trace import TRACE_HEADER, configure_tracing  # noqa: F401
