"""Rate-limited structured progress logging — the replacement for bare
`print(..., file=sys.stderr)` progress lines.

A Heartbeat logs through the standard logging stack at most once per
`every_s` seconds (the first beat always fires), and mirrors each emitted
beat into the obs registry as an instant event + a beat counter when obs
is enabled. Call `.beat(...)` as often as you like from a loop; the cost
of a suppressed beat is one time.time() call.

Derived rates: for every numeric field, an emitted beat also reports the
rate since the PREVIOUS emitted beat (`rows=512000` grows a
`rows_per_s=17066.7`), so a 30 s ingest heartbeat reads as throughput,
not as a cumulative count you must difference by hand. Rates are computed
between fired beats only (suppressed beats don't reset the window), skip
non-monotone fields (a counter that went down is re-baselined, not
reported as a negative rate), and never appear on the first beat.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from . import core
from .recorder import thread_guard
from ..config import knobs

log = logging.getLogger("ytklearn_tpu.obs")


class Heartbeat:
    __slots__ = ("name", "every_s", "_last", "_log", "_prev", "_prev_t")

    def __init__(
        self,
        name: str,
        every_s: float = 30.0,
        logger: Optional[logging.Logger] = None,
    ):
        self.name = name
        self.every_s = float(every_s)
        self._last = 0.0  # epoch 0 -> the first beat always fires
        self._log = logger or log
        self._prev: Dict[str, float] = {}  # numeric fields at last fired beat
        self._prev_t = 0.0

    def _rates(self, now: float, fields: dict) -> Dict[str, float]:
        dt = now - self._prev_t
        rates: Dict[str, float] = {}
        if self._prev and dt > 0:
            for k, v in fields.items():
                prev = self._prev.get(k)
                if (
                    prev is not None
                    and isinstance(v, (int, float))
                    and not isinstance(v, bool)
                    and v >= prev
                ):
                    rates[f"{k}_per_s"] = round((v - prev) / dt, 1)
        return rates

    def beat(self, msg: str = "", force: bool = False, **fields) -> bool:
        """Emit one progress line (+ obs event) unless rate-limited.
        Returns True when the beat fired."""
        now = time.time()
        if not force and (now - self._last) < self.every_s:
            return False
        self._last = now
        rates = self._rates(now, fields)
        text = msg
        shown = {**fields, **rates}
        if shown:
            kv = " ".join(f"{k}={v}" for k, v in shown.items())
            text = f"{text} {kv}".strip()
        self._log.info("[%s] %s", self.name, text)
        if core.enabled():
            core.REGISTRY.inc(f"heartbeat.{self.name}", 1.0)
            core.event(f"heartbeat.{self.name}", msg=text, **rates)
        # re-baseline on every fired beat (rates are beat-to-beat)
        self._prev = {
            k: float(v)
            for k, v in fields.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        self._prev_t = now
        return True


def heartbeat(name: str, every_s: float = 30.0, logger=None) -> Heartbeat:
    return Heartbeat(name, every_s=every_s, logger=logger)


# ---------------------------------------------------------------------------
# Metrics-history sampler: the obs heartbeat thread
# ---------------------------------------------------------------------------

#: the singleton sampler thread + its stop event; guarded by _sampler_lock
#: (start is called from ServeApp/FleetFront start paths concurrently)
_sampler: Optional[threading.Thread] = None
_sampler_stop: Optional[threading.Event] = None
_sampler_lock = threading.Lock()


@thread_guard
def _sampler_loop(stop: threading.Event, interval_s: float) -> None:
    while not stop.wait(interval_s):
        if core.enabled():
            core.REGISTRY.sample_history()


def start_history_sampler(
    interval_s: Optional[float] = None, ring_n: Optional[int] = None
) -> bool:
    """Arm the metrics history plane: per-metric (ts, value) rings on the
    registry plus one process-wide daemon thread sampling them every
    `interval_s` (YTK_OBS_HISTORY_S). Idempotent — the serving layer calls
    this at every start(). Returns True when the plane is armed, False
    when YTK_OBS_HISTORY_N=0 disables it."""
    global _sampler, _sampler_stop
    n = ring_n if ring_n is not None else knobs.get_int("YTK_OBS_HISTORY_N")
    if not n or n <= 0:
        return False
    every = (interval_s if interval_s is not None
             else knobs.get_float("YTK_OBS_HISTORY_S")) or 1.0
    core.REGISTRY.enable_history(n)
    core.REGISTRY.sample_history()  # t=0 sample: history is never empty
    with _sampler_lock:
        if _sampler is not None and _sampler.is_alive():
            return True
        stop = threading.Event()
        t = threading.Thread(
            target=_sampler_loop, args=(stop, float(every)),
            name="ytk-obs-history", daemon=True,
        )
        _sampler, _sampler_stop = t, stop
        t.start()
    return True


def stop_history_sampler(disable: bool = True) -> None:
    """Stop the sampler thread (joined) and, by default, drop the history
    rings — test isolation; production processes just exit."""
    global _sampler, _sampler_stop
    with _sampler_lock:
        t, stop = _sampler, _sampler_stop
        _sampler, _sampler_stop = None, None
    if stop is not None:
        stop.set()
    if t is not None:
        t.join(timeout=10.0)
    if disable:
        core.REGISTRY.disable_history()
