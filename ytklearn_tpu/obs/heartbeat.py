"""Rate-limited structured progress logging — the replacement for bare
`print(..., file=sys.stderr)` progress lines.

A Heartbeat logs through the standard logging stack at most once per
`every_s` seconds (the first beat always fires), and mirrors each emitted
beat into the obs registry as an instant event + a beat counter when obs
is enabled. Call `.beat(...)` as often as you like from a loop; the cost
of a suppressed beat is one time.time() call.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from . import core

log = logging.getLogger("ytklearn_tpu.obs")


class Heartbeat:
    __slots__ = ("name", "every_s", "_last", "_log")

    def __init__(
        self,
        name: str,
        every_s: float = 30.0,
        logger: Optional[logging.Logger] = None,
    ):
        self.name = name
        self.every_s = float(every_s)
        self._last = 0.0  # epoch 0 -> the first beat always fires
        self._log = logger or log

    def beat(self, msg: str = "", force: bool = False, **fields) -> bool:
        """Emit one progress line (+ obs event) unless rate-limited.
        Returns True when the beat fired."""
        now = time.time()
        if not force and (now - self._last) < self.every_s:
            return False
        self._last = now
        text = msg
        if fields:
            kv = " ".join(f"{k}={v}" for k, v in fields.items())
            text = f"{text} {kv}".strip()
        self._log.info("[%s] %s", self.name, text)
        if core.enabled():
            core.REGISTRY.inc(f"heartbeat.{self.name}", 1.0)
            core.event(f"heartbeat.{self.name}", msg=text)
        return True


def heartbeat(name: str, every_s: float = 30.0, logger=None) -> Heartbeat:
    return Heartbeat(name, every_s=every_s, logger=logger)
