"""Run-health sentinels + memory/recompilation telemetry.

The r7 obs layer records evidence; this module *interprets* it at the few
places the host already syncs with the device — so a diverging L-BFGS run,
a NaN loss, an empty boosted tree, a retrace storm, or a rotten input file
raises a flag (or, in strict mode, a `HealthError` carrying a flight-dump
path) instead of finishing with garbage numbers.

Sentinels (all fire `health.*` counters + an `obs.event`, and log):

  check_loss(site, value)        NaN/inf detection on an already-fetched
                                 host float; strict -> HealthError
  ProgressGuard(site, window)    no-progress divergence: `window`
                                 consecutive checks without relative
                                 improvement fires `health.divergence`
  check_ingest(site, errors, rows)  parse error-rate threshold
                                 (YTK_HEALTH_INGEST_TOL, default 1%)
  check_tree(site, n_nodes, gains)  empty-tree / NaN-gain detection on the
                                 host-side tree conversion
  SLOBurnSentinel(site, slo_ms)  serving SLO burn-rate: windowed request
                                 violation rate over the error budget
                                 fires `health.slo_burn`
                                 (YTK_SLO_BURN_{WINDOW,BUDGET})
  DriftSentinel(site)            serving input drift: consecutive
                                 quality-evaluator ticks with per-feature
                                 PSI/KS over threshold fire `health.drift`
                                 (YTK_HEALTH_DRIFT_{PSI,KS,WINDOWS,
                                 MIN_ROWS}; obs/quality.py feeds it)
  CalibrationSentinel(site)      mean predicted score vs the training
                                 sidecar's score distribution fires
                                 `health.calibration`
                                 (YTK_HEALTH_CALIBRATION_TOL)

Telemetry:

  record_memory(phase)           per-phase peak device memory
                                 (device.memory_stats() where the backend
                                 reports it; host RSS fallback) as `mem.*`
                                 gauges
  install_trace_counters()       jax.monitoring listeners -> `compile.traces.*`
                                 counters (XLA backend compiles, jaxpr
                                 traces, cache hits)
  RetraceSentinel(site)          warmup-armed: a compile counted after
                                 arm() fires `health.retrace` — the
                                 unexpected-recompilation alarm for steady
                                 loops

Knobs:
  YTK_HEALTH=0            opt out of every sentinel (checks become one
                          attribute load + return — tier-1 contract)
  YTK_HEALTH_STRICT=1     escalate sentinel hits to HealthError (message
                          names the flight dump; read per-hit so tests and
                          operators can flip it at runtime)
  YTK_HEALTH_INGEST_TOL   ingest error-rate threshold (fraction, 0.01)

Counters fire only while obs collection is enabled (`inc` is a no-op
otherwise); detection itself — and strict escalation — work either way,
so an un-instrumented production run still dies loudly instead of
silently. Disabled-path contract pinned in tests/test_health.py.
"""

from __future__ import annotations

import logging
import math
import os
import threading
from typing import Optional, Sequence

from . import core, recorder
from ..config import knobs

log = logging.getLogger("ytklearn_tpu.obs.health")

#: sites with fewer parsed lines than this never trip the ingest sentinel
#: (a 10-line smoke file with one typo is not a pipeline regression)
INGEST_MIN_LINES = 100


class HealthError(RuntimeError):
    """A sentinel hit under YTK_HEALTH_STRICT=1. `dump_path` names the
    flight dump written at escalation time ("" when dumping failed)."""

    def __init__(self, message: str, dump_path: str = ""):
        super().__init__(message)
        self.dump_path = dump_path


class _HealthState:
    __slots__ = ("on", "strict", "ingest_tol")

    def __init__(self):
        self.on = knobs.get_bool("YTK_HEALTH")
        self.strict: Optional[bool] = None  # None -> read env per hit
        self.ingest_tol = knobs.get_float("YTK_HEALTH_INGEST_TOL")


_state = _HealthState()


def enabled() -> bool:
    return _state.on


def configure_health(
    on: Optional[bool] = None,
    strict: Optional[bool] = None,
    ingest_tol: Optional[float] = None,
) -> None:
    """Runtime override of the YTK_HEALTH* env knobs (tests; operators)."""
    if on is not None:
        _state.on = bool(on)
    if strict is not None:
        _state.strict = bool(strict)
    if ingest_tol is not None:
        _state.ingest_tol = float(ingest_tol)


def _strict() -> bool:
    if _state.strict is not None:
        return _state.strict
    return knobs.get_bool("YTK_HEALTH_STRICT")


def _fire(kind: str, site: str, msg: str, escalate: bool = True, **args) -> None:
    """Record one sentinel hit: `health.<kind>` counters + an instant obs
    event + a warning log line; under strict (and `escalate`) dump the
    flight ring and raise HealthError naming the dump."""
    core.inc(f"health.{kind}")
    core.inc(f"health.{kind}.{site}")
    core.event(f"health.{kind}", site=site, **args)
    log.warning("[health.%s] %s: %s", kind, site, msg)
    if escalate and _strict():
        path = recorder.dump(reason=f"health.{kind}:{site}")
        raise HealthError(
            f"health.{kind} at {site}: {msg} (flight dump: {path or 'unavailable'})",
            dump_path=path,
        )


def check_loss(site: str, value: float, **args) -> bool:
    """NaN/inf sentinel on an already-materialized loss. True = healthy."""
    if not _state.on:
        return True
    if math.isfinite(value):
        return True
    _fire("nan", site, f"non-finite loss {value!r}", value=repr(value), **args)
    return False


class ProgressGuard:
    """No-progress divergence detection over a sliding window of loss
    fetches: `window` consecutive updates without `rel_tol` relative
    improvement over the best-seen value fires `health.divergence` once
    (then re-arms, so a long plateau fires once per window, not per step).

    Observability-only by design: a plateau can be legitimate (the
    optimizer's own convergence test is the stopping authority), so even
    strict mode only flags it — escalation is reserved for NaN/inf.
    """

    __slots__ = ("site", "window", "rel_tol", "best", "stalled")

    def __init__(self, site: str, window: int = 10, rel_tol: float = 1e-7):
        self.site = site
        self.window = window
        self.rel_tol = rel_tol
        self.best = math.inf
        self.stalled = 0

    def update(self, value: float, **args) -> bool:
        """True = still making progress (or health off / not yet stalled)."""
        if not _state.on:
            return True
        if not math.isfinite(value):
            return True  # check_loss owns the NaN path
        if self.best == math.inf or value < self.best - self.rel_tol * max(
            abs(self.best), 1.0
        ):
            self.best = value
            self.stalled = 0
            return True
        self.stalled += 1
        if self.stalled < self.window:
            return True
        _fire(
            "divergence",
            self.site,
            f"no loss improvement in {self.stalled} checks "
            f"(best {self.best:.6g}, latest {value:.6g})",
            escalate=False,
            best=self.best,
            latest=value,
            stalled=self.stalled,
            **args,
        )
        self.stalled = 0  # re-arm
        return False


def check_ingest(site: str, errors: int, rows: int, **args) -> bool:
    """Parse error-rate sentinel. `max_error_tol` (an absolute count from
    the reference config) stays the hard abort; this catches the *rate*
    regression under it — a feed that is 5% garbage but below the absolute
    cap. True = healthy."""
    if not _state.on:
        return True
    total = errors + rows
    if total < INGEST_MIN_LINES or errors == 0:
        return True
    rate = errors / total
    if rate <= _state.ingest_tol:
        return True
    _fire(
        "ingest_errors",
        site,
        f"{errors}/{total} lines bad ({100 * rate:.2f}% > "
        f"{100 * _state.ingest_tol:.2f}% tolerance)",
        errors=errors,
        rows=rows,
        rate=round(rate, 5),
        **args,
    )
    return False


def check_tree(site: str, n_nodes: int, gains: Sequence[float], **args) -> bool:
    """Boosted-tree sanity on the host conversion: an empty tree (no
    split found — the learner has stopped learning) or a NaN gain (the
    split statistics went rotten upstream). True = healthy."""
    if not _state.on:
        return True
    ok = True
    if n_nodes <= 1:
        # warning-level like divergence: boosting can legitimately
        # saturate into stump trees (round_num oversized for the data) —
        # escalating would abort mid-conversion and discard a valid model
        _fire("empty_tree", site, "tree has no splits", escalate=False, **args)
        ok = False
    bad = [g for g in gains if not math.isfinite(g)]
    if bad:
        _fire(
            "nan",
            site,
            f"{len(bad)} non-finite split gain(s)",
            bad_gains=len(bad),
            **args,
        )
        ok = False
    return ok


class SLOBurnSentinel:
    """SLO burn-rate alarm for the serving layer (Clipper's SLO-first
    argument applied to the r8 sentinel discipline): observe() every
    request's client-visible latency (or an explicit violation — a shed
    429 / deadline 504 burned budget without ever being scored), and once
    per full window of `window` requests judge the violation rate against
    the error `budget`. Crossing it fires `health.slo_burn` (counter +
    flight-ring event naming the rate, window, and SLO; strict mode
    escalates to HealthError like any other sentinel), then the window
    re-arms so a sustained burn fires once per window, not per request.

    Thread-safe: handler threads observe concurrently; the counters are
    advanced under a tiny lock and the fire happens OUTSIDE it (the
    strict path writes a flight dump — IO under a request-path lock would
    be a ytklint blocking-call-under-lock finding and a real stall).
    """

    __slots__ = ("site", "slo_ms", "window", "budget", "_viol", "_n",
                 "_lock", "windows_fired")

    def __init__(
        self,
        site: str,
        slo_ms: float,
        window: Optional[int] = None,
        budget: Optional[float] = None,
    ):
        self.site = site
        self.slo_ms = float(slo_ms)
        # no `or`-fallbacks here: the knobs carry declared defaults, and
        # an explicit 0 budget (zero-tolerance) must survive as 0
        self.window = max(1, int(
            window if window is not None
            else knobs.get_int("YTK_SLO_BURN_WINDOW")
        ))
        self.budget = float(
            budget if budget is not None
            else knobs.get_float("YTK_SLO_BURN_BUDGET")
        )
        self._viol = 0
        self._n = 0
        self._lock = threading.Lock()
        self.windows_fired = 0

    def observe(
        self, latency_ms: Optional[float] = None, violated: Optional[bool] = None,
        **args,
    ) -> bool:
        """Feed one request. True = budget intact (or health off)."""
        if not _state.on:
            return True
        if violated is None:
            violated = latency_ms is not None and latency_ms > self.slo_ms
        fire_rate = None
        with self._lock:
            self._n += 1
            if violated:
                self._viol += 1
            if self._n >= self.window:
                rate = self._viol / self._n
                if rate > self.budget:
                    fire_rate = rate
                    # counted under the lock (a lockless += here is the
                    # r14 _inflight lost-update shape); only the _fire —
                    # which may write a flight dump — stays outside
                    self.windows_fired += 1
                self._n = 0
                self._viol = 0
        if fire_rate is None:
            return True
        _fire(
            "slo_burn",
            self.site,
            f"SLO burn: {100 * fire_rate:.1f}% of the last {self.window} "
            f"requests violated the {self.slo_ms:g} ms SLO "
            f"(budget {100 * self.budget:.1f}%)",
            rate=round(fire_rate, 4),
            window=self.window,
            budget=self.budget,
            slo_ms=self.slo_ms,
            **args,
        )
        return False


class DriftSentinel:
    """Input-drift alarm for the serving quality plane (obs/quality.py):
    fed once per evaluator tick with the worst per-feature PSI and KS of
    a served model versus its training sidecar. `windows` CONSECUTIVE
    over-threshold ticks fire `health.drift` (counter + flight-ring
    event naming the model and the offending features; strict mode
    escalates like every sentinel), then the streak re-arms so a
    sustained drift fires once per `windows` ticks, not per tick. Ticks
    with fewer than `min_rows` sampled rows are never judged — a
    two-request warmup is not a distribution.

    Fed from ONE thread (the quality evaluator; metrics scrapes use
    feed_sentinels=False), so the streak counter needs no lock.
    """

    __slots__ = ("site", "psi_threshold", "ks_threshold", "windows",
                 "min_rows", "_over", "fired")

    def __init__(
        self,
        site: str,
        psi_threshold: Optional[float] = None,
        ks_threshold: Optional[float] = None,
        windows: Optional[int] = None,
        min_rows: Optional[int] = None,
    ):
        self.site = site
        self.psi_threshold = float(
            psi_threshold if psi_threshold is not None
            else knobs.get_float("YTK_HEALTH_DRIFT_PSI")
        )
        self.ks_threshold = float(
            ks_threshold if ks_threshold is not None
            else knobs.get_float("YTK_HEALTH_DRIFT_KS")
        )
        self.windows = max(1, int(
            windows if windows is not None
            else knobs.get_int("YTK_HEALTH_DRIFT_WINDOWS")
        ))
        self.min_rows = int(
            min_rows if min_rows is not None
            else knobs.get_int("YTK_HEALTH_DRIFT_MIN_ROWS")
        )
        self._over = 0
        self.fired = 0

    def observe(
        self,
        psi: Optional[float],
        ks: Optional[float],
        rows: int,
        **args,
    ) -> bool:
        """Feed one evaluator tick. True = no drift alarm (or health off
        / not enough rows yet)."""
        if not _state.on:
            return True
        if rows < self.min_rows:
            return True
        over = (psi is not None and psi > self.psi_threshold) or (
            ks is not None and ks > self.ks_threshold
        )
        if not over:
            self._over = 0
            return True
        self._over += 1
        if self._over < self.windows:
            return True
        self._over = 0  # re-arm
        self.fired += 1
        psi_txt = f"{psi:.3f}" if psi is not None else "n/a"
        ks_txt = f"{ks:.3f}" if ks is not None else "n/a"
        _fire(
            "drift",
            self.site,
            f"input drift: PSI {psi_txt} (threshold "
            f"{self.psi_threshold:g}) / KS {ks_txt} (threshold "
            f"{self.ks_threshold:g}) over {rows} sampled rows",
            psi=round(psi, 4) if psi is not None else None,
            ks=round(ks, 4) if ks is not None else None,
            rows=rows,
            **args,
        )
        return False


class CalibrationSentinel:
    """Calibration-drift alarm: the mean predicted score/probability of
    serving traffic versus the training sidecar's score distribution
    (the McMahan calibration check, label-free). `windows` consecutive
    evaluator ticks with |mean_pred - baseline_mean| above
    `YTK_HEALTH_CALIBRATION_TOL` fire `health.calibration`, then
    re-arm. Same single-feeder-thread contract as DriftSentinel."""

    __slots__ = ("site", "tol", "windows", "min_rows", "_over", "fired")

    def __init__(
        self,
        site: str,
        tol: Optional[float] = None,
        windows: Optional[int] = None,
        min_rows: Optional[int] = None,
    ):
        self.site = site
        self.tol = float(
            tol if tol is not None
            else knobs.get_float("YTK_HEALTH_CALIBRATION_TOL")
        )
        self.windows = max(1, int(
            windows if windows is not None
            else knobs.get_int("YTK_HEALTH_DRIFT_WINDOWS")
        ))
        self.min_rows = int(
            min_rows if min_rows is not None
            else knobs.get_int("YTK_HEALTH_DRIFT_MIN_ROWS")
        )
        self._over = 0
        self.fired = 0

    def observe(self, delta: Optional[float], rows: int, **args) -> bool:
        """Feed one evaluator tick with the absolute mean-prediction
        delta. True = calibration intact (or health off / warming up)."""
        if not _state.on:
            return True
        if delta is None or rows < self.min_rows:
            return True
        if delta <= self.tol:
            self._over = 0
            return True
        self._over += 1
        if self._over < self.windows:
            return True
        self._over = 0  # re-arm
        self.fired += 1
        _fire(
            "calibration",
            self.site,
            f"calibration drift: mean prediction off the training "
            f"baseline by {delta:.4f} (tolerance {self.tol:g}) over "
            f"{rows} sampled rows",
            delta=round(delta, 6),
            rows=rows,
            **args,
        )
        return False


def root_health_counters(counters) -> dict:
    """The ROOT `health.<kind>` counters (the per-site
    `health.<kind>.<site>` breakdown would double-count every hit). THE
    definition of "a sentinel fired" — bench.py, the regression gate's
    old-artifact fallback, and the continual promotion gate all consume
    it and must agree, or one gate compares skewed numbers."""
    return {
        k: v
        for k, v in counters.items()
        if k.startswith("health.") and k.count(".") == 1
    }


def total_sentinel_hits(counters) -> int:
    """Sum of the root sentinel counters (see root_health_counters)."""
    return int(sum(root_health_counters(counters).values()))


# ---------------------------------------------------------------------------
# Telemetry: memory watermarks + recompilation counters
# ---------------------------------------------------------------------------


def _host_rss_peak_bytes() -> Optional[float]:
    try:
        import resource

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes
        return float(rss * 1024 if os.uname().sysname == "Linux" else rss)
    # ytklint: allow(broad-except) reason=memory telemetry is best-effort; platforms without the resource module just skip the gauge
    except Exception:
        return None


def record_memory(phase: str) -> None:
    """Publish `mem.<phase>.*` gauges: per-device peak/in-use bytes where
    the backend exposes memory_stats() (TPU/GPU), host peak RSS always.
    One device query + two gauge writes — call at phase boundaries, never
    per row/round."""
    if not core.enabled():
        return
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    # ytklint: allow(broad-except) reason=backends without memory_stats() fall back to host RSS below
    except Exception:
        stats = None
    if stats:
        peak = stats.get("peak_bytes_in_use")
        in_use = stats.get("bytes_in_use")
        if peak is not None:
            core.gauge(f"mem.{phase}.device_peak_bytes", float(peak))
            prev = core.REGISTRY.gauges.get("mem.device_peak_bytes", 0.0)
            core.gauge("mem.device_peak_bytes", max(prev, float(peak)))
        if in_use is not None:
            core.gauge(f"mem.{phase}.device_bytes_in_use", float(in_use))
    rss = _host_rss_peak_bytes()
    if rss is not None:
        core.gauge(f"mem.{phase}.host_rss_peak_bytes", rss)
        core.gauge("mem.host_rss_peak_bytes", rss)


_trace_counters_installed = False


def install_trace_counters() -> None:
    """Route jax.monitoring compile/trace events into `compile.traces.*`
    counters (idempotent; listeners are process-global and cost one
    enabled() check per event when obs is off).

      compile.traces.backend_compile        XLA backend compiles
      compile.traces.backend_compile_secs   cumulative seconds
      compile.traces.jaxpr_trace            python->jaxpr traces
      compile.traces.cache_hits             persistent-cache hits
    """
    global _trace_counters_installed
    if _trace_counters_installed:
        return
    try:
        import jax.monitoring as monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if not core.enabled():
                return
            if event.endswith("backend_compile_duration"):
                core.inc("compile.traces.backend_compile")
                core.inc("compile.traces.backend_compile_secs", duration)
            elif event.endswith("jaxpr_trace_duration"):
                core.inc("compile.traces.jaxpr_trace")

        def _on_event(event: str, **kw) -> None:
            if not core.enabled():
                return
            if "cache_hit" in event:
                core.inc("compile.traces.cache_hits")

        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _trace_counters_installed = True
    except Exception as e:  # noqa: BLE001 — older jax without monitoring
        log.debug("trace counters unavailable: %s", e)
        _trace_counters_installed = True  # don't retry every call


class RetraceSentinel:
    """Unexpected-recompilation alarm for steady-state loops: arm() after
    warmup (first sync), then every check() that sees the global
    `compile.traces.backend_compile` counter above the armed baseline
    fires `health.retrace` + `compile.retraces.unexpected` and re-baselines.
    Needs install_trace_counters() + obs enabled (otherwise the counter
    never moves and check() is a dict lookup).

    Culprit naming (r20): pass the call's abstract signature
    (`profiler.abstract_signature(...)`) to arm()/check() and the fired
    event carries `changed` — the argument/dim diff vs the armed entry.
    When the ytkprof plane is on, the event additionally carries
    `culprits`: the compile-ledger entries (program label + per-program
    signature diff) that landed between arm and the tripping check, so
    the postmortem names *which program* recompiled even when the loop's
    own arguments never changed."""

    __slots__ = ("site", "baseline", "sig", "_ledger_seq")

    def __init__(self, site: str):
        self.site = site
        self.baseline: Optional[float] = None
        self.sig = None
        self._ledger_seq = 0

    @staticmethod
    def _compiles() -> float:
        return core.REGISTRY.counters.get("compile.traces.backend_compile", 0.0)

    @staticmethod
    def _ledger():
        from . import profiler

        return profiler.LEDGER if profiler.enabled() else None

    def arm(self, sig=None) -> None:
        if _state.on:
            self.baseline = self._compiles()
            if sig is not None:
                self.sig = sig
            led = self._ledger()
            if led is not None:
                self._ledger_seq = led.mark()

    def check(self, sig=None, **args) -> bool:
        if not _state.on or self.baseline is None:
            return True
        cur = self._compiles()
        if cur <= self.baseline:
            return True
        n = cur - self.baseline
        self.baseline = cur
        core.inc("compile.retraces.unexpected", n)
        from . import profiler

        if sig is not None:
            changed = profiler.signature_diff(self.sig, sig)
            if changed:
                args["changed"] = changed
            self.sig = sig
        led = self._ledger()
        if led is not None:
            culprits = [
                {k: e[k] for k in ("program", "ms", "changed") if k in e}
                for e in led.entries_since(self._ledger_seq)
            ]
            if culprits:
                args["culprits"] = culprits
            self._ledger_seq = led.mark()
        _fire(
            "retrace",
            self.site,
            f"{n:.0f} unexpected XLA compile(s) after warmup",
            escalate=False,
            compiles=n,
            **args,
        )
        return False
