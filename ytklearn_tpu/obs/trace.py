"""Serve-side distributed request tracing: per-hop spans + tail exemplars.

The r7/r8 obs layers made *training* deeply observable; this module is the
serving-side counterpart (Dapper, PAPERS.md): every `/predict` request can
carry a trace id from the fleet front through a replica worker, and each
hop of its life — front parse/raw-splice, forwarder queue, HTTP forward,
replica queue wait, batch assembly, ladder-rung execution, cache hit/miss,
response write — is recorded as a named span, so a p99 spike decomposes
into "the milliseconds went HERE" instead of one opaque latency number.

Three pieces:

  head sampler   deterministic counter-hashed draw (splitmix64 over
                 (YTK_TRACE_SEED, request #) < YTK_TRACE_SAMPLE): same
                 seed + same request order = same kept set, so a drill
                 reproduces exactly. `begin()` returns the cached no-op
                 ctx when the draw says no — the unsampled path is one
                 integer hash + compare per request, no allocation.
  trace ctx      `TraceCtx.hop(name, **args)` / `hop_at(...)` record
                 (name, start, dur) tuples on the request as it flows
                 handler -> batcher -> scorer. Cross-process propagation
                 rides the `X-Ytk-Trace` header: the front forwards the
                 sampled ids of a coalesced batch, the replica adopts
                 them (`begin(inbound=...)`) so one trace id spans
                 front -> replica.
  exemplar ring  bounded per-process deque of finished traces, exported
                 at `/admin/traces` and merged cross-process by
                 scripts/obs_report.py (each payload carries the
                 process's wall-clock origin, so hops align on one
                 timeline). Tail rule: shed (429), deadline (504), and
                 SLO-exceeding requests are ALWAYS retained — with full
                 hops when head-sampled, as a minimal exemplar (id,
                 status, latency) otherwise, because the no-op path
                 records nothing by contract.

Batch-scoped hops: code that runs once per coalesced batch (the scorer's
featurize/execute, the front's HTTP forward) records through
`batch_hop(name, **args)` into a thread-local staging list; the batcher
worker brackets the score_fn call with `set_current_batch(traces)` /
`end_current_batch()`, which copies the staged hops onto every traced
request of the batch. With no traced request in the batch, `batch_hop`
returns the cached no-op span.

Knobs: YTK_TRACE_SAMPLE (0 disables the plane entirely), YTK_TRACE_SEED,
YTK_TRACE_EXEMPLARS (ring capacity). The serving layer feeds the SLO used
by the tail rule via `configure_tracing(slo_ms=...)`.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from . import core
from ..config import knobs

#: HTTP header carrying the sampled trace ids of a forwarded batch
#: (comma-separated); a client may set it on an inbound /predict to force
#: a trace (adopt semantics, Dapper's "debug bit")
TRACE_HEADER = "X-Ytk-Trace"

#: statuses the tail rule always retains (shed / deadline-expired)
TAIL_STATUSES = (429, 504)

_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer — the same counter-hash family the chaos layer
    uses, inlined here because this runs once per request on the serve hot
    path (a cross-module call + string hash would double the cost)."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


class _TraceState:
    __slots__ = ("rate", "seed", "slo_ms", "counter", "tail_counter",
                 "threshold")

    def __init__(self):
        self.rate = 0.0
        self.seed = 0
        self.slo_ms: Optional[float] = None
        self.counter = 0  # advanced under _counter_lock (head-sample order)
        # tail-only exemplars draw ids from their OWN counter: advancing
        # the head counter for them would shift subsequent begin() draws
        # and break the same-seed-same-kept-set determinism contract
        self.tail_counter = 0
        self.threshold = 0  # rate pre-scaled to the 64-bit hash range

    def set_rate(self, rate: float) -> None:
        self.rate = max(0.0, min(1.0, float(rate)))
        # draw < rate compared in integer space: no float division per
        # request, and rate=1.0 keeps everything (threshold = 2^64)
        self.threshold = int(self.rate * float(1 << 64))


_state = _TraceState()
_counter_lock = threading.Lock()

# exemplar ring: bounded deque of finished trace records. Handler threads
# append, /admin/traces snapshots — one small lock, touched once per KEPT
# trace (sample-rate-scaled), never per unsampled request.
_ring: collections.deque = collections.deque(maxlen=256)
_ring_lock = threading.Lock()

_tls = threading.local()


def enabled() -> bool:
    return _state.rate > 0.0


def slo_ms() -> Optional[float]:
    return _state.slo_ms


def configure_tracing(
    sample: Optional[float] = None,
    seed: Optional[int] = None,
    exemplars: Optional[int] = None,
    slo_ms: Optional[float] = None,
    reset: bool = False,
) -> None:
    """Runtime override of the YTK_TRACE_* env knobs (serving layer arms
    the SLO; tests/drills pin the sampler). `reset=True` clears the
    exemplar ring and rewinds the sample counter (determinism tests)."""
    global _ring
    if sample is not None:
        _state.set_rate(sample)
    if seed is not None:
        _state.seed = int(seed)
    if slo_ms is not None:
        _state.slo_ms = float(slo_ms) if slo_ms > 0 else None
    if exemplars is not None and int(exemplars) != _ring.maxlen:
        with _ring_lock:
            _ring = collections.deque(_ring, maxlen=max(1, int(exemplars)))
    if reset:
        with _ring_lock:
            _ring.clear()
        with _counter_lock:
            _state.counter = 0
            _state.tail_counter = 0


def _configure_from_env() -> None:
    _state.set_rate(knobs.get_float("YTK_TRACE_SAMPLE") or 0.0)
    _state.seed = knobs.get_int("YTK_TRACE_SEED") or 0
    n = knobs.get_int("YTK_TRACE_EXEMPLARS")
    if n and n != _ring.maxlen:
        configure_tracing(exemplars=n)


def head_keep(seed: int, n: int) -> bool:
    """The deterministic head-sampling decision for request `n` (1-based)
    under `seed` — public so tests and drills can precompute the kept set
    exactly (the chaos `site_draw` discipline)."""
    return _mix64((seed * 0x9E3779B97F4A7C15 + n) & _M64) < _state.threshold


class _NoopTrace:
    """Cached do-nothing trace ctx — the whole unsampled request path.
    `ids` is empty, which is how every integration point (batcher submit,
    batch-hop bracketing, header propagation) tests for "really traced"."""

    __slots__ = ()
    ids: tuple = ()
    kept = None

    def hop(self, name, **args):
        return core.NOOP_SPAN

    def hop_at(self, name, t0, t1, **args):
        return None

    def add_hops(self, hops):
        return None


NOOP_TRACE = _NoopTrace()


class TraceCtx:
    """One sampled (or adopted) request's hop log.

    Hops are appended by the handler thread AND the batcher worker thread
    (strictly sequenced by the pending handle's completion signal, but a
    lock keeps the container honest under the lockwatch twin); `finish`
    snapshots them into the exemplar record. Timestamps are obs-clock
    offsets (`core._now()`), the same origin as every other obs event, so
    `wall_t0 + ts` aligns traces across processes.
    """

    __slots__ = ("ids", "kept", "t0", "hops", "_lock")

    def __init__(self, ids: Sequence[str], kept: str):
        self.ids = tuple(ids)
        self.kept = kept  # head | adopted (finish may upgrade to tail_*)
        self.t0 = core._now()
        self.hops: List[dict] = []
        self._lock = threading.Lock()

    def hop_at(self, name: str, t0: float, t1: float, **args) -> None:
        """Record one hop from explicit perf_counter timestamps (queue
        waits are measured between enqueue and dequeue, which straddle
        threads)."""
        h = {"name": name, "ts": round(t0 - core._T0, 6),
             "dur_ms": round((t1 - t0) * 1e3, 4)}
        if args:
            h["args"] = args
        with self._lock:
            self.hops.append(h)

    def hop(self, name: str, **args) -> "_HopSpan":
        """`with ctx.hop("front.forward", replica=rid): ...`"""
        return _HopSpan(self, name, args)

    def add_hops(self, hops: List[dict]) -> None:
        """Batch-scoped hops copied onto this request (already in record
        form — shared dicts are fine, records are write-once)."""
        with self._lock:
            self.hops.extend(hops)


class _HopSpan:
    __slots__ = ("_ctx", "_name", "_args", "_t0")

    def __init__(self, ctx, name, args):
        self._ctx = ctx
        self._name = name
        self._args = args

    def __enter__(self) -> "_HopSpan":
        self._t0 = time.perf_counter()
        return self

    def add(self, **kw) -> "_HopSpan":
        self._args.update(kw)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self._args["error"] = exc_type.__name__
        self._ctx.hop_at(self._name, self._t0, time.perf_counter(),
                         **self._args)
        return False


def _new_id(n: int) -> str:
    """Process-unique trace id: pid + counter + a wall-clock nibble so two
    fleets started back to back cannot collide."""
    return f"{os.getpid():x}-{n:x}-{int(time.time() * 1e3) & 0xFFFFFF:x}"


def begin(inbound: Optional[str] = None) -> "TraceCtx | _NoopTrace":
    """Start (or adopt) a request trace.

    `inbound` is the raw X-Ytk-Trace header value: non-empty adopts the
    upstream sampling decision verbatim (the ids were sampled at the
    front — a replica must record them, Dapper's propagated decision).
    Otherwise the deterministic head sampler decides; "no" returns the
    cached no-op ctx."""
    if _state.rate <= 0.0:
        return NOOP_TRACE
    if inbound:
        ids = [t.strip() for t in inbound.split(",") if t.strip()]
        if ids:
            return TraceCtx(ids[:64], kept="adopted")
        return NOOP_TRACE
    with _counter_lock:
        _state.counter += 1
        n = _state.counter
    if not head_keep(_state.seed, n):
        return NOOP_TRACE
    return TraceCtx((_new_id(n),), kept="head")


def finish(
    ctx,
    status: int = 200,
    latency_ms: Optional[float] = None,
    rows: Optional[int] = None,
    **args,
) -> Optional[dict]:
    """Close a request trace and decide exemplar retention.

    Head-sampled / adopted traces are always admitted (that IS the
    sample). Unsampled requests are admitted by the tail rule only —
    shed (429), deadline (504), or latency over the configured SLO — as a
    minimal record without hop decomposition (the no-op ctx recorded
    nothing, by the near-zero-cost contract). Returns the admitted record
    (tests introspect it) or None."""
    if _state.rate <= 0.0:
        return None
    slo = _state.slo_ms
    violated = status in TAIL_STATUSES or (
        slo is not None and latency_ms is not None and latency_ms > slo
    )
    sampled = ctx is not None and ctx is not NOOP_TRACE and ctx.ids
    if not sampled and not violated:
        return None
    if sampled:
        with ctx._lock:
            hops = list(ctx.hops)
        rec = {"trace_id": ctx.ids[0], "ts": round(ctx.t0, 6),
               "kept": ctx.kept, "hops": hops}
        if len(ctx.ids) > 1:
            rec["trace_ids"] = list(ctx.ids)
    else:
        # tail-only exemplar: no hops were recorded, but the incident is
        # still named (when, what, how slow) — a 504 storm must not be
        # invisible just because the head sampler skipped those requests.
        # Ids come from the tail counter so a same-millisecond storm of
        # sheds still yields unique trace ids
        with _counter_lock:
            _state.tail_counter += 1
            t_n = _state.tail_counter
        # ts is the request START like every sampled exemplar (finish
        # time minus the latency) — a tail span placed at its END would
        # render one-latency late on the merged Perfetto timeline
        start = core._now() - (latency_ms / 1e3 if latency_ms else 0.0)
        rec = {"trace_id": f"{os.getpid():x}-t{t_n:x}-"
                           f"{int(time.time() * 1e3) & 0xFFFFFF:x}",
               "ts": round(max(start, 0.0), 6), "kept": "tail", "hops": []}
    if violated:
        rec["kept"] = (
            "tail_shed" if status == 429
            else "tail_deadline" if status == 504
            else "tail_slo"
        )
    rec["status"] = int(status)
    if latency_ms is not None:
        rec["latency_ms"] = round(float(latency_ms), 3)
    if rows is not None:
        rec["rows"] = int(rows)
    if core.IDENTITY:
        rec.update({k: v for k, v in core.IDENTITY.items()
                    if k not in rec})
    if args:
        rec["args"] = args
    with _ring_lock:
        _ring.append(rec)
    core.inc("trace.exemplars")
    core.inc(f"trace.kept.{rec['kept']}")
    return rec


# ---------------------------------------------------------------------------
# Batch-scoped hops (scorer featurize/execute, front HTTP forward)
# ---------------------------------------------------------------------------


def set_current_batch(traces: List[TraceCtx]) -> None:
    """Batcher worker: the traced requests of the batch about to score.
    Only called when the batch HAS traced requests (the untraced hot path
    never enters this module)."""
    _tls.batch = traces
    _tls.staged = []


def end_current_batch() -> None:
    """Copy the staged batch hops onto every traced request, then clear."""
    traces = getattr(_tls, "batch", None)
    staged = getattr(_tls, "staged", None)
    _tls.batch = None
    _tls.staged = None
    if traces and staged:
        for t in traces:
            t.add_hops(staged)


def current_batch_ids() -> List[str]:
    """Trace ids of the in-flight batch (the front's forwarder reads this
    inside score_fn to build the X-Ytk-Trace propagation header)."""
    traces = getattr(_tls, "batch", None)
    if not traces:
        return []
    out: List[str] = []
    for t in traces:
        out.extend(t.ids)
    return out


class _BatchHopSpan:
    __slots__ = ("_name", "_args", "_t0")

    def __init__(self, name, args):
        self._name = name
        self._args = args

    def __enter__(self) -> "_BatchHopSpan":
        self._t0 = time.perf_counter()
        return self

    def add(self, **kw) -> "_BatchHopSpan":
        self._args.update(kw)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        if exc_type is not None:
            self._args["error"] = exc_type.__name__
        h = {"name": self._name, "ts": round(self._t0 - core._T0, 6),
             "dur_ms": round((t1 - self._t0) * 1e3, 4)}
        if self._args:
            h["args"] = self._args
        staged = getattr(_tls, "staged", None)
        if staged is not None:
            staged.append(h)
        return False


def batch_hop(name: str, **args):
    """Span over once-per-batch work, attributed to every traced request
    of the current batch. No-op (cached ctx manager) when the batch has
    no traced request — the scorer calls this on every batch."""
    if getattr(_tls, "batch", None):
        return _BatchHopSpan(name, args)
    return core.NOOP_SPAN


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------


def exemplars(clear: bool = False) -> List[dict]:
    with _ring_lock:
        out = list(_ring)
        if clear:
            _ring.clear()
    return out


def exemplars_payload() -> Dict[str, object]:
    """The /admin/traces document for THIS process. `wall_t0` anchors the
    obs-clock hop offsets to the wall clock (hop wall time = wall_t0 +
    ts), which is how obs_report merges front + replica rings onto one
    timeline — the same handshake value the worker banner carries."""
    return {
        "schema": "ytk_traces",
        "schema_version": 1,
        "pid": os.getpid(),
        "wall_t0": core.WALL_T0,
        "sample": _state.rate,
        "seed": _state.seed,
        "slo_ms": _state.slo_ms,
        "ring_capacity": _ring.maxlen,
        "identity": dict(core.IDENTITY),
        "exemplars": exemplars(),
    }


_configure_from_env()
