"""Model-quality observability plane: data sketches, drift, calibration.

The r17/r18 layers made the serving *machinery* observable (traces,
history, autoscaling); this module watches whether the *models are still
right* (McMahan et al., "Ad Click Prediction: a View from the Trenches" —
PAPERS.md: the production-ML layer that catches what offline metrics
can't). Three pieces:

  train-time sidecar   the trainer dumps `<model>.sketch.json` next to
                       the model: per-feature weighted-GK quantile
                       summaries of the training matrix (the SAME
                       mergeable summary `gbdt/quantile_sketch.py` feeds
                       binning with — XGBoost's weighted quantile sketch,
                       PAPERS.md), per-feature presence rates, and the
                       held-out score distribution. It rides the
                       continual shadow/promote/archive/rollback moves
                       (driver._roots) and the serving fingerprint
                       (registry._sidecar_paths), exactly like the
                       `.bins.json` sidecar.
  serve-side monitor   each replica's predict path feeds a bounded
                       streaming sketch per (model name, version):
                       incoming feature values, score/class-probability
                       distribution, and missing-rate counters — sampled
                       by a deterministic counter-hashed ROW sampler
                       (`YTK_QUALITY_SAMPLE`, same splitmix64 family as
                       the chaos layer and the trace head sampler, so a
                       drill reproduces exactly). The hot path only
                       stages sampled rows into a bounded buffer; a
                       periodic evaluator thread (`YTK_QUALITY_EVAL_S`)
                       drains it into the sketches and computes PSI + KS
                       distances against the training sidecar plus
                       calibration drift (mean predicted vs the sidecar's
                       score distribution), feeding the `health.drift` /
                       `health.calibration` sentinels (obs/health.py) and
                       the `/metrics?quality=1` export.
  fleet merge          per-replica GK summaries MERGE (that is the whole
                       point of the sketch): the fleet front unions every
                       replica's serve-side summaries with
                       `merge_summaries` into one fleet-level drift view,
                       order-independent, so fleet PSI is computed over
                       the union distribution — not replica-0's, not an
                       average of per-replica PSIs.

Missing-sidecar behavior is loud but non-fatal: a model without
`<model>.sketch.json` (legacy dump, non-GBDT family) serves normally with
a named `quality.no_baseline` counter; nothing crashes and nothing is
silently skipped.

Semantics note: the serve-side value sketches record values AS SENT by
clients; features a client omits count toward the missing rate, not the
value distribution. The training-side summaries are built from the
ingest matrix (post missing-fill), so on sparse one-hot features the
missing-rate delta — exported per feature, never gated — is the honest
signal while PSI watches the dense numeric ones.

Knobs: YTK_QUALITY_SAMPLE (0 disables the plane), YTK_QUALITY_SEED,
YTK_QUALITY_B (sketch size), YTK_QUALITY_EVAL_S; sentinel thresholds
ride YTK_HEALTH_DRIFT_* / YTK_HEALTH_CALIBRATION_TOL (obs/health.py).
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import core, health
from .recorder import thread_guard
from ..config import knobs
from ..gbdt.quantile_sketch import (
    Summary,
    WeightedQuantileSketch,
    merge_summaries,
    prune_summary,
)

log = logging.getLogger("ytklearn_tpu.obs.quality")

QUALITY_SCHEMA = "ytk-quality-sketch"

#: rows staged per model between evaluator ticks; overflow is counted
#: (`quality.buffer_dropped`), never silently widened — the buffer bounds
#: the request-path memory the plane can ever hold
BUFFER_ROWS = 8192

#: training-side sketch builders subsample the matrix to this many rows
#: (deterministic stride) — drift baselines need stable quantiles, not
#: exact quantiles of 78M rows
TRAIN_SKETCH_ROWS = 1 << 18

#: probability clamp for PSI (a zero observed bin must read as "very
#: drifted", not log(0))
PSI_EPS = 1e-6

#: PSI quantile-bin count (the industry-standard decile convention)
PSI_BINS = 10

_M64 = (1 << 64) - 1
_GOLD = 0x9E3779B97F4A7C15


def quality_sidecar_path(data_path: str) -> str:
    return data_path + ".sketch.json"


# ---------------------------------------------------------------------------
# Deterministic counter-hashed row sampler (the chaos/trace draw family)
# ---------------------------------------------------------------------------


def _mix64(x: int) -> int:
    """splitmix64 finalizer — scalar reference; `sample_mask` is the
    vectorized twin and tests pin them equal."""
    x = (x + _GOLD) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def row_keep(seed: int, n: int, rate: float) -> bool:
    """The deterministic per-ROW sampling decision for row counter `n`
    (1-based) under `seed` — public like chaos.site_draw / trace.head_keep
    so tests and drills precompute the kept set exactly."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return _mix64((seed * _GOLD + n) & _M64) < int(rate * float(1 << 64))


def sample_mask(seed: int, start: int, n: int, rate: float) -> np.ndarray:
    """Vectorized `row_keep` for row counters start+1 .. start+n — one
    numpy pass per request instead of n python hashes. Bit-identical to
    the scalar reference (test-pinned)."""
    if rate >= 1.0:
        return np.ones(n, bool)
    if rate <= 0.0 or n <= 0:
        return np.zeros(n, bool)
    threshold = np.uint64(int(rate * float(1 << 64)) & _M64)
    base = (seed * _GOLD) & _M64
    with np.errstate(over="ignore"):
        x = np.uint64(base) + np.arange(
            start + 1, start + n + 1, dtype=np.uint64
        )
        x = x + np.uint64(_GOLD)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x < threshold


# ---------------------------------------------------------------------------
# Distribution distances on GK summaries
# ---------------------------------------------------------------------------


def summary_to_json(s: Summary) -> dict:
    return {
        "value": [float(v) for v in s.value],
        "rmin": [float(v) for v in s.rmin],
        "rmax": [float(v) for v in s.rmax],
        "w": [float(v) for v in s.w],
        "total": float(s.total),
    }


def summary_from_json(d: dict) -> Summary:
    return Summary(
        value=np.asarray(d["value"], np.float64),
        rmin=np.asarray(d["rmin"], np.float64),
        rmax=np.asarray(d["rmax"], np.float64),
        w=np.asarray(d["w"], np.float64),
        total=float(d["total"]),
    )


def summary_cdf(s: Summary, xs) -> np.ndarray:
    """Estimated CDF of the sketched distribution at `xs`: mass of values
    <= x over total, via the rmax rank bound — EXACT for unpruned
    summaries (rmax is the true cumulative there), within the GK rank
    error otherwise."""
    xs = np.asarray(xs, np.float64)
    if s.size == 0 or s.total <= 0:
        return np.zeros(xs.shape)
    idx = np.searchsorted(s.value, xs, side="right") - 1
    cdf = np.where(idx >= 0, s.rmax[np.maximum(idx, 0)] / s.total, 0.0)
    return np.clip(cdf, 0.0, 1.0)


def quantile_edges(s: Summary, bins: int = PSI_BINS) -> np.ndarray:
    """`bins-1` interior quantile edges of the sketched distribution
    (deduped — discrete distributions can collapse bins)."""
    if s.size == 0:
        return np.zeros(0)
    ranks = (np.arange(1, bins) / bins) * s.total
    mid = 0.5 * (s.rmin + s.rmax)
    pos = np.searchsorted(mid, ranks, side="left").clip(0, s.size - 1)
    return np.unique(s.value[pos])


def bin_probs(s: Summary, edges: np.ndarray) -> np.ndarray:
    """Per-bin probability mass of `s` over the (len(edges)+1) intervals
    the edges cut the line into."""
    cdf = summary_cdf(s, edges)
    return np.diff(np.concatenate([[0.0], cdf, [1.0]]))


def psi_from_probs(expected, actual) -> float:
    """Population stability index over matched bin probabilities:
    sum((a - e) * ln(a / e)), probabilities clamped at PSI_EPS then
    renormalized. The hand-pinnable primitive (tests/test_quality.py)."""
    e = np.clip(np.asarray(expected, np.float64), PSI_EPS, None)
    a = np.clip(np.asarray(actual, np.float64), PSI_EPS, None)
    e = e / e.sum()
    a = a / a.sum()
    return float(np.sum((a - e) * np.log(a / e)))


def psi_summaries(
    baseline: Summary, observed: Summary, bins: int = PSI_BINS
) -> Optional[float]:
    """PSI of `observed` against `baseline`, binned at the BASELINE's
    quantile edges (the training distribution defines the bins; serving
    traffic is judged against them). None when either side is empty."""
    if baseline.size == 0 or observed.size == 0:
        return None
    edges = quantile_edges(baseline, bins)
    if edges.size == 0:
        return None
    return psi_from_probs(bin_probs(baseline, edges), bin_probs(observed, edges))


def ks_summaries(
    a: Summary, b: Summary, max_points: int = 2048
) -> Optional[float]:
    """Kolmogorov–Smirnov distance (max |CDF_a - CDF_b|) evaluated over
    the union of both summaries' support points."""
    if a.size == 0 or b.size == 0:
        return None
    xs = np.unique(np.concatenate([a.value, b.value]))
    if len(xs) > max_points:
        xs = xs[:: (len(xs) // max_points) + 1]
    return float(np.max(np.abs(summary_cdf(a, xs) - summary_cdf(b, xs))))


def score_vector(preds) -> np.ndarray:
    """Predictions -> the 1-D quantity the score distribution tracks:
    the prediction itself for single-output models, the per-row TOP-CLASS
    probability for (B, K) multiclass outputs (a confidence collapse
    after a bad promotion shows up as a left-shift of this). The SAME
    reduction runs train-side (sidecar) and serve-side, so the
    distributions are comparable by construction."""
    p = np.asarray(preds, np.float64)
    if p.ndim <= 1:
        return p.reshape(-1)
    return np.max(p, axis=-1)


# ---------------------------------------------------------------------------
# Train-time sidecar: build / dump / load
# ---------------------------------------------------------------------------


def _stride_sample(n: int, cap: int = TRAIN_SKETCH_ROWS) -> np.ndarray:
    """Deterministic row subsample: every k-th row, capped at `cap`."""
    if n <= cap:
        return np.arange(n)
    return np.arange(0, n, max(1, n // cap))[:cap]


def build_training_sketch(
    X: np.ndarray,
    feature_names: Sequence[str],
    weight: Optional[np.ndarray] = None,
    preds: Optional[np.ndarray] = None,
    b: Optional[int] = None,
) -> dict:
    """The `<model>.sketch.json` payload: per-feature pruned GK summaries
    + presence rates over a deterministic row subsample of the training
    matrix, plus the (held-out, when the trainer has one) score
    distribution. numpy-only — runs once per dump on the host."""
    if b is None:
        b = knobs.get_int("YTK_QUALITY_B")
    n, F = X.shape
    idx = _stride_sample(n)
    w = None if weight is None else np.asarray(weight, np.float64)[idx]
    features: Dict[str, dict] = {}
    for f in range(min(F, len(feature_names))):
        col = np.asarray(X[idx, f], np.float64)
        finite = np.isfinite(col)
        present = float(np.mean(finite)) if len(col) else 0.0
        vals = col[finite]
        wv = w[finite] if w is not None else None
        sk = WeightedQuantileSketch(b=b)
        if len(vals):
            sk.push(vals, wv)
        features[str(feature_names[f])] = {
            "present": round(present, 6),
            "summary": summary_to_json(prune_summary(sk.summary(), b)),
        }
    payload = {
        "schema": QUALITY_SCHEMA,
        "version": 1,
        "rows": int(n),
        "sampled_rows": int(len(idx)),
        "features": features,
    }
    if preds is not None:
        payload["score"] = build_score_block(preds, b=b)
    return payload


def build_score_block(preds, b: Optional[int] = None) -> dict:
    """The sidecar's `score` block: GK summary + mean of the (held-out)
    prediction distribution, reduced through `score_vector` so train and
    serve compare the same quantity."""
    if b is None:
        b = knobs.get_int("YTK_QUALITY_B")
    sv = score_vector(preds)
    sv = sv[np.isfinite(sv)]
    sv = sv[_stride_sample(len(sv))]
    sk = WeightedQuantileSketch(b=b)
    if len(sv):
        sk.push(sv)
    return {
        "n": int(len(sv)),
        "mean": float(np.mean(sv)) if len(sv) else 0.0,
        "summary": summary_to_json(prune_summary(sk.summary(), b)),
    }


def dump_quality_sidecar(
    fs, path: str, payload: dict, model_digest: Optional[str] = None
) -> None:
    """Atomic sidecar dump (same discipline as `.bins.json`: written
    BEFORE the model file, `model_digest` = sha256 of the model text
    about to land so a consumer can verify the pairing)."""
    import json

    if model_digest is not None:
        payload = {**payload, "model_digest": model_digest}
    with fs.atomic_open(path) as f:
        json.dump(payload, f)


def load_quality_baseline(
    fs, path: str, model_digest: Optional[str] = None
) -> Optional[dict]:
    """Parsed baseline: {"features": {name: {"summary": Summary,
    "present": float}}, "score": Summary | None, "score_mean": float,
    "rows": int} — or None (missing / unreadable / digest mismatch), in
    which case the caller serves normally and counts
    `quality.no_baseline` (loud but non-fatal by contract)."""
    import json

    if not fs.exists(path):
        return None
    try:
        with fs.open(path) as f:
            payload = json.load(f)
        if payload.get("schema") != QUALITY_SCHEMA:
            raise ValueError(f"not a quality sidecar: {path}")
        want = payload.get("model_digest")
        if model_digest is not None and want is not None \
                and want != model_digest:
            log.warning(
                "quality sidecar %s was dumped for a different model "
                "(digest mismatch); treating the model as baseline-less",
                path,
            )
            return None
        features = {
            str(name): {
                "summary": summary_from_json(info["summary"]),
                "present": float(info.get("present", 1.0)),
            }
            for name, info in (payload.get("features") or {}).items()
        }
        score = payload.get("score") or {}
        return {
            "features": features,
            "score": (
                summary_from_json(score["summary"])
                if "summary" in score else None
            ),
            "score_mean": float(score.get("mean", 0.0)),
            "rows": int(payload.get("rows", 0)),
        }
    except (OSError, ValueError, KeyError, TypeError) as e:
        log.warning(
            "quality sidecar %s unreadable (%s: %s); treating the model "
            "as baseline-less", path, type(e).__name__, e,
        )
        return None


# ---------------------------------------------------------------------------
# Serve-side monitor
# ---------------------------------------------------------------------------


class _ModelState:
    """Streaming quality state for one served (model name, version)."""

    __slots__ = (
        "key", "model", "version", "fingerprint", "lock", "baseline",
        "no_baseline", "rows_seen", "rows_sampled", "buf", "buf_dropped",
        "sketches", "missing", "score_sketch", "score_sum", "score_n",
        "last_eval", "drift", "calibration", "b",
    )

    def __init__(self, key: str, model: str, version: int,
                 fingerprint: str, baseline: Optional[dict], b: int):
        self.key = key
        self.model = model
        self.version = version
        self.fingerprint = fingerprint
        self.lock = threading.Lock()
        self.baseline = baseline
        self.no_baseline = baseline is None
        self.rows_seen = 0
        self.rows_sampled = 0
        self.buf: List[Tuple[dict, float]] = []
        self.buf_dropped = 0
        self.b = b
        # per-feature streaming sketches, bounded by the BASELINE feature
        # set (cardinality is the sidecar's, never the client's)
        self.sketches: Dict[str, WeightedQuantileSketch] = {}
        self.missing: Dict[str, int] = {}
        self.score_sketch = WeightedQuantileSketch(b=b)
        self.score_sum = 0.0
        self.score_n = 0
        self.last_eval: Optional[dict] = None
        # sentinels are fed ONLY from evaluator ticks (feed_sentinels),
        # so their windows count evaluator intervals, not scrapes
        self.drift = health.DriftSentinel("serve.quality")
        self.calibration = health.CalibrationSentinel("serve.quality")


class QualityMonitor:
    """Per-process model-quality monitor: observe() stages sampled rows
    (the request hot path — one vectorized hash + a bounded list append),
    evaluate() does all sketch pushes and distance math (the evaluator
    thread / metrics scrape path)."""

    def __init__(
        self,
        sample: Optional[float] = None,
        seed: Optional[int] = None,
        b: Optional[int] = None,
    ):
        self.rate = float(
            sample if sample is not None
            else (knobs.get_float("YTK_QUALITY_SAMPLE") or 0.0)
        )
        self.seed = int(
            seed if seed is not None else (knobs.get_int("YTK_QUALITY_SEED") or 0)
        )
        self.b = int(b if b is not None else knobs.get_int("YTK_QUALITY_B"))
        self._lock = threading.Lock()
        self._counter = 0  # row counter feeding the deterministic sampler
        self._threshold = int(min(max(self.rate, 0.0), 1.0) * float(1 << 64))
        self._states: Dict[str, _ModelState] = {}

    # -- configuration -----------------------------------------------------

    def configure(self, sample=None, seed=None, b=None, reset=False) -> None:
        with self._lock:
            if sample is not None:
                self.rate = float(sample)
                self._threshold = int(
                    min(max(self.rate, 0.0), 1.0) * float(1 << 64)
                )
            if seed is not None:
                self.seed = int(seed)
            if b is not None:
                self.b = int(b)
            if reset:
                self._counter = 0
                self._states = {}

    def enabled(self) -> bool:
        return self.rate > 0.0

    # -- the request hot path ----------------------------------------------

    def _make_state(self, entry) -> _ModelState:
        """Build (and baseline-load) a state for a served entry — called
        OUTSIDE every lock: the sidecar read is IO and must never sit on
        the request path's lock."""
        baseline = None
        data_path = None
        try:
            data_path = getattr(entry.predictor.params.model, "data_path", None)
            if data_path:
                baseline = load_quality_baseline(
                    entry.predictor.fs, quality_sidecar_path(data_path)
                )
        except Exception as e:  # noqa: BLE001 — baseline-less beats a 500
            log.warning(
                "quality baseline load failed for %r (%s: %s); serving "
                "baseline-less", entry.name, type(e).__name__, e,
            )
        st = _ModelState(
            f"{entry.name}@v{entry.version}", entry.name, entry.version,
            getattr(entry, "fingerprint", ""), baseline, self.b,
        )
        if st.no_baseline:
            core.inc("quality.no_baseline")
            core.event(
                "quality.no_baseline", model=entry.name,
                version=entry.version, path=str(data_path),
            )
            log.warning(
                "model %r v%d has no quality sidecar (%s): serving "
                "normally, drift/calibration unmonitored",
                entry.name, entry.version,
                quality_sidecar_path(data_path) if data_path else "no path",
            )
        return st

    def state_for(self, entry) -> _ModelState:
        key = f"{entry.name}@v{entry.version}"
        with self._lock:
            st = self._states.get(key)
        if st is None:
            built = self._make_state(entry)  # IO outside the lock
            with self._lock:
                st = self._states.setdefault(key, built)
                if st is built:
                    # version turnover (hot reload / rollback): retire the
                    # other versions of this model name, or a long-running
                    # server under continual retraining accumulates one
                    # full state (baseline + sketches + buffer) per
                    # retired version forever and re-evaluates them all
                    # every tick. An in-flight observe holding a retired
                    # state still completes; its staged rows just never
                    # evaluate — monitoring, not accounting.
                    for old_key in [
                        k for k, s in self._states.items()
                        if s.model == entry.name and k != key
                    ]:
                        del self._states[old_key]
        return st

    def observe(self, entry, rows: Sequence[dict], preds) -> int:
        """Feed one scored request (rows + model outputs). Returns the
        number of rows the deterministic sampler kept (staged for the
        next evaluate())."""
        if self.rate <= 0.0 or not rows:
            return 0
        n = len(rows)
        with self._lock:
            start = self._counter
            self._counter += n
        st = self.state_for(entry)
        # small requests (the serve hot path is dominated by 1-row HTTP
        # requests) take a pure-int scalar draw — the numpy temporaries
        # of sample_mask cost more than the whole request's bookkeeping
        # at B=1; both paths are the same splitmix64 draws (test-pinned)
        if n <= 16:
            thr = self._threshold
            base = (self.seed * _GOLD) & _M64
            kept_idx = [
                i for i in range(n)
                if _mix64((base + start + 1 + i) & _M64) < thr
            ]
        else:
            kept_idx = np.nonzero(
                sample_mask(self.seed, start, n, self.rate)
            )[0]
        kept = len(kept_idx)
        core.inc("quality.rows_seen", n)
        if st.no_baseline:
            with st.lock:
                st.rows_seen += n
                st.rows_sampled += kept  # counted, not sketched
            return kept
        if not kept:
            with st.lock:
                st.rows_seen += n
            return 0
        sv = score_vector(preds)
        staged = [
            (rows[i], float(sv[i]) if i < len(sv) else math.nan)
            for i in kept_idx
        ]
        with st.lock:
            st.rows_seen += n
            space = BUFFER_ROWS - len(st.buf)
            if space < len(staged):
                st.buf_dropped += len(staged) - max(space, 0)
                core.inc("quality.buffer_dropped",
                         len(staged) - max(space, 0))
                staged = staged[: max(space, 0)]
            st.buf.extend(staged)
            st.rows_sampled += len(staged)
        core.inc("quality.rows_sampled", len(staged))
        return len(staged)

    # -- evaluation ---------------------------------------------------------

    def _ingest(self, st: _ModelState, buf: List[Tuple[dict, float]]) -> None:
        """Drain staged rows into the streaming sketches (called under
        st.lock; pure numpy — no IO, no locks below this one)."""
        if not buf:
            return
        feats = st.baseline["features"]
        per_feature: Dict[str, List[float]] = {}
        scores: List[float] = []
        for fmap, sv in buf:
            for name in feats:
                v = fmap.get(name)
                if v is None or not isinstance(v, (int, float)) \
                        or not math.isfinite(v):
                    st.missing[name] = st.missing.get(name, 0) + 1
                else:
                    per_feature.setdefault(name, []).append(float(v))
            if math.isfinite(sv):
                scores.append(sv)
        for name, vals in per_feature.items():
            sk = st.sketches.get(name)
            if sk is None:
                sk = st.sketches[name] = WeightedQuantileSketch(b=st.b)
            sk.push(np.asarray(vals, np.float64))
        if scores:
            arr = np.asarray(scores, np.float64)
            st.score_sketch.push(arr)
            st.score_sum += float(np.sum(arr))
            st.score_n += len(arr)

    def _compute(self, st: _ModelState) -> dict:
        """Per-feature PSI/KS + score drift + calibration (under st.lock)."""
        feats_out: Dict[str, dict] = {}
        psi_max = ks_max = 0.0
        worst: List[Tuple[float, str]] = []
        base = st.baseline
        for name, info in base["features"].items():
            sk = st.sketches.get(name)
            # ONE summary() per feature per tick: it merges the whole GK
            # level cascade, and this runs under st.lock next to the
            # request path's staging
            serve_sum = sk.summary() if sk is not None else None
            rows = int(serve_sum.total) if serve_sum is not None else 0
            rec: Dict[str, object] = {
                "rows": rows,
                "missing": st.missing.get(name, 0),
                "missing_rate": round(
                    st.missing.get(name, 0) / max(st.rows_sampled, 1), 4
                ),
                "baseline_present": info["present"],
            }
            if serve_sum is not None and rows > 0:
                p = psi_summaries(info["summary"], serve_sum)
                k = ks_summaries(info["summary"], serve_sum)
                if p is not None:
                    rec["psi"] = round(p, 4)
                    psi_max = max(psi_max, p)
                    worst.append((p, name))
                if k is not None:
                    rec["ks"] = round(k, 4)
                    ks_max = max(ks_max, k)
            feats_out[name] = rec
        score_psi = None
        cal_delta = None
        mean_pred = None
        if st.score_n > 0:
            mean_pred = st.score_sum / st.score_n
            if base["score"] is not None:
                score_psi = psi_summaries(base["score"], st.score_sketch.summary())
                cal_delta = abs(mean_pred - base["score_mean"])
        worst.sort(reverse=True)
        return {
            "rows_seen": st.rows_seen,
            "rows_sampled": st.rows_sampled,
            "buffer_dropped": st.buf_dropped,
            "psi_max": round(psi_max, 4),
            "ks_max": round(ks_max, 4),
            "worst_features": [name for _p, name in worst[:3]],
            "features": feats_out,
            "score": {
                "psi": round(score_psi, 4) if score_psi is not None else None,
                "mean_pred": (
                    round(mean_pred, 6) if mean_pred is not None else None
                ),
                "baseline_mean": round(base["score_mean"], 6),
                "calibration_delta": (
                    round(cal_delta, 6) if cal_delta is not None else None
                ),
            },
        }

    def evaluate(self, feed_sentinels: bool = True) -> dict:
        """Drain every model's staged rows, recompute drift metrics, and
        (from the evaluator thread only) feed the sentinels. Returns the
        per-model metrics. Cheap when nothing was sampled."""
        with self._lock:
            states = list(self._states.values())
        out: Dict[str, dict] = {}
        psi_all = ks_all = cal_all = 0.0
        for st in states:
            if st.no_baseline:
                with st.lock:
                    out[st.key] = {
                        "model": st.model, "version": st.version,
                        "no_baseline": True, "rows_seen": st.rows_seen,
                        "rows_sampled": st.rows_sampled,
                    }
                continue
            with st.lock:
                buf, st.buf = st.buf, []
                self._ingest(st, buf)
                metrics = self._compute(st)
                st.last_eval = metrics
                rows_sampled = st.rows_sampled
            metrics = {
                "model": st.model, "version": st.version,
                "fingerprint": st.fingerprint, "no_baseline": False,
                **metrics,
            }
            out[st.key] = metrics
            psi_all = max(psi_all, metrics["psi_max"])
            ks_all = max(ks_all, metrics["ks_max"])
            cal = metrics["score"]["calibration_delta"]
            if cal is not None:
                cal_all = max(cal_all, cal)
            if feed_sentinels:
                # sentinel observe OUTSIDE st.lock: a strict-mode fire
                # writes a flight dump, and IO under a request-path lock
                # is the ytklint blocking-call-under-lock shape
                st.drift.observe(
                    metrics["psi_max"], metrics["ks_max"], rows_sampled,
                    model=st.model, version=st.version,
                    worst_features=",".join(metrics["worst_features"]),
                )
                if cal is not None:
                    st.calibration.observe(
                        cal, rows_sampled, model=st.model,
                        version=st.version,
                        mean_pred=metrics["score"]["mean_pred"],
                        baseline_mean=metrics["score"]["baseline_mean"],
                    )
        if states:
            core.gauge("quality.psi_max", psi_all)
            core.gauge("quality.ks_max", ks_all)
            core.gauge("quality.calibration_delta", cal_all)
        core.inc("quality.evals")
        return out

    # -- export -------------------------------------------------------------

    def snapshot(
        self, include_sketches: bool = False, refresh: bool = True
    ) -> dict:
        """The `/metrics?quality=1` document. `include_sketches`
        additionally serializes the per-feature serve-side GK summaries
        AND the baseline summaries — the fleet front merges the former
        and judges against the latter (merge_quality_payloads)."""
        models = (
            self.evaluate(feed_sentinels=False) if refresh
            else {
                st.key: {"model": st.model, "version": st.version,
                         "no_baseline": st.no_baseline,
                         **(st.last_eval or {})}
                for st in list(self._states.values())
            }
        )
        if include_sketches:
            with self._lock:
                states = list(self._states.values())
            for st in states:
                m = models.get(st.key)
                if m is None or st.no_baseline:
                    continue
                with st.lock:
                    m["sketches"] = {
                        name: summary_to_json(prune_summary(sk.summary(), st.b))
                        for name, sk in st.sketches.items()
                    }
                    m["baseline"] = {
                        name: summary_to_json(info["summary"])
                        for name, info in st.baseline["features"].items()
                    }
                    m["baseline_score"] = (
                        summary_to_json(st.baseline["score"])
                        if st.baseline["score"] is not None else None
                    )
                    m["baseline_score_mean"] = st.baseline["score_mean"]
                    m["score_sketch"] = summary_to_json(
                        prune_summary(st.score_sketch.summary(), st.b)
                    )
                    m["score_sum"] = st.score_sum
                    m["score_n"] = st.score_n
        return {
            "sample": self.rate,
            "seed": self.seed,
            "sketch_b": self.b,
            "models": models,
        }


# ---------------------------------------------------------------------------
# Fleet merge: per-replica serve-side summaries -> one fleet drift view
# ---------------------------------------------------------------------------


def merge_quality_payloads(per_replica: Dict[str, dict]) -> dict:
    """Merge replica `/metrics?quality=1` payloads (with sketches) into
    the fleet-level view: per (model, version), every replica's
    serve-side GK summary merges via `merge_summaries` — associative and
    commutative, so replica order cannot change the answer (test-pinned)
    — and fleet PSI/KS are computed over the MERGED distribution against
    the shared baseline. Returns {"fleet": {model_key: {...}},
    "replicas": {rid: {model_key: compact}}}."""
    fleet: Dict[str, dict] = {}
    compact: Dict[str, dict] = {}
    merged_sketch: Dict[str, Dict[str, Summary]] = {}
    merged_score: Dict[str, Summary] = {}
    baselines: Dict[str, dict] = {}
    for rid in sorted(per_replica):
        payload = per_replica[rid] or {}
        rep_compact: Dict[str, dict] = {}
        for key, m in (payload.get("models") or {}).items():
            rep_compact[key] = {
                "psi_max": m.get("psi_max"),
                "ks_max": m.get("ks_max"),
                "rows_sampled": m.get("rows_sampled"),
                "no_baseline": m.get("no_baseline", False),
            }
            # ONE dict shape for both branches: replicas can legitimately
            # disagree on no_baseline for the same key (one spawned before
            # the sidecar landed, one after) — a shape split here was a
            # KeyError that took /metrics?quality=1 down fleet-wide
            f = fleet.setdefault(key, {
                "model": m.get("model"), "version": m.get("version"),
                "no_baseline": True, "rows_seen": 0, "rows_sampled": 0,
                "replicas": 0, "score_sum": 0.0, "score_n": 0,
            })
            f["rows_seen"] += int(m.get("rows_seen") or 0)
            f["rows_sampled"] += int(m.get("rows_sampled") or 0)
            if m.get("no_baseline"):
                continue
            # any replica WITH a baseline makes the fleet view a real one
            f["no_baseline"] = False
            f["replicas"] += 1
            f["score_sum"] += float(m.get("score_sum") or 0.0)
            f["score_n"] += int(m.get("score_n") or 0)
            if key not in baselines and m.get("baseline"):
                baselines[key] = m
            sketches = merged_sketch.setdefault(key, {})
            for name, sj in (m.get("sketches") or {}).items():
                s = summary_from_json(sj)
                prev = sketches.get(name)
                sketches[name] = s if prev is None else merge_summaries(prev, s)
            if m.get("score_sketch"):
                s = summary_from_json(m["score_sketch"])
                prev = merged_score.get(key)
                merged_score[key] = (
                    s if prev is None else merge_summaries(prev, s)
                )
        compact[rid] = rep_compact
    for key, f in fleet.items():
        if f.get("no_baseline"):
            # every replica served this key baseline-less: drop the
            # accumulator fields that only mean something with a baseline
            f.pop("replicas", None)
            f.pop("score_sum", None)
            f.pop("score_n", None)
            continue
        base_m = baselines.get(key)
        if base_m is None:
            continue
        feats_out: Dict[str, dict] = {}
        psi_max = ks_max = 0.0
        worst: List[Tuple[float, str]] = []
        for name, bj in (base_m.get("baseline") or {}).items():
            base_s = summary_from_json(bj)
            serve_s = merged_sketch.get(key, {}).get(name)
            if serve_s is None or serve_s.total <= 0:
                continue
            p = psi_summaries(base_s, serve_s)
            k = ks_summaries(base_s, serve_s)
            rec = {"rows": int(serve_s.total)}
            if p is not None:
                rec["psi"] = round(p, 4)
                psi_max = max(psi_max, p)
                worst.append((p, name))
            if k is not None:
                rec["ks"] = round(k, 4)
                ks_max = max(ks_max, k)
            feats_out[name] = rec
        worst.sort(reverse=True)
        f["features"] = feats_out
        f["psi_max"] = round(psi_max, 4)
        f["ks_max"] = round(ks_max, 4)
        f["worst_features"] = [name for _p, name in worst[:3]]
        score_s = merged_score.get(key)
        base_score = base_m.get("baseline_score")
        score_rec: Dict[str, object] = {
            "baseline_mean": base_m.get("baseline_score_mean"),
        }
        if f["score_n"] > 0:
            mean_pred = f["score_sum"] / f["score_n"]
            score_rec["mean_pred"] = round(mean_pred, 6)
            if base_m.get("baseline_score_mean") is not None:
                score_rec["calibration_delta"] = round(
                    abs(mean_pred - float(base_m["baseline_score_mean"])), 6
                )
        if score_s is not None and base_score:
            p = psi_summaries(summary_from_json(base_score), score_s)
            if p is not None:
                score_rec["psi"] = round(p, 4)
        f["score"] = score_rec
        f.pop("score_sum", None)
        f.pop("score_n", None)
    return {"fleet": fleet, "replicas": compact}


# ---------------------------------------------------------------------------
# Module-level default monitor + evaluator thread
# ---------------------------------------------------------------------------

_default: Optional[QualityMonitor] = None
_default_lock = threading.Lock()


def default_monitor() -> QualityMonitor:
    global _default
    with _default_lock:
        if _default is None:
            _default = QualityMonitor()
        return _default


def configure_quality(sample=None, seed=None, b=None, reset=False) -> None:
    """Runtime override of the YTK_QUALITY_* env knobs (tests/drills)."""
    default_monitor().configure(sample=sample, seed=seed, b=b, reset=reset)


def quality_enabled() -> bool:
    return default_monitor().enabled()


#: the singleton evaluator thread + stop event (the obs history-sampler
#: discipline: daemon thread, start is idempotent, stop joins)
_evaluator: Optional[threading.Thread] = None
_evaluator_stop: Optional[threading.Event] = None
_evaluator_lock = threading.Lock()


@thread_guard
def _evaluator_loop(stop: threading.Event, interval_s: float) -> None:
    while not stop.wait(interval_s):
        try:
            default_monitor().evaluate(feed_sentinels=True)
        except health.HealthError:
            raise  # strict escalation is the operator's explicit ask
        except Exception:  # noqa: BLE001 — the evaluator must survive
            log.exception("quality evaluator tick crashed")


def start_quality_evaluator(interval_s: Optional[float] = None) -> bool:
    """Arm the periodic drift/calibration evaluator. Idempotent — the
    serving layer calls this at every start(); False when the plane is
    off (YTK_QUALITY_SAMPLE=0)."""
    global _evaluator, _evaluator_stop
    if not default_monitor().enabled():
        return False
    every = (
        interval_s if interval_s is not None
        else knobs.get_float("YTK_QUALITY_EVAL_S")
    ) or 5.0
    with _evaluator_lock:
        if _evaluator is not None and _evaluator.is_alive():
            return True
        stop = threading.Event()
        t = threading.Thread(
            target=_evaluator_loop, args=(stop, float(every)),
            name="ytk-quality-eval", daemon=True,
        )
        _evaluator, _evaluator_stop = t, stop
        t.start()
    return True


def stop_quality_evaluator() -> None:
    """Stop the evaluator thread (joined) — test isolation; production
    processes just exit (the thread is a daemon)."""
    global _evaluator, _evaluator_stop
    with _evaluator_lock:
        t, stop = _evaluator, _evaluator_stop
        _evaluator, _evaluator_stop = None, None
    if stop is not None:
        stop.set()
    if t is not None:
        t.join(timeout=10.0)


def evaluator_running() -> bool:
    with _evaluator_lock:
        return _evaluator is not None and _evaluator.is_alive()
