"""Exporters: JSONL event stream + Chrome-trace/Perfetto JSON.

Chrome trace format (the JSON Object Format of the Trace Event spec —
what chrome://tracing and https://ui.perfetto.dev both load): spans are
complete "X" events with µs timestamps relative to the process clock
origin, counters become one "C" sample at the trace end, and "M" metadata
events name the process/threads. `tests/test_obs.py` pins validity
(parses, every X has ts+dur, B/E — if ever emitted — must match).

JSONL: line 1 is a meta record carrying the schema version and the wall
origin; every following line is one event / counter / gauge record.
`load_jsonl` is the inverse (schema round-trip tested).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from .core import REGISTRY, WALL_T0, Registry

JSONL_SCHEMA_VERSION = 1


def _tid_map(events: List[dict]) -> Dict[int, int]:
    """Compress python thread idents into small stable tids (0 = first)."""
    out: Dict[int, int] = {}
    for ev in events:
        t = ev.get("tid", 0)
        if t not in out:
            out[t] = len(out)
    return out


def chrome_trace_events(registry: Registry = REGISTRY) -> List[dict]:
    with registry._lock:
        events = list(registry.events)
        counters = dict(registry.counters)
    pid = os.getpid()
    tids = _tid_map(events)
    out: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "ytklearn-tpu"},
        }
    ]
    end_ts = 0.0
    for ev in events:
        ts_us = ev["ts"] * 1e6
        rec = {
            "name": ev["name"],
            "cat": ev["name"].split(".", 1)[0],
            "ph": ev["ph"],
            "ts": round(ts_us, 3),
            "pid": pid,
            "tid": tids.get(ev.get("tid", 0), 0),
        }
        if ev["ph"] == "X":
            rec["dur"] = round(ev.get("dur", 0.0) * 1e6, 3)
            end_ts = max(end_ts, ts_us + rec["dur"])
        else:
            if ev["ph"] == "i":
                rec["s"] = "t"  # thread-scoped instant
            end_ts = max(end_ts, ts_us)
        if ev.get("args"):
            rec["args"] = ev["args"]
        out.append(rec)
    for name, value in sorted(counters.items()):
        out.append(
            {
                "name": name,
                "cat": "counter",
                "ph": "C",
                "ts": round(end_ts, 3),
                "pid": pid,
                "tid": 0,
                "args": {"value": value},
            }
        )
    return out


def export_chrome_trace(path: str, registry: Registry = REGISTRY) -> str:
    """Write a Perfetto-loadable Chrome trace JSON; returns the path."""
    doc = {
        "traceEvents": chrome_trace_events(registry),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "ytklearn_tpu.obs", "wall_t0": WALL_T0},
    }
    from ..io.fs import LocalFileSystem  # lazy: fs pulls the retry seam, which imports obs

    with LocalFileSystem().atomic_open(path, "w") as f:
        json.dump(doc, f)
    return path


def exemplar_trace_events(
    payloads: List[dict], align_wall_t0: Optional[float] = None
) -> List[dict]:
    """Merge per-process /admin/traces payloads (obs/trace.py
    `exemplars_payload()`) into one clock-aligned Chrome-trace event list.

    Each payload carries its process's `wall_t0` (the obs clock origin on
    the wall clock); hop offsets become wall times and are re-anchored to
    the EARLIEST origin across payloads, so front and replica spans of
    one trace id line up on a single Perfetto timeline. Each process gets
    its own pid lane; every exemplar contributes one enclosing span plus
    its hops, all tagged with the trace id."""
    if align_wall_t0 is None:
        align_wall_t0 = min(
            (p.get("wall_t0") or 0.0 for p in payloads), default=0.0
        )
    out: List[dict] = []
    for p in payloads:
        pid = p.get("pid") or 0
        ident = p.get("identity") or {}
        label = ("replica %s" % ident["replica_id"]
                 if "replica_id" in ident else "front/solo")
        out.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"ytk-serve {label} (pid {pid})"},
        })
        base_us = ((p.get("wall_t0") or 0.0) - align_wall_t0) * 1e6
        for rec in p.get("exemplars") or []:
            ts_us = base_us + rec.get("ts", 0.0) * 1e6
            dur_us = rec.get("latency_ms", 0.0) * 1e3
            args = {"trace_id": rec.get("trace_id"),
                    "kept": rec.get("kept"),
                    "status": rec.get("status")}
            out.append({
                "name": f"trace.request[{rec.get('kept')}]",
                "cat": "trace", "ph": "X", "pid": pid, "tid": 0,
                "ts": round(ts_us, 3), "dur": round(dur_us, 3),
                "args": args,
            })
            for hop in rec.get("hops") or []:
                h_args = dict(hop.get("args") or {})
                h_args["trace_id"] = rec.get("trace_id")
                out.append({
                    "name": hop["name"], "cat": "trace.hop", "ph": "X",
                    "pid": pid, "tid": 1,
                    "ts": round(base_us + hop.get("ts", 0.0) * 1e6, 3),
                    "dur": round(hop.get("dur_ms", 0.0) * 1e3, 3),
                    "args": h_args,
                })
    return out


def export_jsonl(path: str, registry: Registry = REGISTRY) -> str:
    """Write the JSONL event stream; returns the path."""
    with registry._lock:
        events = list(registry.events)
        counters = dict(registry.counters)
        gauges = dict(registry.gauges)
    from ..io.fs import LocalFileSystem  # lazy: fs pulls the retry seam, which imports obs

    with LocalFileSystem().atomic_open(path, "w") as f:
        f.write(
            json.dumps(
                {
                    "type": "meta",
                    "schema_version": JSONL_SCHEMA_VERSION,
                    "wall_t0": WALL_T0,
                    "pid": os.getpid(),
                }
            )
            + "\n"
        )
        for ev in events:
            rec = {"type": "span" if ev["ph"] == "X" else "event"}
            rec.update(ev)
            f.write(json.dumps(rec) + "\n")
        for name, value in sorted(counters.items()):
            f.write(
                json.dumps({"type": "counter", "name": name, "value": value}) + "\n"
            )
        for name, value in sorted(gauges.items()):
            f.write(
                json.dumps({"type": "gauge", "name": name, "value": value}) + "\n"
            )
    return path


def load_jsonl(path: str) -> dict:
    """Parse a JSONL export back into {meta, events, counters, gauges}."""
    meta: dict = {}
    events: List[dict] = []
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    from ..io.fs import LocalFileSystem  # lazy: fs pulls the retry seam, which imports obs

    with LocalFileSystem().open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            t = rec.pop("type", None)
            if t == "meta":
                meta = rec
            elif t in ("span", "event"):
                events.append(rec)
            elif t == "counter":
                counters[rec["name"]] = rec["value"]
            elif t == "gauge":
                gauges[rec["name"]] = rec["value"]
    return {"meta": meta, "events": events, "counters": counters, "gauges": gauges}
