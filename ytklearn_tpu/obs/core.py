"""Tracing + metrics core — one process-wide registry for every layer.

The reference scattered its run evidence across ad-hoc plumbing
(`trainer.time_stats`, per-tree device fetches, stderr progress prints);
this module replaces all of that with three primitives every layer shares:

  spans     nested wall-clock intervals (`with span("tree.grow", tree=t):`)
            with optional device-settled timing (`settle=` blocks on a jax
            value before the end timestamp is taken)
  counters  monotonically accumulated floats (`inc("ingest.rows", n)`)
  gauges    last-write-wins floats (`gauge("gbdt.partition", 1)`)

Everything lands in one `Registry`; the exporters (obs/export.py) turn it
into a JSONL event stream and a Chrome-trace/Perfetto JSON file.

Disabled-path contract (the < 1% tier-1 overhead budget): with obs off,
`span()` is one module-global attribute load plus a cached no-op context
manager, and `inc()`/`gauge()`/`event()` are one attribute load + return.
No locks, no allocation beyond the kwargs dict at the call site. Tests
pin this (tests/test_obs.py::test_disabled_path_is_noop).

Env knobs (read once at import; `configure()` overrides at runtime):
  YTK_TRACE=path        enable + write a Chrome-trace JSON at process exit
  YTK_TRACE_JSONL=path  enable + write the JSONL event stream at exit
  YTK_OBS=1             enable collection without any export
  YTK_OBS=0             force-disable (wins over the path knobs)
  YTK_OBS_JAX=1         also wrap spans in jax.profiler.TraceAnnotation so
                        they show up inside XLA/xprof traces
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
from typing import Dict, List, Optional

from ..config import knobs

# process-level clock origin: span timestamps are seconds since import on
# the monotonic clock (Chrome trace wants relative µs; JSONL carries the
# wall origin in its meta line so events can be re-anchored)
_T0 = time.perf_counter()
WALL_T0 = time.time()


def _now() -> float:
    return time.perf_counter() - _T0


class Registry:
    """Process-wide store for counters, gauges, and finished span events.

    Span *stacks* are thread-local (nesting is a per-thread property);
    counters/gauges/events are shared under one lock — contention is nil
    because the hot paths touch the registry a handful of times per
    tree/iteration, never per row.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.events: List[dict] = []
        # flight-recorder ring (obs/recorder.py): a bounded deque the
        # recorder installs so the last N events survive for a postmortem
        # dump even though `events` may be huge. None when not installed.
        self.ring = None
        # metrics history plane: per-metric bounded (wall_ts, value) rings
        # fed by sample_history() (the heartbeat sampler thread) so every
        # counter/gauge has a recent time series, not just a point-in-time
        # value. None until enable_history(); bounded per metric by
        # YTK_OBS_HISTORY_N. /metrics?history=1 exports it.
        self.history = None
        self._history_n = 0
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def add_event(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)
            if self.ring is not None:
                self.ring.append(ev)

    def snapshot(self) -> dict:
        """Point-in-time copy of counters + gauges (the bench/report
        surface; events are export-only)."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
            }

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.events.clear()
            if self.ring is not None:
                self.ring.clear()
            if self.history is not None:
                self.history.clear()

    # -- metrics history plane -------------------------------------------

    def enable_history(self, n: int) -> None:
        """Arm per-metric time-series rings of length `n` (idempotent at
        the same capacity; re-arming at a new capacity starts fresh)."""
        with self._lock:
            if self.history is None or self._history_n != n:
                self.history = {}
                self._history_n = max(1, int(n))

    def disable_history(self) -> None:
        with self._lock:
            self.history = None
            self._history_n = 0

    def sample_history(self, now: Optional[float] = None) -> None:
        """Append one (wall_ts, value) sample per live counter/gauge. One
        lock hold, dict-scan cost — called at the history interval (1 s
        default), never per request/row."""
        if self.history is None:
            return
        if now is None:
            now = time.time()
        ts = round(now, 3)
        with self._lock:
            hist = self.history
            if hist is None:  # disabled between check and lock
                return
            n = self._history_n
            for name, value in self.counters.items():
                ring = hist.get(name)
                if ring is None:
                    ring = hist[name] = collections.deque(maxlen=n)
                ring.append((ts, value))
            for name, value in self.gauges.items():
                ring = hist.get(name)
                if ring is None:
                    ring = hist[name] = collections.deque(maxlen=n)
                ring.append((ts, value))

    def history_snapshot(self) -> Optional[dict]:
        """{"series": {name: [[wall_ts, value], ...]}} or None when the
        history plane is off."""
        with self._lock:
            if self.history is None:
                return None
            return {
                "ring_n": self._history_n,
                "series": {
                    name: [[t, v] for t, v in ring]
                    for name, ring in sorted(self.history.items())
                },
            }


REGISTRY = Registry()

#: process identity attached to every obs event + flight dump (serve fleet:
#: a replica worker stamps its replica_id here at startup, so a fleet
#: postmortem names the sick replica instead of "some pid"). Empty = solo
#: process, nothing is attached. Written once at process start, read-only
#: after — no lock needed.
IDENTITY: Dict[str, object] = {}


def set_identity(**kw) -> None:
    """Stamp process identity (e.g. replica_id=3) onto every subsequent
    obs event and flight dump. Values must be JSON-serializable."""
    IDENTITY.update({k: v for k, v in kw.items() if v is not None})


class _State:
    __slots__ = ("enabled", "trace_path", "jsonl_path", "jax_annotations")

    def __init__(self):
        self.enabled = False
        self.trace_path: Optional[str] = None
        self.jsonl_path: Optional[str] = None
        self.jax_annotations = False


_state = _State()
_UNSET = object()


def enabled() -> bool:
    return _state.enabled


class _NoopSpan:
    """Cached do-nothing context manager — the whole disabled span path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **kw):
        return self


NOOP_SPAN = _NoopSpan()


class Span:
    """An open span; records one complete ("X") event on exit.

    `settle` (array, pytree, or zero-arg callable returning one) is
    block_until_ready'd before the end timestamp — opt-in device-settled
    timing for spans that enqueue async device work.
    """

    __slots__ = ("name", "args", "t0", "_settle", "_jax_ann")

    def __init__(self, name: str, args: dict, settle=None):
        self.name = name
        self.args = args
        self._settle = settle
        self._jax_ann = None

    def add(self, **kw) -> "Span":
        self.args.update(kw)
        return self

    def __enter__(self) -> "Span":
        if _state.jax_annotations:
            try:
                import jax.profiler

                self._jax_ann = jax.profiler.TraceAnnotation(self.name)
                self._jax_ann.__enter__()
            # ytklint: allow(broad-except) reason=profiler annotation is best-effort decoration; a broken profiler must not fail the span
            except Exception:
                self._jax_ann = None
        REGISTRY._stack().append(self.name)
        self.t0 = _now()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._settle is not None:
            try:
                import jax

                target = self._settle() if callable(self._settle) else self._settle
                jax.block_until_ready(target)
            # ytklint: allow(broad-except) reason=settle targets may be deleted/donated by exit time; timing must never kill the run
            except Exception:
                pass
        t1 = _now()
        if self._jax_ann is not None:
            try:
                self._jax_ann.__exit__(exc_type, exc, tb)
            # ytklint: allow(broad-except) reason=profiler exit is best-effort; the span event must still be recorded below
            except Exception:
                pass
        stack = REGISTRY._stack()
        if stack:
            stack.pop()
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": self.t0,
            "dur": t1 - self.t0,
            "tid": threading.get_ident(),
            "depth": len(stack),
        }
        if self.args:
            ev["args"] = self.args
        if exc_type is not None:
            ev.setdefault("args", {})["error"] = exc_type.__name__
        REGISTRY.add_event(ev)
        return False


def span(name: str, settle=None, **args):
    """`with span("tree.grow", tree=t): ...` — no-op when obs is disabled.

    `settle` is reserved: pass a jax value (or a callable producing one)
    to block on it before the end timestamp (device-settled duration)."""
    if not _state.enabled:
        return NOOP_SPAN
    return Span(name, args, settle)


def inc(name: str, value: float = 1.0) -> None:
    if not _state.enabled:
        return
    REGISTRY.inc(name, value)


def gauge(name: str, value: float) -> None:
    if not _state.enabled:
        return
    REGISTRY.gauge(name, value)


def event(name: str, **args) -> None:
    """Instant event (Chrome-trace "i" phase) — a point-in-time marker."""
    if not _state.enabled:
        return
    ev = {
        "name": name,
        "ph": "i",
        "ts": _now(),
        "tid": threading.get_ident(),
        "depth": len(REGISTRY._stack()),
    }
    if IDENTITY:
        args = {**IDENTITY, **args} if args else dict(IDENTITY)
    if args:
        ev["args"] = args
    REGISTRY.add_event(ev)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def reset() -> None:
    REGISTRY.reset()


# ---------------------------------------------------------------------------
# Collective-call recording (parallel/collectives.py hooks)
# ---------------------------------------------------------------------------


def _leaf_bytes(x) -> int:
    """Static byte size of an array-like (works on jax tracers: shape and
    dtype are trace-time facts) or a pytree of them."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            return int(math.prod(shape)) * int(dtype.itemsize)
        # ytklint: allow(broad-except) reason=abstract/extended dtypes without itemsize count as 0 bytes in the census
        except Exception:
            return 0
    if isinstance(x, dict):
        return sum(_leaf_bytes(v) for v in x.values())
    if isinstance(x, (tuple, list)):
        return sum(_leaf_bytes(v) for v in x)
    return 0


def record_collective(verb: str, x, axis_name: str) -> None:
    """Count a collective verb + its operand bytes and drop a zero-duration
    span into the trace.

    Called from the collectives module at *trace time* (inside jit
    tracing), so counts are per-compilation, not per-execution — a static
    census of the program's collective surface. That is exactly what you
    want when debugging a hung multi-host collective ("which verbs, what
    sizes, staged from where"); per-step collective wall time lives in the
    XLA profile (YTK_OBS_JAX=1 / YTK_PROFILE_DIR)."""
    if not _state.enabled:
        return
    nbytes = _leaf_bytes(x)
    REGISTRY.inc(f"collectives.{verb}.calls", 1.0)
    REGISTRY.inc(f"collectives.{verb}.bytes", float(nbytes))
    REGISTRY.add_event(
        {
            "name": f"collectives.{verb}",
            "ph": "X",
            "ts": _now(),
            "dur": 0.0,
            "tid": threading.get_ident(),
            "depth": len(REGISTRY._stack()),
            "args": {"axis": axis_name, "bytes": nbytes, "traced": True},
        }
    )


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

_atexit_registered = False


def _ensure_atexit() -> None:
    global _atexit_registered
    if _atexit_registered:
        return
    import atexit

    atexit.register(flush)
    _atexit_registered = True


def flush() -> None:
    """Write the configured exports now (also runs at process exit)."""
    from .export import export_chrome_trace, export_jsonl

    if _state.trace_path:
        export_chrome_trace(_state.trace_path, REGISTRY)
    if _state.jsonl_path:
        export_jsonl(_state.jsonl_path, REGISTRY)


def configure(
    enabled: Optional[bool] = None,
    trace_path=_UNSET,
    jsonl_path=_UNSET,
    jax_annotations: Optional[bool] = None,
) -> None:
    """Runtime configuration (the CLI's --trace-out lands here).

    Setting a non-empty export path implies enabled=True unless `enabled`
    is explicitly passed as False in the same call."""
    if trace_path is not _UNSET:
        _state.trace_path = trace_path or None
        if trace_path and enabled is None:
            enabled = True
    if jsonl_path is not _UNSET:
        _state.jsonl_path = jsonl_path or None
        if jsonl_path and enabled is None:
            enabled = True
    if enabled is not None:
        _state.enabled = bool(enabled)
    if jax_annotations is not None:
        _state.jax_annotations = bool(jax_annotations)
    if _state.trace_path or _state.jsonl_path:
        _ensure_atexit()


def _configure_from_env() -> None:
    flag = knobs.get_raw("YTK_OBS")
    if flag == "0":  # force-off wins over everything
        return
    trace = knobs.get_str("YTK_TRACE")
    jsonl = knobs.get_str("YTK_TRACE_JSONL")
    if trace or jsonl or flag == "1":
        configure(enabled=True, trace_path=trace, jsonl_path=jsonl)
    if knobs.get_bool("YTK_OBS_JAX"):
        _state.jax_annotations = True


_configure_from_env()
