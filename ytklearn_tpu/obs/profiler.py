"""ytkprof — device-time, compile-cost, and memory-watermark profiling.

The r7 span substrate answers *what ran and for how long on the host*;
this plane answers the three questions it could not:

  where does **device** time go?   phase accounting + an opt-in
      `jax.profiler.trace` capture per phase, parsed into device-time
      buckets per named span and a top-k kernel table. On CPU/interpreter
      (no hardware) the plane degrades to settled wall-time: phases still
      decompose the run, the kernel table comes from the CPU trace's HLO
      events when a capture exists and is empty otherwise.

  why did a steady-state **recompile** fire?   a compile ledger records
      every XLA backend compile (program label, abstract arg signature,
      compile ms). Instrumented call sites label the compile via
      `LEDGER.program(...)`; the r8 RetraceSentinel asks the ledger for
      entries since it armed, so `health.retrace` names the culprit
      program and the argument/dim that changed instead of reporting a
      bare counter delta.

  what allocated the memory?   a background watermark sampler feeds
      device bytes-in-use + host RSS into bounded history rings (the r17
      ring idiom) and attributes peak watermarks to the enclosing
      profiler phase; the phase peaks ride flight dumps so an OOM
      postmortem names the allocating phase.

Disabled-path contract (mirrors obs core): with `YTK_PROF` unset/`0`,
`phase()` is one module-global attribute load plus a cached no-op
context manager and `LEDGER.program()` returns the same cached no-op —
zero new per-call work (tests/test_profiler.py pins this).

Knobs: YTK_PROF (`1` = on, a path = on + capture dir), YTK_PROF_TOPK,
YTK_PROF_MEM_S, YTK_PROF_LEDGER_N. The CLI's `--profile [DIR]` lands on
`configure_profiler()`.
"""

from __future__ import annotations

import collections
import gzip
import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..config import knobs
from . import core
from .recorder import thread_guard

log = logging.getLogger("ytklearn_tpu.obs.profiler")

_UNSET = object()

SCHEMA = "ytkprof"

# signature strings are capped so a pathological pytree cannot bloat
# events, ledger entries, or flight dumps
_SIG_MAX_LEAVES = 256
_DIFF_MAX_LINES = 16


class _ProfState:
    __slots__ = ("on", "capture_dir", "topk", "mem_interval")

    def __init__(self):
        self.on = False
        self.capture_dir: Optional[str] = None
        self.topk = 10
        self.mem_interval = 0.5


_state = _ProfState()


def enabled() -> bool:
    return _state.on


def capture_dir() -> Optional[str]:
    return _state.capture_dir


# ---------------------------------------------------------------------------
# Phase accounting
# ---------------------------------------------------------------------------


class _NoopPhase:
    """Cached do-nothing context manager — the whole disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_PHASE = _NoopPhase()

#: per-process phase stack shared across threads *for reading* by the mem
#: sampler (which must attribute a sample to "the phase the trainer is in
#: right now"); writes happen under _acc_lock. Entries are phase names.
_phase_stack: List[str] = []

_acc_lock = threading.Lock()
#: name -> {"wall_s": float, "count": int, "depth": int(min seen)}
_phases: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
#: (phase_name, capture_subdir) for every completed jax.profiler capture
_captures: List[Tuple[str, str]] = []
#: only one jax.profiler.trace may be live per process
_capture_active = threading.Lock()


def current_phase() -> Optional[str]:
    """Innermost open profiler phase (None outside any phase). Lock-free
    read of the shared stack — worst case the sampler sees a phase one
    tick stale, which is fine for watermark attribution."""
    st = _phase_stack
    return st[-1] if st else None


class _Phase:
    __slots__ = ("name", "_span", "_capture", "_cap_dir", "_t0")

    def __init__(self, name: str, settle, capture: bool, args: dict):
        self.name = name
        self._span = core.span(name, settle=settle, **args)
        self._capture = capture
        self._cap_dir = None

    def __enter__(self) -> "_Phase":
        with _acc_lock:
            _phase_stack.append(self.name)
        # capture must open *before* the span: TraceAnnotations only
        # record when the profiler is live at annotation start, and the
        # phase's own annotation is the top-level bucket in the capture
        if self._capture and _state.capture_dir:
            self._start_capture()
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def _start_capture(self) -> None:
        # one live capture per process: a second concurrent request (or a
        # YTK_PROFILE_DIR trace already running) skips and counts instead
        # of raising out of the phase body
        if not _capture_active.acquire(blocking=False):
            core.inc("prof.capture.skipped")
            return
        sub = os.path.join(
            _state.capture_dir,
            "%s_%d" % (self.name.replace("/", "_"), os.getpid()),
        )
        try:
            import jax.profiler

            os.makedirs(sub, exist_ok=True)
            jax.profiler.start_trace(sub)
            self._cap_dir = sub
        except Exception as e:  # capture is best-effort decoration
            log.debug("prof capture start failed for %s: %s", self.name, e)
            core.inc("prof.capture.failed")
            _capture_active.release()

    def __exit__(self, exc_type, exc, tb) -> bool:
        # Span.__exit__ runs the settle (block_until_ready) before its end
        # timestamp; exiting the span *before* taking our own end time
        # means the accountant records the settled duration too
        self._span.__exit__(exc_type, exc, tb)
        if self._cap_dir is not None:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
                with _acc_lock:
                    _captures.append((self.name, self._cap_dir))
            except Exception as e:  # backend may tear down mid-phase
                log.debug("prof capture stop failed: %s", e)
                core.inc("prof.capture.failed")
            finally:
                _capture_active.release()
        dt = time.perf_counter() - self._t0
        with _acc_lock:
            if _phase_stack:
                _phase_stack.pop()
            depth = len(_phase_stack)
            rec = _phases.get(self.name)
            if rec is None:
                _phases[self.name] = {"wall_s": dt, "count": 1, "depth": depth}
            else:
                rec["wall_s"] += dt
                rec["count"] += 1
                if depth < rec["depth"]:
                    rec["depth"] = depth
        return False


def phase(name: str, settle=None, capture: bool = False, **args):
    """`with profiler.phase("gbdt.train", capture=True): ...`

    Opens an obs span (which carries the TraceAnnotation when armed),
    pushes the phase for watermark attribution, optionally wraps the body
    in a `jax.profiler.trace` capture, and records settled wall time into
    the phase accountant.

    With the plane off this *is* `core.span(...)` — call sites that used
    to open a bare span can move to phase() without changing behavior,
    and with obs off too the whole call degrades to the same cached
    NOOP_SPAN the r7 contract pins."""
    if not _state.on:
        return core.span(name, settle=settle, **args)
    return _Phase(name, settle, capture, args)


def phases_snapshot() -> Dict[str, dict]:
    """{name: {wall_s, count, depth}} in first-seen order."""
    with _acc_lock:
        return {k: dict(v) for k, v in _phases.items()}


def coverage(wall_s: float) -> float:
    """Fraction of `wall_s` decomposed by top-level (depth-0) phases."""
    if wall_s <= 0:
        return 0.0
    with _acc_lock:
        top = sum(v["wall_s"] for v in _phases.values() if v["depth"] == 0)
    return min(1.0, top / wall_s)


# ---------------------------------------------------------------------------
# Abstract signatures (the retrace culprit vocabulary)
# ---------------------------------------------------------------------------


def _leaf_abstract(x) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            name = getattr(dtype, "name", None) or str(dtype)
            return "%s[%s]" % (name, ",".join(str(int(d)) for d in shape))
        # ytklint: allow(broad-except) reason=extended dtypes/symbolic dims fall back to repr below
        except Exception:
            pass
    return type(x).__name__


def abstract_signature(*trees) -> List[List[str]]:
    """Flatten pytrees into `[path, "f32[4,8]"]` pairs — a hashable-ish,
    JSON-friendly abstract signature of a jit call's arguments. Capped at
    _SIG_MAX_LEAVES leaves (a trailing marker records the overflow)."""
    try:
        from jax.tree_util import keystr, tree_flatten_with_path
    # ytklint: allow(broad-except-swallow) reason=jax absent or too old: signatures degrade to positional type names
    except Exception:
        return [["args[%d]" % i, _leaf_abstract(t)] for i, t in enumerate(trees)]
    out: List[List[str]] = []
    for i, tree in enumerate(trees):
        leaves, _ = tree_flatten_with_path(tree)
        for path, leaf in leaves:
            if len(out) >= _SIG_MAX_LEAVES:
                return out + [["...", "+more leaves"]]
            out.append(["args[%d]%s" % (i, keystr(path)), _leaf_abstract(leaf)])
    return out


def signature_diff(old, new) -> List[str]:
    """Human-readable lines naming what changed between two signatures
    (`args[0][1]: f32[4,8] -> f32[5,8]`; added/removed leaves included)."""
    if old is None or new is None:
        return []
    o = {p: a for p, a in old}
    n = {p: a for p, a in new}
    lines: List[str] = []
    for p, a in new:
        if p not in o:
            lines.append("%s: added %s" % (p, a))
        elif o[p] != a:
            lines.append("%s: %s -> %s" % (p, o[p], a))
        if len(lines) >= _DIFF_MAX_LINES:
            lines.append("...")
            return lines
    for p, a in old:
        if p not in n:
            lines.append("%s: removed %s" % (p, a))
            if len(lines) >= _DIFF_MAX_LINES:
                lines.append("...")
                return lines
    return lines


# ---------------------------------------------------------------------------
# Compile ledger
# ---------------------------------------------------------------------------


class CompileLedger:
    """Every XLA backend compile, named. `jax.monitoring` fires compile
    durations synchronously on the compiling thread but carries no
    program identity, so instrumented call sites push a label (and a lazy
    signature thunk) onto a thread-local stack via `program()`; the
    listener attributes the compile to the innermost label, computes the
    signature diff against that program's previous compile, and appends a
    bounded ledger entry. Unlabelled compiles land as `<unlabeled>` —
    still counted, still timed, just anonymous."""

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.entries: "collections.deque[dict]" = collections.deque(maxlen=maxlen)
        self._last_sig: Dict[str, Any] = {}
        self._by_program: Dict[str, dict] = {}
        self.seq = 0

    # -- labelling ----------------------------------------------------------

    class _ProgramCtx:
        __slots__ = ("_ledger", "_frame")

        def __init__(self, ledger, frame):
            self._ledger = ledger
            self._frame = frame

        def __enter__(self):
            st = getattr(self._ledger._tls, "labels", None)
            if st is None:
                st = self._ledger._tls.labels = []
            st.append(self._frame)
            return self

        def __exit__(self, *exc):
            st = getattr(self._ledger._tls, "labels", None)
            if st:
                st.pop()
            return False

    def program(self, name: str, sig=None, sig_fn=None):
        """`with LEDGER.program("gbdt.round", sig_fn=lambda: ...):` — any
        backend compile inside the body is attributed to `name`. `sig_fn`
        is only called if a compile actually lands (keep it cheap anyway:
        it runs on the compiling thread). Cached no-op when off."""
        if not _state.on:
            return NOOP_PHASE
        return CompileLedger._ProgramCtx(self, (name, sig, sig_fn))

    def _current_label(self):
        st = getattr(self._tls, "labels", None)
        return st[-1] if st else None

    # -- the monitoring listener entry point --------------------------------

    def on_compile(self, duration_s: float) -> None:
        if not _state.on:
            return
        frame = self._current_label()
        if frame is None:
            name, sig = "<unlabeled>", None
        else:
            name, sig, sig_fn = frame
            if sig is None and sig_fn is not None:
                try:
                    sig = sig_fn()
                # ytklint: allow(broad-except) reason=a signature thunk over donated/deleted args must not kill the compile path
                except Exception:
                    sig = None
        ms = duration_s * 1000.0
        with self._lock:
            self.seq += 1
            prev = self._last_sig.get(name)
            changed = signature_diff(prev, sig) if sig is not None else []
            if sig is not None:
                self._last_sig[name] = sig
            entry = {
                "seq": self.seq,
                "ts": round(time.time(), 3),
                "program": name,
                "ms": round(ms, 3),
            }
            if sig is not None:
                entry["sig"] = sig
            if changed:
                entry["changed"] = changed
            self.entries.append(entry)
            agg = self._by_program.setdefault(name, {"compiles": 0, "ms": 0.0})
            agg["compiles"] += 1
            agg["ms"] += ms
        core.inc("compile.ledger.compiles")
        core.inc("compile.ledger.ms", ms)
        if changed:
            core.event("compile.ledger.retrace", program=name, ms=round(ms, 1),
                       changed=changed)

    # -- queries ------------------------------------------------------------

    def mark(self) -> int:
        """Current sequence number — pair with entries_since() to ask
        "what compiled after this point" (the RetraceSentinel handshake)."""
        with self._lock:
            return self.seq

    def entries_since(self, seq: int, limit: int = 8) -> List[dict]:
        with self._lock:
            out = [dict(e) for e in self.entries if e["seq"] > seq]
        return out[-limit:]

    def snapshot(self, limit: int = 32) -> dict:
        with self._lock:
            tail = [dict(e) for e in list(self.entries)[-limit:]]
            return {
                "compiles": sum(v["compiles"] for v in self._by_program.values()),
                "total_ms": round(
                    sum(v["ms"] for v in self._by_program.values()), 3
                ),
                "by_program": {
                    k: {"compiles": v["compiles"], "ms": round(v["ms"], 3)}
                    for k, v in sorted(self._by_program.items())
                },
                "entries": tail,
            }

    def reset(self) -> None:
        with self._lock:
            self.entries.clear()
            self._last_sig.clear()
            self._by_program.clear()
            self.seq = 0


LEDGER = CompileLedger(maxlen=knobs.get_int("YTK_PROF_LEDGER_N") or 512)

_ledger_listener_installed = False


def _install_ledger_listener() -> None:
    """Route jax.monitoring backend-compile durations into LEDGER
    (idempotent; one enabled() check per event when the plane is off)."""
    global _ledger_listener_installed
    if _ledger_listener_installed:
        return
    try:
        import jax.monitoring as monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if _state.on and event.endswith("backend_compile_duration"):
                LEDGER.on_compile(duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _ledger_listener_installed = True
    except Exception as e:  # noqa: BLE001 — older jax without monitoring
        log.debug("compile ledger unavailable: %s", e)
        _ledger_listener_installed = True  # don't retry every call


# ---------------------------------------------------------------------------
# Memory watermark sampler
# ---------------------------------------------------------------------------


def _device_mem_stats() -> Tuple[Optional[float], Optional[float]]:
    """(bytes_in_use, peak_bytes_in_use) from the first jax device, or
    (None, None) on backends without memory_stats (CPU returns None)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None, None
        return (
            float(stats.get("bytes_in_use", 0)),
            float(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))),
        )
    # ytklint: allow(broad-except) reason=memory_stats is backend-optional; the sampler degrades to host RSS only
    except Exception:
        return None, None


def _host_rss_bytes() -> Optional[float]:
    """Current RSS from /proc (linux); falls back to ru_maxrss (a peak,
    but monotone — still a usable watermark signal)."""
    try:
        # ytklint: allow(unseamed-io) reason=/proc pseudo-file sampler; local kernel read, no durability or retry semantics apply
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    # ytklint: allow(broad-except) reason=/proc is linux-only; resource fallback below
    except Exception:
        pass
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return float(rss) * (1.0 if sys.platform == "darwin" else 1024.0)
    # ytklint: allow(broad-except) reason=no resource module = no host watermark; device side still samples
    except Exception:
        return None


class MemWatermarkSampler:
    """Background thread sampling device bytes-in-use + host RSS into
    bounded (wall_ts, value) rings, attributing running peaks to the
    enclosing profiler phase. Mirrors the heartbeat sampler lifecycle
    (daemon thread, Event stop, joined in stop())."""

    SERIES = ("mem.device_bytes_in_use", "mem.device_peak_bytes",
              "mem.host_rss_bytes")

    def __init__(self):
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._ring_n = 0
        self.rings: Dict[str, "collections.deque"] = {}
        #: phase -> {"device_peak_bytes": x, "host_rss_peak_bytes": y}
        self.phase_peaks: Dict[str, dict] = {}

    def sample_once(self, now: Optional[float] = None) -> None:
        """One tick (also the deterministic unit tests' entry point):
        read stats *outside* the lock, then append + attribute under it."""
        in_use, peak = _device_mem_stats()
        rss = _host_rss_bytes()
        ph = current_phase() or "<none>"
        ts = round(now if now is not None else time.time(), 3)
        with self._lock:
            if self._ring_n <= 0:
                return
            for name, val in (
                ("mem.device_bytes_in_use", in_use),
                ("mem.device_peak_bytes", peak),
                ("mem.host_rss_bytes", rss),
            ):
                if val is None:
                    continue
                ring = self.rings.get(name)
                if ring is None:
                    ring = self.rings[name] = collections.deque(
                        maxlen=self._ring_n
                    )
                ring.append((ts, val))
            pk = self.phase_peaks.setdefault(ph, {})
            if peak is not None or in_use is not None:
                dv = peak if peak is not None else in_use
                if dv > pk.get("device_peak_bytes", -1.0):
                    pk["device_peak_bytes"] = dv
            if rss is not None and rss > pk.get("host_rss_peak_bytes", -1.0):
                pk["host_rss_peak_bytes"] = rss
        if in_use is not None:
            core.gauge("mem.sampled.device_bytes_in_use", in_use)
        if rss is not None:
            core.gauge("mem.sampled.host_rss_bytes", rss)

    @thread_guard
    def _run(self, stop: threading.Event, interval: float) -> None:
        while not stop.is_set():
            self.sample_once()
            stop.wait(interval)

    def start(self, interval: Optional[float] = None,
              ring_n: Optional[int] = None) -> bool:
        if interval is None:
            interval = _state.mem_interval
        if ring_n is None:
            ring_n = knobs.get_int("YTK_OBS_HISTORY_N") or 256
        if interval <= 0 or ring_n <= 0:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            if self._ring_n != ring_n:
                self.rings = {}
                self._ring_n = int(ring_n)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._run,
                args=(self._stop, float(interval)),
                name="ytk-prof-mem",
                daemon=True,
            )
            self._thread.start()
        return True

    def stop(self) -> None:
        with self._lock:
            t, ev = self._thread, self._stop
            self._thread = None
            self._stop = None
        if ev is not None:
            ev.set()
        if t is not None:
            t.join(timeout=2.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ring_n": self._ring_n,
                "series": {
                    name: [[t, v] for t, v in ring]
                    for name, ring in sorted(self.rings.items())
                },
                "phase_peaks": {k: dict(v) for k, v in self.phase_peaks.items()},
            }

    def reset(self, ring_n: Optional[int] = None) -> None:
        with self._lock:
            self.rings = {}
            self.phase_peaks = {}
            if ring_n is not None:
                self._ring_n = int(ring_n)


MEM = MemWatermarkSampler()


# ---------------------------------------------------------------------------
# Trace-capture parser (Chrome-trace JSON written by jax.profiler.trace)
# ---------------------------------------------------------------------------


def _load_trace_doc(path: str) -> Optional[dict]:
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as fh:
                return json.load(fh)
        from ..io.fs import LocalFileSystem  # lazy: fs pulls the retry seam, which imports obs

        with LocalFileSystem().open(path) as fh:
            return json.load(fh)
    except Exception as e:  # partial/corrupt captures are skipped, not fatal
        log.debug("trace parse failed for %s: %s", path, e)
        return None


#: obs span names are lowercase dotted identifiers ("gbdt.train",
#: "serve.score"); anything else on a python thread is interpreter or
#: jax-runtime noise
_ANN_NAME = re.compile(r"^[a-z][a-z0-9_.\-]*$")


def parse_trace_json(path: str) -> Optional[dict]:
    """Bucket one captured Chrome trace into per-annotation device time
    and a kernel aggregate.

    Layout facts (verified against jax 0.4.x CPU + TPU captures):
      * thread_name/process_name metadata arrive as `ph:"M"` events;
      * python-side frames are `$`-prefixed; `TraceAnnotation` spans are
        the un-prefixed X events on python threads;
      * device work is X events carrying `args.hlo_op` (CPU runtime
        thread) or living under a `/device:` process (TPU).

    Returns {"annotations": {name: ms}, "span_device_ms": {name: ms},
    "kernels": {name: {"ms", "count"}}} or None if unreadable."""
    doc = _load_trace_doc(path)
    if doc is None:
        return None
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    thread_names: Dict[Tuple[int, int], str] = {}
    proc_names: Dict[int, str] = {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        if ev.get("name") == "thread_name":
            thread_names[(ev.get("pid"), ev.get("tid"))] = (
                ev.get("args", {}).get("name", "")
            )
        elif ev.get("name") == "process_name":
            proc_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
    ann_events: List[dict] = []
    kernel_events: List[dict] = []
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        name = ev.get("name", "")
        args = ev.get("args") or {}
        pname = proc_names.get(ev.get("pid"), "")
        if "hlo_op" in args or "/device:" in pname or "Device" in pname:
            kernel_events.append(ev)
            continue
        tname = thread_names.get((ev.get("pid"), ev.get("tid")), "")
        if not _ANN_NAME.match(name):
            # python interpreter frames ($-prefixed), C++ runtime scopes
            # (Foo::Bar), jax-internal python TraceMes (jit(f),
            # ExecuteReplicated.__call__) — neither a user annotation nor
            # device work; obs span names are lowercase dotted identifiers
            continue
        if "python" in tname.lower() or not thread_names:
            ann_events.append(ev)
    annotations: Dict[str, float] = {}
    for ev in ann_events:
        annotations[ev["name"]] = (
            annotations.get(ev["name"], 0.0) + ev["dur"] / 1000.0
        )
    # innermost-containing-annotation attribution: smallest annotation
    # interval whose [ts, ts+dur) contains the kernel midpoint
    intervals = sorted(
        ((ev["ts"], ev["ts"] + ev["dur"], ev["name"]) for ev in ann_events),
        key=lambda iv: iv[1] - iv[0],
    )
    span_device: Dict[str, float] = {}
    kernels: Dict[str, dict] = {}
    for ev in kernel_events:
        mid = ev["ts"] + ev["dur"] / 2.0
        ms = ev["dur"] / 1000.0
        kname = ev.get("name", "?")
        k = kernels.setdefault(kname, {"ms": 0.0, "count": 0})
        k["ms"] += ms
        k["count"] += 1
        for lo, hi, name in intervals:
            if lo <= mid < hi:
                span_device[name] = span_device.get(name, 0.0) + ms
                break
    return {
        "annotations": {k: round(v, 3) for k, v in annotations.items()},
        "span_device_ms": {k: round(v, 3) for k, v in span_device.items()},
        "kernels": {
            k: {"ms": round(v["ms"], 3), "count": v["count"]}
            for k, v in kernels.items()
        },
    }


def parse_capture_dir(root: str) -> Optional[dict]:
    """Find + parse the newest `*.trace.json(.gz)` under a capture dir
    (jax nests them below plugins/profile/<run>/)."""
    newest, newest_m = None, -1.0
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            if fn.endswith(".trace.json.gz") or fn.endswith(".trace.json"):
                p = os.path.join(dirpath, fn)
                m = os.path.getmtime(p)
                if m > newest_m:
                    newest, newest_m = p, m
    return parse_trace_json(newest) if newest else None


def parse_captures(topk: Optional[int] = None) -> dict:
    """Merge every completed phase capture into span device-time buckets
    and one top-k kernel table."""
    if topk is None:
        topk = _state.topk
    with _acc_lock:
        captures = list(_captures)
    span_device: Dict[str, float] = {}
    kernels: Dict[str, dict] = {}
    parsed = 0
    for _phase_name, cap_dir in captures:
        res = parse_capture_dir(cap_dir)
        if res is None:
            continue
        parsed += 1
        for k, v in res["span_device_ms"].items():
            span_device[k] = round(span_device.get(k, 0.0) + v, 3)
        for k, v in res["kernels"].items():
            agg = kernels.setdefault(k, {"ms": 0.0, "count": 0})
            agg["ms"] = round(agg["ms"] + v["ms"], 3)
            agg["count"] += v["count"]
    top = sorted(kernels.items(), key=lambda kv: -kv[1]["ms"])[: max(0, topk)]
    total_ms = sum(v["ms"] for v in kernels.values())
    return {
        "captures": len(captures),
        "parsed": parsed,
        "span_device_ms": span_device,
        "device_total_ms": round(total_ms, 3),
        "top_kernels": [
            {
                "name": k,
                "ms": v["ms"],
                "count": v["count"],
                "share": round(v["ms"] / total_ms, 4) if total_ms else 0.0,
            }
            for k, v in top
        ],
    }


# ---------------------------------------------------------------------------
# Report / flight-dump surface
# ---------------------------------------------------------------------------


def report(wall_s: Optional[float] = None, topk: Optional[int] = None) -> dict:
    """The `ytkprof` schema: everything the plane knows, JSON-ready."""
    rep = {
        "schema": SCHEMA,
        "schema_version": 1,
        "enabled": _state.on,
        "phases": phases_snapshot(),
        "compile": LEDGER.snapshot(),
        "mem": MEM.snapshot(),
        "kernels": parse_captures(topk=topk),
    }
    if wall_s is not None:
        rep["wall_s"] = round(wall_s, 4)
        rep["phase_coverage"] = round(coverage(wall_s), 4)
    return rep


def format_report(rep: dict) -> str:
    """Render a ytkprof report for terminals (the profile_* CLIs and
    prof_drill share this — one timing presentation, one plane)."""
    lines: List[str] = []
    phases = rep.get("phases") or {}
    if phases:
        lines.append("phase                          wall_s   calls")
        for name, p in phases.items():
            pad = "  " * p.get("depth", 0)
            lines.append(
                "%-30s %7.3f  %6d" % (pad + name, p["wall_s"], p["count"])
            )
    if rep.get("wall_s") is not None:
        lines.append(
            "wall %.3fs  coverage %.1f%%"
            % (rep["wall_s"], 100.0 * rep.get("phase_coverage", 0.0))
        )
    comp = rep.get("compile") or {}
    if comp.get("compiles"):
        lines.append(
            "compiles %d  total %.1f ms"
            % (comp["compiles"], comp.get("total_ms", 0.0))
        )
        for name, v in (comp.get("by_program") or {}).items():
            lines.append(
                "  %-28s %3d compile(s)  %8.1f ms"
                % (name, v["compiles"], v["ms"])
            )
    kern = rep.get("kernels") or {}
    if kern.get("top_kernels"):
        lines.append(
            "top kernels (device total %.1f ms over %d capture(s)):"
            % (kern.get("device_total_ms", 0.0), kern.get("parsed", 0))
        )
        for k in kern["top_kernels"]:
            lines.append(
                "  %-40s %8.2f ms  x%-5d %5.1f%%"
                % (k["name"][:40], k["ms"], k["count"], 100.0 * k["share"])
            )
    peaks = (rep.get("mem") or {}).get("phase_peaks") or {}
    if peaks:
        lines.append("memory peaks by phase:")
        for ph, v in peaks.items():
            bits = []
            if "device_peak_bytes" in v:
                bits.append("device %.1f MiB" % (v["device_peak_bytes"] / 2**20))
            if "host_rss_peak_bytes" in v:
                bits.append("rss %.1f MiB" % (v["host_rss_peak_bytes"] / 2**20))
            lines.append("  %-28s %s" % (ph, "  ".join(bits)))
    return "\n".join(lines)


def flight_block() -> Optional[dict]:
    """Compact prof block for flight dumps (phase wall table, ledger
    tail, phase-attributed memory peaks) — None when the plane is off so
    dumps stay byte-identical for non-profiled runs."""
    if not _state.on:
        return None
    mem = MEM.snapshot()
    return {
        "phases": phases_snapshot(),
        "compile": LEDGER.snapshot(limit=16),
        "mem_phase_peaks": mem.get("phase_peaks", {}),
    }


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


def _activate() -> None:
    """Arm everything the plane rides on: obs collection (spans), jax
    TraceAnnotations (so captures carry span names), the health compile
    counters, the ledger listener, and the watermark sampler."""
    from . import health

    core.configure(enabled=True, jax_annotations=True)
    health.install_trace_counters()
    _install_ledger_listener()
    MEM.start()


def configure_profiler(
    on: Optional[bool] = None,
    capture_dir=_UNSET,
    topk: Optional[int] = None,
    mem_interval: Optional[float] = None,
) -> None:
    """Runtime configuration (the CLI's --profile lands here). Setting a
    capture dir implies on=True unless `on=False` is passed explicitly."""
    if capture_dir is not _UNSET:
        _state.capture_dir = capture_dir or None
        if capture_dir and on is None:
            on = True
    if topk is not None:
        _state.topk = int(topk)
    if mem_interval is not None:
        _state.mem_interval = float(mem_interval)
    if on is not None:
        was = _state.on
        _state.on = bool(on)
        if _state.on and not was:
            _activate()
        elif was and not _state.on:
            MEM.stop()


def reset_profiler() -> None:
    """Clear accumulated state (tests; the sampler thread keeps running
    if armed — stop it via configure_profiler(on=False))."""
    with _acc_lock:
        _phases.clear()
        del _captures[:]
        del _phase_stack[:]
    LEDGER.reset()
    MEM.reset()


def _configure_from_env() -> None:
    raw = knobs.get_raw("YTK_PROF")
    topk = knobs.get_int("YTK_PROF_TOPK")
    mem_s = knobs.get_float("YTK_PROF_MEM_S")
    if topk is not None:
        _state.topk = topk
    if mem_s is not None:
        _state.mem_interval = mem_s
    if raw is None or raw == "" or raw == "0":
        return
    if raw == "1":
        configure_profiler(on=True)
    else:
        configure_profiler(on=True, capture_dir=raw)


_configure_from_env()
