"""Flight recorder: a bounded ring of the last N obs events plus a crash
dump, so an abnormal exit leaves a self-contained postmortem instead of a
bare stack trace.

`install()` puts a `collections.deque(maxlen=N)` ring on the registry
(every span/event lands in it as it is recorded), then hooks the three
abnormal-exit paths:

  sys.excepthook   uncaught exception -> dump, then chain to the previous
                   hook (the traceback still prints)
  SIGTERM          dump, restore the previous handler, re-raise the signal
                   (exit status is still the signal's)
  SIGINT           dump, then hand back to the previous disposition — a
                   Ctrl-C postmortem gets the same flight dump a SIGTERM
                   does (the python default still raises KeyboardInterrupt
                   afterwards, so interactive semantics are unchanged)
  atexit           dump only when an abnormal condition was flagged earlier
                   (a clean exit writes nothing)

`dump()` writes `flight_<ts>_<pid>.json` to `YTK_FLIGHT_DIR` (default
`flight_dumps/`, created on demand — gitignored so a crash dump can
never end up committed).
The file is a valid Chrome-trace/Perfetto document — `traceEvents` holds
the ring as complete "X"/"i" events plus counter samples, so
https://ui.perfetto.dev opens it directly — with one extra `flight` block
(reason, raw ring, registry snapshot, config fingerprint, jax/device and
process info) that `scripts/obs_report.py` renders as a run-health report.

Knobs:
  YTK_FLIGHT_N=4096              ring capacity (events)
  YTK_FLIGHT_DIR=flight_dumps    dump directory (gitignored default)
  YTK_FLIGHT=0        disable auto_install() (trainers call it; explicit
                      install() still works)

Disabled-path contract: with obs collection off, spans/events never reach
the registry, so the ring stays empty and `auto_install()` returns None
after one enabled() check — the same attribute-load-only budget as the
rest of the obs surface (pinned in tests/test_health.py).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional

from . import core
from ..config import knobs

log = logging.getLogger("ytklearn_tpu.obs")

FLIGHT_SCHEMA_VERSION = 1
DEFAULT_RING_N = 4096


class _RecState:
    __slots__ = (
        "installed",
        "dir",
        "prev_excepthook",
        "prev_sigterm",
        "prev_sigint",
        "abnormal",
        "last_dump_path",
        "config_fingerprint",
        "dump_seq",
    )

    def __init__(self):
        self.installed = False
        self.dir: Optional[str] = None
        self.prev_excepthook = None
        self.prev_sigterm = None
        self.prev_sigint = None
        self.abnormal = False
        self.last_dump_path: Optional[str] = None
        self.config_fingerprint: Optional[dict] = None
        self.dump_seq = 0


_state = _RecState()
_install_lock = threading.Lock()


def installed() -> bool:
    return _state.installed


def last_dump_path() -> Optional[str]:
    return _state.last_dump_path


def thread_guard(fn):
    """Decorator for thread entry points: a worker must not die silently.

    An exception escaping a ``Thread(target=...)`` entry evaporates into
    threading's default excepthook — no obs event, nothing in the flight
    ring, and the first symptom is a subsystem that quietly stopped (the
    r14 respawn bug's failure mode). The guard logs the exception, drops
    a ``thread.died`` event into the ring (so a later flight dump names
    the dead worker), and re-raises — semantics are otherwise unchanged.
    ytklint's silent-thread-death rule recognizes this decorator.
    """
    import functools

    @functools.wraps(fn)
    def _guarded(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            log.exception(
                "thread entry %s died: %s: %s",
                getattr(fn, "__qualname__", fn), type(e).__name__, e,
            )
            core.event(
                "thread.died",
                entry=getattr(fn, "__qualname__", str(fn)),
                error=type(e).__name__,
            )
            raise
    return _guarded


def set_config_fingerprint(obj) -> None:
    """Record a compact fingerprint of the run config for the dump —
    a stable hash plus a short head of the repr (enough to tell two runs
    apart without serializing a whole params tree)."""
    import hashlib

    try:
        text = repr(obj)
    # ytklint: allow(broad-except) reason=a broken user repr must not kill training; the fingerprint degrades to the type name
    except Exception:
        text = f"<unrepresentable {type(obj).__name__}>"
    _state.config_fingerprint = {
        "type": type(obj).__name__,
        "sha1": hashlib.sha1(text.encode("utf-8", "replace")).hexdigest(),
        "head": text[:400],
    }


def _flight_dir() -> str:
    return _state.dir or knobs.get_str("YTK_FLIGHT_DIR") or os.getcwd()


def _runtime_info() -> dict:
    import platform

    info = {
        "pid": os.getpid(),
        "argv": sys.argv,
        "python": sys.version.split()[0],
        "platform": platform.platform(),
    }
    if core.IDENTITY:
        # fleet postmortems must name the replica, not just a pid
        info["identity"] = dict(core.IDENTITY)
    # jax/device facts are best-effort: the dump must succeed even when the
    # crash IS a broken jax runtime
    try:
        import jax

        info["jax"] = jax.__version__
        info["backend"] = jax.default_backend()
        devs = jax.local_devices()
        info["device_count"] = len(devs)
        info["device_kind"] = devs[0].device_kind if devs else None
    except Exception as e:  # noqa: BLE001
        info["jax_error"] = f"{type(e).__name__}: {e}"[:200]
    return info


def dump(reason: str = "manual", exc: Optional[BaseException] = None) -> str:
    """Write the flight dump now; returns the path. Always writes a fresh
    file (timestamp + pid + sequence keyed), never raises — a failing dump
    logs and returns "" rather than masking the original crash."""
    try:
        return _dump(reason, exc)
    except Exception as e:  # noqa: BLE001 — the recorder must never be the crash
        log.error("flight dump failed: %s: %s", type(e).__name__, e)
        return ""


def _dump(reason: str, exc: Optional[BaseException]) -> str:
    from .export import chrome_trace_events

    # timed acquire, not `with`: the SIGTERM handler runs on the main
    # thread between bytecodes, so the signal can land while THIS thread
    # already holds the (non-reentrant) registry lock inside add_event —
    # a blocking acquire would deadlock a dying process. On timeout, copy
    # without the lock: GIL-atomic enough for a best-effort postmortem.
    locked = core.REGISTRY._lock.acquire(timeout=1.0)
    try:
        ring = list(core.REGISTRY.ring) if core.REGISTRY.ring is not None else []
        counters = dict(core.REGISTRY.counters)
        gauges = dict(core.REGISTRY.gauges)
    finally:
        if locked:
            core.REGISTRY._lock.release()

    # a throwaway registry holding only the ring -> reuse the exporter so
    # the dump is Perfetto-loadable without duplicating the conversion
    ring_reg = core.Registry()
    ring_reg.events = ring
    ring_reg.counters = counters
    trace_events = chrome_trace_events(ring_reg)

    flight = {
        "schema_version": FLIGHT_SCHEMA_VERSION,
        "reason": reason,
        "wall_time": time.time(),
        "wall_t0": core.WALL_T0,
        "ring": ring,
        "ring_capacity": (
            core.REGISTRY.ring.maxlen if core.REGISTRY.ring is not None else 0
        ),
        "snapshot": {"counters": counters, "gauges": gauges},
        "config_fingerprint": _state.config_fingerprint,
        "runtime": _runtime_info(),
    }
    if exc is not None:
        flight["exception"] = f"{type(exc).__name__}: {exc}"[:1000]
    try:
        from . import trace as _trace

        if _trace.enabled():
            # a serving postmortem carries its tail exemplars: the slow /
            # shed / 504'd request traces that were in the ring when the
            # process died (obs/trace.py; empty list when none were kept)
            flight["traces"] = _trace.exemplars()
    # ytklint: allow(broad-except) reason=the flight dump must land even when the trace plane is the broken part
    except Exception:
        pass
    try:
        from . import profiler as _profiler

        prof = _profiler.flight_block()
        if prof is not None:
            # an OOM/crash postmortem names the allocating phase: phase
            # wall table, compile-ledger tail, phase-attributed memory
            # peak watermarks (None — and absent — when ytkprof is off)
            flight["prof"] = prof
    # ytklint: allow(broad-except) reason=the flight dump must land even when the profiling plane is the broken part
    except Exception:
        pass
    try:
        from . import model_metrics as _model_metrics

        mm = _model_metrics.flight_block()
        if mm is not None:
            # a serving postmortem names the tenant: per-model counters,
            # latency percentiles, and burn-sentinel state (None — and
            # absent — outside a serving process)
            flight["model_metrics"] = mm
    # ytklint: allow(broad-except) reason=the flight dump must land even when the per-model plane is the broken part
    except Exception:
        pass

    _state.dump_seq += 1
    ts = time.strftime("%Y%m%d-%H%M%S")
    name = f"flight_{ts}_{os.getpid()}_{_state.dump_seq}.json"
    out_dir = _flight_dir()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    doc = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "ytklearn_tpu.obs.recorder"},
        "flight": flight,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    _state.last_dump_path = path
    log.warning("flight dump (%s) written to %s", reason, path)
    return path


def load_flight(path: str) -> dict:
    """Parse a flight dump back into its `flight` block (+ traceEvents)."""
    with open(path) as f:
        doc = json.load(f)
    out = dict(doc.get("flight") or {})
    out["traceEvents"] = doc.get("traceEvents") or []
    return out


def _excepthook(exc_type, exc, tb):
    _state.abnormal = True
    dump("excepthook", exc)
    prev = _state.prev_excepthook or sys.__excepthook__
    prev(exc_type, exc, tb)


def _sigterm_handler(signum, frame):
    _state.abnormal = True
    dump("sigterm")
    # restore the EXACT previous disposition (SIG_IGN included — a wrapper
    # that ignored SIGTERM must keep ignoring it after our dump), then
    # re-raise so the exit status is still the signal's
    prev = _state.prev_sigterm
    signal.signal(
        signal.SIGTERM, prev if prev is not None else signal.SIG_DFL
    )
    os.kill(os.getpid(), signal.SIGTERM)


def _sigint_handler(signum, frame):
    _state.abnormal = True
    dump("sigint")
    prev = _state.prev_sigint
    if callable(prev):
        # the python default (default_int_handler) raises KeyboardInterrupt
        # from here — exactly the old Ctrl-C semantics, now with a dump
        signal.signal(signal.SIGINT, prev)
        prev(signum, frame)
        return
    signal.signal(
        signal.SIGINT, prev if prev is not None else signal.SIG_DFL
    )
    os.kill(os.getpid(), signal.SIGINT)


def _atexit_handler():
    if _state.abnormal and _state.last_dump_path is None:
        dump("atexit")


def install(ring_n: Optional[int] = None, flight_dir: Optional[str] = None) -> None:
    """Install the ring + abnormal-exit hooks (idempotent)."""
    with _install_lock:
        n = ring_n or knobs.get_int("YTK_FLIGHT_N")
        if flight_dir:
            _state.dir = flight_dir
        with core.REGISTRY._lock:
            if core.REGISTRY.ring is None or core.REGISTRY.ring.maxlen != n:
                core.REGISTRY.ring = deque(core.REGISTRY.events[-n:], maxlen=n)
        if _state.installed:
            return
        _state.prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
        try:
            _state.prev_sigterm = signal.signal(signal.SIGTERM, _sigterm_handler)
            _state.prev_sigint = signal.signal(signal.SIGINT, _sigint_handler)
        except ValueError:
            _state.prev_sigterm = None  # non-main thread: excepthook/atexit only
            _state.prev_sigint = None
        atexit.register(_atexit_handler)
        _state.installed = True


def auto_install() -> None:
    """Trainer entry hook: install when obs is collecting (YTK_FLIGHT=0
    opts out). With obs disabled this is one enabled() check and a return —
    the no-op contract call sites rely on."""
    if not core.enabled():
        return
    if not knobs.get_bool("YTK_FLIGHT"):
        return
    install()


def uninstall() -> None:
    """Remove hooks + ring (test isolation; atexit stays registered but
    becomes a no-op once the abnormal flag is cleared)."""
    with _install_lock:
        if _state.installed:
            sys.excepthook = _state.prev_excepthook or sys.__excepthook__
            if _state.prev_sigterm is not None:
                try:
                    signal.signal(signal.SIGTERM, _state.prev_sigterm)
                except ValueError:
                    pass
            if _state.prev_sigint is not None:
                try:
                    signal.signal(signal.SIGINT, _state.prev_sigint)
                except ValueError:
                    pass
            _state.installed = False
        with core.REGISTRY._lock:
            core.REGISTRY.ring = None
    # the crash-path flags are LOCKLESS state by design: signal handlers
    # and the excepthook write them and a handler must never take a lock
    # (the interrupted thread may hold it — instant deadlock). Resetting
    # them under _install_lock above would make them look lock-guarded
    # (ytklint unguarded-shared-write) when the lock never actually
    # protected them; single-reference stores are atomic under the GIL.
    _state.abnormal = False
    _state.last_dump_path = None
    _state.config_fingerprint = None
