"""Per-model accounting plane (mesh-obs): scoped metric families.

Every serving signal used to be process-global — one latency ring, one
SLO-burn sentinel, global shed/504 counters — so an abusive tenant and
its victims were indistinguishable in `/metrics`, traces, and flight
dumps. This module gives each *registered* model name its own family:

  counters   `serve.model.<name>.{requests,request_rows,shed,
             deadline_expired,cache.hit,cache.miss,not_found}` — plain
             registry counters, so they ride the existing history-ring
             sampling, the fleet front's `serve.`-prefix scrape filter,
             and flight-dump snapshots for free, and are the same
             cached no-op as every other counter under YTK_OBS=0
  latency    a bounded per-model (wall_ts, ms) ring — the SAME sample
             shape as the process ring, so the fleet front's windowed
             ring union (serve/fleet/front.py) merges it unchanged
  sentinel   a per-model SLOBurnSentinel whose `health.slo_burn` event
             names the model (site `serve.model.<name>`); SLO resolved
             per model: YTK_SERVE_SLO_MODELS="name:ms,..." override,
             else the app-wide --slo-ms default

Cardinality is bounded BY CONSTRUCTION (the Prometheus label-flood
lesson): only `register()` — called for names the registry actually
loaded — can create a named family, and at most YTK_MODEL_METRICS_MAX
of them; everything else (404 name floods, names past the budget)
lands in the shared `__overflow__` bucket. The accounting identity the
mesh drill checks (exact conservation): every per-model counter is
incremented at the SAME call site as its global twin, so for each
counter pair, sum over families == the global value, always.

`ServeApp` owns one instance and publishes it as the process default so
flight dumps (obs/recorder.py) attach the per-model block and
postmortems name the tenant.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from . import core
from .health import SLOBurnSentinel
from ..config import knobs

#: the shared bucket for every name past the family budget (and for 404
#: floods of never-registered names) — bounded cardinality's escape hatch
OVERFLOW = "__overflow__"

#: counter namespace; the fleet front's scrape filter keeps `serve.*`
COUNTER_PREFIX = "serve.model."

#: per-model latency ring capacity (the process-global ring is 4096; a
#: model's share of traffic is smaller, and the fleet union windows on
#: timestamps anyway, so stale depth buys nothing)
RING_N = 1024


def parse_slo_models(spec: Optional[str]) -> Dict[str, float]:
    """Parse YTK_SERVE_SLO_MODELS ("name:ms,name2:ms") into {name: ms}.

    Malformed fragments raise ValueError: a typo'd SLO override must fail
    serve startup loudly, not silently arm the wrong budget."""
    out: Dict[str, float] = {}
    if not spec:
        return out
    for frag in spec.split(","):
        frag = frag.strip()
        if not frag:
            continue
        name, sep, ms = frag.rpartition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"YTK_SERVE_SLO_MODELS fragment {frag!r}: expected 'name:ms'"
            )
        try:
            val = float(ms)
        except ValueError:
            raise ValueError(
                f"YTK_SERVE_SLO_MODELS fragment {frag!r}: {ms!r} is not a number"
            ) from None
        if not val > 0:
            raise ValueError(
                f"YTK_SERVE_SLO_MODELS fragment {frag!r}: SLO must be > 0 ms"
            )
        out[name] = val
    return out


class _ModelLatencyRing:
    """Bounded (wall_ts, ms) ring, multi-writer safe. Pairs, not bare
    floats: the fleet front WINDOWS the union on sample timestamps so an
    idle model's stale samples can't dilute the fleet percentile."""

    __slots__ = ("_ring", "_lock")

    def __init__(self, maxlen: int = RING_N):
        self._ring = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        with self._lock:
            self._ring.append((time.time(), float(ms)))

    def raw(self) -> list:
        """[[wall_ts, ms], ...] — the fleet ring-union input shape."""
        with self._lock:
            return [[round(t, 3), round(v, 3)] for t, v in self._ring]

    def values(self) -> List[float]:
        with self._lock:
            return [v for _, v in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class ModelFamily:
    """One model's scoped instruments: latency ring + burn sentinel.
    Counters live in the process obs registry under its name prefix."""

    __slots__ = ("scope", "slo_ms", "ring", "sentinel")

    def __init__(
        self,
        scope: str,
        slo_ms: float,
        burn_window: Optional[int] = None,
        burn_budget: Optional[float] = None,
    ):
        self.scope = scope
        self.slo_ms = float(slo_ms or 0.0)
        self.ring = _ModelLatencyRing()
        # the sentinel's site carries the model name, so both the
        # `health.slo_burn.serve.model.<name>` counter and the fired
        # event name the tenant
        self.sentinel = (
            SLOBurnSentinel(
                COUNTER_PREFIX + scope, self.slo_ms,
                window=burn_window, budget=burn_budget,
            )
            if self.slo_ms > 0 else None
        )


class ModelMetrics:
    """The bounded per-model family map. Hot-path reads (`family()`) are
    a plain dict get — families are only ever *added*, under `_lock`, and
    published by dict assignment (atomic under the GIL); the overflow
    family exists from construction so reads never miss."""

    def __init__(
        self,
        slo_ms: Optional[float] = None,
        max_models: Optional[int] = None,
        slo_models: Optional[Dict[str, float]] = None,
        burn_window: Optional[int] = None,
        burn_budget: Optional[float] = None,
    ):
        self.max_models = max(1, int(
            max_models if max_models is not None
            else knobs.get_int("YTK_MODEL_METRICS_MAX")
        ))
        self.slo_ms = float(slo_ms or 0.0)
        self.slo_models = (
            dict(slo_models) if slo_models is not None
            else parse_slo_models(knobs.get_str("YTK_SERVE_SLO_MODELS"))
        )
        self._burn_window = burn_window
        self._burn_budget = burn_budget
        self._lock = threading.Lock()
        self._collapsed: set = set()
        # overflow keeps the GLOBAL default SLO: models collapsed past
        # the budget still get burn protection, just not by name
        self._families: Dict[str, ModelFamily] = {
            OVERFLOW: ModelFamily(
                OVERFLOW, self.slo_ms, burn_window, burn_budget
            ),
        }

    # -- family admission -------------------------------------------------

    def register(self, name: str) -> str:
        """Admit a registry-loaded model name as a scoped family
        (idempotent). Returns the scope it landed on: the name itself, or
        OVERFLOW once the family budget is spent. Only this method
        creates named families — a request for an unknown name can never
        grow the map (the 404-flood bound)."""
        if not name or not isinstance(name, str) or name == OVERFLOW:
            return OVERFLOW
        if name in self._families:
            return name
        with self._lock:
            if name in self._families:
                return name
            if len(self._families) - 1 >= self.max_models:  # -1: overflow
                if name not in self._collapsed:
                    self._collapsed.add(name)
                    core.inc(
                        COUNTER_PREFIX + OVERFLOW + ".names_collapsed"
                    )
                return OVERFLOW
            self._families[name] = ModelFamily(
                name, self.slo_models.get(name, self.slo_ms),
                self._burn_window, self._burn_budget,
            )
            return name

    def scope_name(self, name: Optional[str]) -> str:
        """The family scope a name's signals land on (no creation)."""
        if name and isinstance(name, str) and name in self._families:
            return name
        return OVERFLOW

    def family(self, name: Optional[str]) -> ModelFamily:
        fam = (
            self._families.get(name)
            if name and isinstance(name, str) else None
        )
        return fam if fam is not None else self._families[OVERFLOW]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    # -- recording (the serve hot path) -----------------------------------

    def record_request(self, name: Optional[str], rows: int,
                       ms: float) -> None:
        """One completed request (cache-hit or scored): mirrors the
        global `serve.requests`/`serve.request_rows` increments, feeds
        the model's latency ring and burn sentinel. Called at the SAME
        sites as the global counters — the conservation identity."""
        fam = self.family(name)
        pre = COUNTER_PREFIX + fam.scope
        core.inc(pre + ".requests")
        core.inc(pre + ".request_rows", float(rows))
        fam.ring.record(ms)
        if fam.sentinel is not None:
            fam.sentinel.observe(ms, model=fam.scope)

    def record_violation(self, name: Optional[str], status: int) -> None:
        """A shed 429 / deadline 504 burned the model's SLO budget
        without being scored. Counters for these land at the batcher's
        own shed/expiry sites; this only feeds the sentinel."""
        fam = self.family(name)
        if fam.sentinel is not None:
            fam.sentinel.observe(
                violated=True, model=fam.scope, status=int(status)
            )

    def record_not_found(self, name: Optional[str]) -> None:
        """404 on an unknown model name — lands in __overflow__ (only
        `register()` creates families), so a name-flood moves one
        counter, not the family map."""
        fam = self.family(name)
        core.inc(COUNTER_PREFIX + fam.scope + ".not_found")

    # -- export -----------------------------------------------------------

    def snapshot(self, raw: bool = False,
                 counters: Optional[dict] = None) -> dict:
        """The `/metrics?models=1` block (and the flight-dump block):
        per-family counters, latency percentiles (+ the raw ring when
        `raw` — the fleet union input), and sentinel state. `counters`
        accepts a pre-taken registry snapshot so one payload takes the
        registry lock once."""
        if counters is None:
            counters = (
                core.snapshot()["counters"] if core.enabled() else {}
            )
        # one percentile implementation serves the process ring, the
        # fleet union, and the per-model rings — lazy import: obs must
        # not import serve at module load
        from ..serve.fleet.front import latency_percentiles

        with self._lock:
            fams = [self._families[s] for s in sorted(self._families)]
        models = {}
        for fam in fams:
            pre = COUNTER_PREFIX + fam.scope + "."
            latency = latency_percentiles(fam.ring.values())
            if raw:
                latency["raw_ms"] = fam.ring.raw()
            block = {
                "counters": {
                    k[len(pre):]: round(v, 3)
                    for k, v in counters.items() if k.startswith(pre)
                },
                "latency": latency,
            }
            if fam.sentinel is not None:
                block["slo"] = {
                    "slo_ms": fam.sentinel.slo_ms,
                    "window": fam.sentinel.window,
                    "budget": fam.sentinel.budget,
                    "windows_fired": fam.sentinel.windows_fired,
                }
            models[fam.scope] = block
        return {"max_models": self.max_models, "models": models}


# -- process default (flight-dump attachment) ------------------------------

_default: Optional[ModelMetrics] = None


def set_default(mm: Optional[ModelMetrics]) -> None:
    """Publish the serving process's ModelMetrics so flight dumps
    (obs/recorder.py) attach the per-model block. Last writer wins —
    one ServeApp per process is the deployment shape."""
    global _default
    _default = mm


def get_default() -> Optional[ModelMetrics]:
    return _default


def flight_block() -> Optional[dict]:
    """The per-model block a flight dump carries (None when no serving
    app published a default — training processes dump without it)."""
    mm = _default
    if mm is None:
        return None
    return mm.snapshot()
