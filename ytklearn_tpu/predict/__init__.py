"""Predictor side-stack (reference: predictor/ package, 2,907 LoC).

`create_predictor(model_name, config)` mirrors
predictor/OnlinePredictorFactory.java:32-80; `batch_predict_from_files`
mirrors the offline CLI path (Predicts.java:36-54).
"""

from __future__ import annotations

from .base import OnlinePredictor, batch_predict_from_files, parse_feature_kvs
from .continuous import (
    ContinuousPredictor,
    FFMPredictor,
    FMPredictor,
    LinearPredictor,
    MulticlassLinearPredictor,
)
from .trees import GBDTPredictor, GBSTPredictor

__all__ = [
    "OnlinePredictor",
    "ContinuousPredictor",
    "LinearPredictor",
    "MulticlassLinearPredictor",
    "FMPredictor",
    "FFMPredictor",
    "GBDTPredictor",
    "GBSTPredictor",
    "create_predictor",
    "batch_predict_from_files",
    "parse_feature_kvs",
]


def create_predictor(model_name: str, config, fs=None) -> OnlinePredictor:
    """name -> predictor (reference: OnlinePredictorFactory.java:32-80).
    `config` is a HOCON path or an already-parsed config dict."""
    name = model_name.lower()
    if name == "linear":
        return LinearPredictor(config, fs)
    if name == "multiclass_linear":
        return MulticlassLinearPredictor(config, fs)
    if name == "fm":
        return FMPredictor(config, fs)
    if name == "ffm":
        return FFMPredictor(config, fs)
    if name == "gbdt":
        return GBDTPredictor(config, fs)
    if name in ("gbmlr", "gbsdt", "gbhmlr", "gbhsdt"):
        return GBSTPredictor(name, config, fs)
    raise ValueError(f"unknown model name {model_name!r}")
