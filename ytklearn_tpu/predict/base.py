"""Online/offline predictor side-stack — no mesh, no collectives.

Rebuild of reference predictor/OnlinePredictor.java (abstract API :120-182,
ResultSaveMode/PredictType enums :51-90, batchPredictFromFiles :174) as a
standalone host library: a trained model's text files + the training config
are enough to serve `score/predict/loss` on feature dicts.

The TPU stays out of the hot path by design (the reference predictor is
likewise mp4j-free): per-sample scoring is numpy; only the activation
(loss.predict) may touch jax.
"""

from __future__ import annotations

import logging
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..config import hocon
from ..eval import EvalSet
from ..io.fs import FileSystem, LocalFileSystem, create_filesystem
from ..io.reader import load_transform_hook
from ..obs import heartbeat as obs_heartbeat, inc as obs_inc, span as obs_span

log = logging.getLogger("ytklearn_tpu.predict")

SAVE_MODES = ("predict_result_only", "label_and_predict", "predict_as_feature")

#: losses whose predict() is the identity (LossFunction.predict default or
#: the multiclass-margin identity override) — the activation fast path
#: below must list them explicitly, because a wrong identity assumption
#: would silently serve raw scores for e.g. sigmoid
_IDENTITY_ACTIVATIONS = {
    "l2", "l1", "huber", "mape", "inv_mape", "smape",
    "hinge", "l2_hinge", "smooth_hinge", "exponential",
    "multiclass_hinge", "multiclass_l2_hinge", "multiclass_smooth_hinge",
    "base",
}


def _np_sigmoid(s):
    s = np.asarray(s, np.float64)
    t = np.exp(-np.abs(s))  # stable: never exponentiates a large positive
    return np.where(s >= 0.0, 1.0 / (1.0 + t), t / (1.0 + t))


def _np_softmax(s):
    s = np.asarray(s, np.float64)
    z = s - np.max(s, axis=-1, keepdims=True)
    e = np.exp(z)
    return e / np.sum(e, axis=-1, keepdims=True)


def numpy_activation(loss):
    """Host-numpy mirror of `loss.predict`, or None when only the jnp
    implementation exists (hsoftmax's heap walk).

    The per-sample serving hot path must not dispatch jnp per request: a
    single `loss.predict(score)` call is a device round-trip (~100 ms
    through a remote-chip tunnel — the same lesson batch_predict_from_files
    already encodes for files). Predictors cache this per instance and fall
    back to the jnp path for unknown losses, so results stay correct either
    way; tests/test_predict_hotpath.py pins the no-dispatch contract."""
    name = getattr(loss, "name", "")
    if name in _IDENTITY_ACTIVATIONS:
        return lambda s: s
    if name == "sigmoid":
        return _np_sigmoid
    if name == "poisson":
        from ..losses import _POISSON_MAX_EXP  # the one clamp, both paths

        return lambda s: np.exp(
            np.minimum(np.asarray(s, np.float64), _POISSON_MAX_EXP)
        )
    if name == "softmax":
        return _np_softmax
    return None
#: reference enum-name aliases (ResultSaveMode.PREDICT_AS_FEATURE prints
#: "label_as_feature", OnlinePredictor.java:55)
SAVE_MODE_ALIASES = {"label_as_feature": "predict_as_feature"}
PREDICT_TYPES = ("value", "leafid")


class OnlinePredictor:
    """Config-driven model server (reference: OnlinePredictor.java).

    Subclasses implement _load_model() and score(features, other); features
    is a {name: value} dict, `other` carries the sample-dependent base score
    for GBST/GBDT models when configured.
    """

    supports_leaf = False
    n_outputs = 1

    def __init__(self, config, fs: Optional[FileSystem] = None):
        if isinstance(config, str):
            config = hocon.load(config)
        self.config = config
        scheme = str(config.get("fs_scheme", "local"))
        self.fs = fs or (
            LocalFileSystem() if scheme in ("local", "") else create_filesystem(scheme)
        )

    # -- core API --------------------------------------------------------

    def score(self, features: Dict[str, float], other=None) -> float:
        raise NotImplementedError

    def scores(self, features: Dict[str, float], other=None) -> List[float]:
        return [self.score(features, other)]

    def _activation(self):
        """Cached numpy_activation(self.loss); None -> jnp fallback. Lazy
        (not in __init__) so subclasses that set self.loss late still work;
        the racy first computation is idempotent, so no lock."""
        act = self.__dict__.get("_np_act", False)
        if act is False:
            act = self.__dict__["_np_act"] = numpy_activation(self.loss)
        return act

    def predict(self, features: Dict[str, float], other=None) -> float:
        s = self.score(features, other)
        act = self._activation()
        if act is not None:
            return float(act(s))
        return float(self.loss.predict(s))

    def predicts(self, features: Dict[str, float], other=None) -> List[float]:
        return [self.predict(features, other)]

    def loss_value(self, features: Dict[str, float], label, other=None) -> float:
        return float(self.loss.loss(self.score(features, other), label))

    def predict_leaf(self, features: Dict[str, float]) -> List[int]:
        raise NotImplementedError(f"{type(self).__name__} has no leaf predict")

    # -- batch helpers ----------------------------------------------------

    def batch_scores(self, rows: Sequence[Dict[str, float]], others=None) -> np.ndarray:
        out = np.empty((len(rows), self.n_outputs), np.float64)
        for i, fmap in enumerate(rows):
            o = others[i] if others is not None else None
            out[i] = self.scores(fmap, o)
        return out if self.n_outputs > 1 else out[:, 0]

    def batch_predicts(self, rows, others=None) -> np.ndarray:
        s = self.batch_scores(rows, others)
        act = self._activation()
        if act is not None:
            return np.asarray(act(s))
        return np.asarray(self.loss.predict(s))


def parse_feature_kvs(text: str, delim) -> Dict[str, float]:
    fmap: Dict[str, float] = {}
    for kv in text.split(delim.features_delim):
        if not kv:
            continue
        name, _, val = kv.partition(delim.feature_name_val_delim)
        fmap[name] = float(val)
    return fmap


class _RowError(Exception):
    pass


def batch_predict_from_files(
    predictor: OnlinePredictor,
    model_name: str,
    file_dir: str,
    need_py_transform: bool = False,
    py_transform_script: str = "",
    result_save_mode: str = "predict_result_only",
    result_file_suffix: str = "_predict",
    max_error_tol: int = 0,
    eval_metric_str: str = "",
    predict_type_str: str = "value",
    K: int = -1,
) -> float:
    """Offline batch prediction (reference: ContinuousOnlinePredictor
    .batchPredictFromFiles:178-330 + Predicts.java:36-54). Writes one
    `<path><suffix>` result file per input file; returns the weighted avg
    loss over labeled rows (0.0 when none)."""
    save_mode = result_save_mode.lower()
    save_mode = SAVE_MODE_ALIASES.get(save_mode, save_mode)
    if save_mode not in SAVE_MODES:
        raise ValueError(f"unknown result_save_mode {result_save_mode!r}")
    predict_type = (predict_type_str or "value").lower()
    if predict_type not in PREDICT_TYPES:
        raise ValueError("predict type invalid! value or leafid")
    if predict_type == "leafid" and not predictor.supports_leaf:
        raise ValueError(f"{model_name} does not support predict type: leafid")

    delim = predictor.params.data.delim
    fs = predictor.fs
    hook = load_transform_hook(py_transform_script) if need_py_transform else None

    multiclass = model_name.lower() == "multiclass_linear"
    if multiclass and K <= 0:
        K = predictor.n_outputs
    eval_set = (
        EvalSet([m for m in eval_metric_str.split(",") if m], K=max(K, 2))
        if eval_metric_str
        else None
    )
    is_gbst = model_name.lower() in ("gbmlr", "gbsdt", "gbhmlr", "gbhsdt")
    is_gbdt = model_name.lower() == "gbdt"
    opt_cfg = predictor.config.get("optimization") or {}
    sample_dep = bool(
        predictor.config.get("sample_dependent_base_prediction", False)
        or (isinstance(opt_cfg, dict) and opt_cfg.get("sample_dependent_base_prediction"))
    )

    total_loss, weight_cnt, errors = 0.0, 0.0, 0
    ev_preds: List = []
    ev_labels: List = []
    ev_weights: List[float] = []

    def stage(line: str) -> dict:
        """Per-row parse + model walk (host numpy). The jnp activation/loss
        is NOT applied here — it runs once per file on the whole score
        matrix, because per-row jnp dispatch is a device round-trip (~100 ms
        each through a remote-chip tunnel; the original per-line design took
        minutes for a 1.6k-row file)."""
        try:
            xsplits = line.split(delim.x_delim)
            weight = float(xsplits[0])
            label_text = xsplits[1].strip()
            fmap = parse_feature_kvs(xsplits[2], delim)
        except (IndexError, ValueError) as e:
            raise _RowError(str(e)) from e

        has_label = len(label_text) > 0
        if not has_label and save_mode != "predict_result_only":
            raise _RowError(f"sample has no label: {line}")

        other = None
        if sample_dep and len(xsplits) > 3:
            # per-sample base score column (reference: ContinuousOnlinePredictor
            # GBST branch; GBDTOnlinePredictor.batchPredictFromFiles:361-369
            # reads a y_delim-split Float[] per class group)
            if is_gbst:
                other = float(xsplits[3])
            elif is_gbdt:
                oinfo = [float(v) for v in xsplits[3].split(delim.y_delim)]
                other = oinfo if len(oinfo) > 1 else oinfo[0]

        st: dict = {"xsplits": xsplits, "weight": weight, "labels": None}
        try:
            if predict_type == "leafid":
                st["preds"] = [int(v) for v in predictor.predict_leaf(fmap)]
                return st
            st["raw"] = np.asarray(predictor.scores(fmap, other), np.float64)
            if has_label:
                linfo = [float(v) for v in label_text.split(delim.y_delim)]
                k = len(st["raw"])
                if multiclass or k > 1:
                    if len(linfo) == 1:
                        labels = [0.0] * max(K, k)
                        labels[int(linfo[0])] = 1.0
                    elif len(linfo) == max(K, k):
                        labels = linfo
                    else:
                        raise _RowError(f"label num must be {max(K, k)} or 1: {line}")
                    st["labels"] = labels
                else:
                    st["labels"] = [linfo[0]]
        except _RowError:
            raise
        except Exception as e:
            raise _RowError(str(e)) from e
        return st

    def fmt(st: dict) -> str:
        xsplits, preds = st["xsplits"], st["preds"]
        pred_text = delim.y_delim.join(repr(p) for p in preds)
        if save_mode == "predict_result_only":
            return pred_text
        if save_mode == "label_and_predict":
            return xsplits[1] + delim.x_delim + pred_text
        extra = delim.features_delim.join(
            f"{model_name}_label_{i}{delim.feature_name_val_delim}{p!r}"
            for i, p in enumerate(preds)
        )
        return (
            xsplits[0] + delim.x_delim + xsplits[1] + delim.x_delim
            + xsplits[2] + delim.features_delim + extra
        )

    hb = obs_heartbeat("predict.batch", every_s=30.0)
    for path in sorted(fs.recur_get_paths([file_dir])):
        staged: List[dict] = []
        with fs.open(path) as f:
            raw_lines: Iterable[str] = list(f)
        with obs_span("predict.score_file", file=path):
            for raw in raw_lines:
                raw = raw.rstrip("\n")
                if not raw.strip():
                    continue
                for line in hook(raw.encode()) if hook is not None else [raw]:
                    try:
                        staged.append(stage(line))
                    except _RowError as e:
                        errors += 1
                        if errors > max_error_tol:
                            raise ValueError(
                                f"max error tolerance exceeded ({errors}): {e}"
                            ) from e
        obs_inc("predict.rows", len(staged))
        hb.beat(file=path, rows=len(staged), errors=errors)

        # batched activation: ONE jnp call per file
        vrows = [s for s in staged if "raw" in s]
        if vrows:
            with obs_span("predict.activate", rows=len(vrows)):
                raws = np.stack([s["raw"] for s in vrows])  # (N, k)
                k = raws.shape[1]
                act = np.asarray(
                    predictor.loss.predict(raws[:, 0] if k == 1 else raws)
                )
                act = act.reshape(len(vrows), -1)
            for s, arow in zip(vrows, act):
                s["preds"] = [float(v) for v in arow]

        # batched loss over labeled rows: ONE jnp call per file
        lrows = [s for s in vrows if s["labels"] is not None]
        if lrows:
            raws_l = np.stack([s["raw"] for s in lrows])
            k = raws_l.shape[1]
            labs = np.asarray([s["labels"] for s in lrows], np.float64)
            lv = np.asarray(
                predictor.loss.loss(
                    raws_l[:, 0] if k == 1 else raws_l,
                    labs[:, 0] if k == 1 else labs,
                )
            ).reshape(-1)
            for s, li in zip(lrows, lv):
                total_loss += s["weight"] * float(li)
                weight_cnt += s["weight"]
                ev_weights.append(s["weight"])
                ev_labels.append(s["labels"] if len(s["labels"]) > 1 else s["labels"][0])
                ev_preds.append(s["preds"] if len(s["preds"]) > 1 else s["preds"][0])

        out_path = path + result_file_suffix
        with fs.open(out_path, "w") as f:
            for line in (fmt(s) for s in staged):
                f.write(line + "\n")
        log.info("predicted %s -> %s", path, out_path)

    if eval_set is not None and ev_preds:
        preds = np.asarray(ev_preds)
        labels = np.asarray(ev_labels)
        weights = np.asarray(ev_weights, np.float32)
        for k, v in eval_set.evaluate(preds, labels, weights).items():
            log.info("eval %s: %.6f", k, v)

    obs_inc("predict.error_lines", errors)
    return total_loss / weight_cnt if weight_cnt > 0 else 0.0
