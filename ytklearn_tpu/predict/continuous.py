"""Convex-family predictors: linear, multiclass linear, FM, FFM.

Rebuild of reference predictor/ContinuousOnlinePredictor.java:54 (shared
load: transform-stat replay, feature hashing, bias) +
LinearOnlinePredictor.java:55-165 (name->(w, std) map, Thompson sampling)
+ MulticlassLinearOnlinePredictor / FMOnlinePredictor:110-160 /
FFMOnlinePredictor (score replay mirrored from the trainers' kernels).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config.params import CommonParams
from ..io.feature_hash import FeatureHash
from ..io.fs import FileSystem
from ..io.reader import TransformNode
from ..losses import create_loss
from ..models.ffm import load_field_dict
from ..transform.pipeline import TransformPipeline
from ..transform.sidecar import read_sidecar, verify_sidecar_digest
from .base import OnlinePredictor

PRECISION_MIN = 1e-9  # reference: LinearOnlinePredictor.java:38


class ContinuousPredictor(OnlinePredictor):
    """Shared linear-family behavior (reference:
    ContinuousOnlinePredictor.java:54-145): typed params, loss function,
    transform-stat sidecar replay, murmur feature hashing."""

    def __init__(self, config, fs: Optional[FileSystem] = None):
        super().__init__(config, fs)
        self.params = CommonParams.from_config(self.config)
        p = self.params
        self.loss = create_loss(p.loss.loss_function)
        fh = p.feature.feature_hash
        self.feature_hash = (
            FeatureHash(fh.bucket_size, fh.seed, fh.feature_prefix)
            if fh.need_feature_hash
            else None
        )
        self.transform_nodes: Dict[str, TransformNode] = {}
        if p.feature.transform.switch_on:
            stat_path = p.model.data_path + "_feature_transform_stat"
            nodes, digest = read_sidecar(self.fs, stat_path)
            # the dump stamps the sidecar with a digest of the model text
            # it was written alongside (transform/sidecar.py); a mismatch
            # is the crash-between-writes window — refuse to serve skewed
            # transforms. Legacy digestless sidecars pass untouched.
            verify_sidecar_digest(self.fs, p.model.data_path, digest)
            self.transform_nodes = nodes
        # the one batched transform path (transform/pipeline.py), shared
        # with ingest and the serving ladder — _prep routes through it
        self.pipeline = TransformPipeline(
            bias_name=p.model.bias_feature_name,
            feature_hash=self.feature_hash,
            nodes=self.transform_nodes,
            transform_on=p.feature.transform.switch_on,
        )
        self._load_model()

    # -- shared plumbing --------------------------------------------------

    def _transform(self, name: str, val: float) -> float:
        """reference: ContinuousOnlinePredictor.transform:135-143 — when
        transform is on, features without a stat node map to 0."""
        return self.pipeline.transform_scalar(name, val)

    def _prep(self, features: Dict[str, float]) -> List[Tuple[str, float]]:
        """bias removal + optional hashing + transform replay
        (reference: every predictor's score() prologue), executed by the
        shared vectorized pipeline."""
        return self.pipeline.prep_row(features)

    def _model_lines(self, path: str):
        """Yield delim-split nonempty lines from every model part file."""
        from ..io.fs import is_tmp_path

        d = self.params.model.delim
        for part in sorted(self.fs.recur_get_paths([path])):
            if is_tmp_path(part):
                continue  # in-flight atomic_open temp from a writer
            with self.fs.open(part) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    info = line.split(d)
                    if len(info) >= 2:
                        yield info

    def _load_model(self) -> None:
        raise NotImplementedError


class LinearPredictor(ContinuousPredictor):
    """score = Σ w·x + bias; Thompson sampling from the Laplace precision
    column (reference: LinearOnlinePredictor.java)."""

    def __init__(self, config, fs=None, rng: Optional[np.random.RandomState] = None):
        self.rng = rng or np.random.RandomState()
        super().__init__(config, fs)

    def _load_model(self) -> None:
        p = self.params.model
        if not self.fs.exists(p.data_path):
            raise FileNotFoundError(f"linear model doesn't exist: {p.data_path}")
        self.model_map: Dict[str, Tuple[float, float]] = {}
        for info in self._model_lines(p.data_path):
            name = info[0].strip()
            wei = float(info[1])
            if name == p.bias_feature_name:
                std = math.sqrt(1.0 / 1e30)
            else:
                try:
                    precision = max(float(info[2]), PRECISION_MIN)
                except (IndexError, ValueError):
                    precision = 1e30
                std = math.sqrt(1.0 / precision)
            self.model_map[name] = (wei, std)

    def score(self, features, other=None) -> float:
        p = self.params.model
        s = 0.0
        for name, val in self._prep(features):
            param = self.model_map.get(name)
            if param is not None:
                s += param[0] * val
        if p.need_bias:
            param = self.model_map.get(p.bias_feature_name)
            if param is not None:
                s += param[0]
        return s

    def thompson_sampling_predict(self, features, alpha: float) -> float:
        """Exploration via the Laplace posterior: w ~ N(w, alpha²·std²)
        (reference: LinearOnlinePredictor.thompsonSamplingPredict:141-163)."""
        p = self.params.model
        s = 0.0
        for name, val in self._prep(features):
            param = self.model_map.get(name)
            if param is not None:
                w, std = param
                s += (w + self.rng.randn() * alpha * std) * val
        if p.need_bias:
            param = self.model_map.get(p.bias_feature_name)
            if param is not None:
                s += param[0]
        act = self._activation()
        if act is not None:
            return float(act(s))
        return float(self.loss.predict(s))


class MulticlassLinearPredictor(ContinuousPredictor):
    """K−1 scores + implicit 0 (reference: MulticlassLinearOnlinePredictor;
    model lines `name,w_0,...,w_{K-2}`)."""

    def _load_model(self) -> None:
        p = self.params.model
        self.K = int(self.params.k)
        self.n_outputs = self.K
        if not self.fs.exists(p.data_path):
            raise FileNotFoundError(f"model doesn't exist: {p.data_path}")
        self.model_map: Dict[str, np.ndarray] = {}
        for info in self._model_lines(p.data_path):
            self.model_map[info[0].strip()] = np.asarray(
                [float(v) for v in info[1 : self.K]], np.float64
            )

    def scores(self, features, other=None) -> List[float]:
        p = self.params.model
        s = np.zeros(self.K - 1, np.float64)
        for name, val in self._prep(features):
            w = self.model_map.get(name)
            if w is not None:
                s += w * val
        if p.need_bias:
            w = self.model_map.get(p.bias_feature_name)
            if w is not None:
                s += w
        return list(s) + [0.0]

    def score(self, features, other=None) -> float:
        raise ValueError("multiclass_linear is multi-output; use scores()")

    def predicts(self, features, other=None) -> List[float]:
        s = np.asarray(self.scores(features))
        act = self._activation()
        out = act(s) if act is not None else self.loss.predict(s)
        return [float(v) for v in out]

    def predict(self, features, other=None) -> float:
        raise ValueError("multiclass_linear is multi-output; use predicts()")

    def loss_value(self, features, label, other=None) -> float:
        s = np.asarray(self.scores(features, other))
        return float(self.loss.loss(s, np.asarray(label)))


class FMPredictor(ContinuousPredictor):
    """wx + ½Σ_k[(Σ v x)² − Σ (v x)²]; the bias (when configured) adds its
    weight and latent row with x = 1 (reference: FMOnlinePredictor.java:110-160)."""

    def _load_model(self) -> None:
        p = self.params.model
        k = self.params.k
        self.sok = int(k[1])
        self.need_first_order = int(k[0]) >= 1
        if not self.fs.exists(p.data_path):
            raise FileNotFoundError(f"model doesn't exist: {p.data_path}")
        self.model_map: Dict[str, np.ndarray] = {}
        for info in self._model_lines(p.data_path):
            self.model_map[info[0].strip()] = np.asarray(
                [float(v) for v in info[1 : 2 + self.sok]], np.float64
            )

    def score(self, features, other=None) -> float:
        p = self.params.model
        wx = 0.0
        S = np.zeros(self.sok, np.float64)
        S2 = np.zeros(self.sok, np.float64)
        w = self.model_map.get(p.bias_feature_name)
        if w is not None and p.need_bias:
            wx += w[0]
            v = w[1:]
            S += v
            S2 += v * v
        for name, val in self._prep(features):
            w = self.model_map.get(name)
            if w is None:
                continue
            if self.need_first_order:
                wx += w[0] * val
            v = w[1:] * val
            S += v
            S2 += v * v
        return wx + 0.5 * float(np.sum(S * S - S2))


class FFMPredictor(ContinuousPredictor):
    """Field-aware pairwise terms: Σ_{p<q} v_p[f_q]·v_q[f_p] x_p x_q
    (reference: FFMOnlinePredictor; model lines
    `name,w,v[field0 k..],v[field1 k..],...`)."""

    def _load_model(self) -> None:
        p = self.params.model
        k = self.params.k
        self.sok = int(k[1])
        self.need_first_order = int(k[0]) >= 1
        if not p.field_dict_path:
            raise ValueError("ffm requires model.field_dict_path")
        self.field_map = load_field_dict(self.fs, p.field_dict_path)
        self.n_fields = len(self.field_map)
        if not self.fs.exists(p.data_path):
            raise FileNotFoundError(f"model doesn't exist: {p.data_path}")
        self.model_map: Dict[str, np.ndarray] = {}
        stride = self.n_fields * self.sok
        for info in self._model_lines(p.data_path):
            self.model_map[info[0].strip()] = np.asarray(
                [float(v) for v in info[1 : 2 + stride]], np.float64
            )

    def _field_of(self, name: str) -> int:
        """Field from the feature name prefix before field_delim
        (mirrors DataIngest.to_dataset: unknown field -> feature dropped)."""
        fd = self.params.data.delim.field_delim
        return self.field_map.get(name.split(fd)[0], -1)

    def score(self, features, other=None) -> float:
        p = self.params.model
        wx = 0.0
        rows = []  # (field, val, V (n_fields, k))
        w = self.model_map.get(p.bias_feature_name)
        if w is not None and p.need_bias:
            # bias rides as a (field 0, x=1) entry like the trainer ingest
            # (reader.to_dataset:466); its latent row is zero unless
            # bias_need_latent_factor was on at train time
            wx += w[0]
            if self.sok > 0:
                rows.append((0, 1.0, w[1:].reshape(self.n_fields, self.sok)))
        for name, val in self._prep(features):
            w = self.model_map.get(name)
            if w is None:
                continue
            fld = self._field_of(name)
            if fld < 0:
                continue  # unknown field: dropped entirely, like training
            if self.need_first_order:
                wx += w[0] * val
            if self.sok > 0:
                rows.append((fld, val, w[1:].reshape(self.n_fields, self.sok)))
        s = wx
        for i in range(len(rows)):
            fi, xi, Vi = rows[i]
            for j in range(i + 1, len(rows)):
                fj, xj, Vj = rows[j]
                s += float(np.dot(Vi[fj], Vj[fi])) * xi * xj
        return s
