"""Tree-family predictors: GBDT + the four GBST soft-tree variants.

Rebuild of reference predictor/GBDTOnlinePredictor.java:55-300 (text-tree
parse, score/scores/predictLeaf:258, missing features -> default child) and
predictor/GBMLR|GBSDT|GBHMLR|GBHSDTOnlinePredictor (per-tree mixture score
replay incl. leaf id via the gate argmax).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..config.params import CommonParams, GBDTParams
from ..gbdt.tree import GBDTModel
from ..io.fs import FileSystem, is_tmp_path
from ..losses import create_loss
from .base import OnlinePredictor
from .continuous import ContinuousPredictor


class GBDTPredictor(OnlinePredictor):
    """Serves the GBDT text model on feature dicts; absent features route to
    the split's default (missing) child — matching NaN at train time
    (reference: GBDTOnlinePredictor.java:130-257, Tree.java:156-168)."""

    supports_leaf = True

    def __init__(self, config, fs: Optional[FileSystem] = None):
        super().__init__(config, fs)
        self.params = GBDTParams.from_config(self.config)
        p = self.params
        self.loss = create_loss(p.loss_function, {"sigmoid_zmax": p.sigmoid_zmax})
        self.learn_type = p.gbdt_type
        self._load_model()

    def _load_model(self) -> None:
        path = self.params.model.data_path
        if not self.fs.exists(path):
            raise FileNotFoundError(f"gbdt model doesn't exist: {path}")
        with self.fs.open(path) as f:
            self.model = GBDTModel.loads(f.read())
        self.K = self.model.num_tree_in_group
        self.n_outputs = self.K
        # use_round_num: serve only the first N rounds if configured smaller
        # (reference: GBDTOnlinePredictor.useRoundNum)
        rounds = len(self.model.trees) // max(self.K, 1)
        conf_rounds = self.params.round_num
        self.use_rounds = min(rounds, conf_rounds) if conf_rounds > 0 else rounds

    def _tree_walk(self, tree, features: Dict[str, float]) -> int:
        nid = 0
        while not tree.is_leaf(nid):
            v = features.get(tree.feat_name[nid])
            if v is None or (isinstance(v, float) and math.isnan(v)):
                go_left = tree.default_left[nid]
            else:
                go_left = v <= tree.split[nid]
            nid = tree.left[nid] if go_left else tree.right[nid]
        return nid

    def score(self, features, other=None) -> float:
        if self.K > 1:
            raise ValueError("multiclass gbdt: use scores()")
        s = 0.0
        for i in range(self.use_rounds):
            t = self.model.trees[i]
            s += t.leaf_value[self._tree_walk(t, features)]
        if self.learn_type == "random_forest":
            s /= max(self.use_rounds, 1)
        s += self.model.base_prediction
        if other is not None:
            s += float(self.loss.pred2score(float(other)))
        return s

    def scores(self, features, other=None) -> List[float]:
        if self.K == 1:
            return [self.score(features, other)]
        s = [0.0] * self.K
        for i in range(self.use_rounds * self.K):
            t = self.model.trees[i]
            s[i % self.K] += t.leaf_value[self._tree_walk(t, features)]
        if self.learn_type == "random_forest":
            s = [v / max(self.use_rounds, 1) for v in s]
        s = [v + self.model.base_prediction for v in s]
        if other is not None:
            # per-group sample-dependent base (reference:
            # GBDTOnlinePredictor.batchPredictFromFiles:361-369)
            others = other if isinstance(other, (list, tuple)) else [other] * self.K
            s = [
                v + float(self.loss.pred2score(float(o))) for v, o in zip(s, others)
            ]
        return s

    def predict(self, features, other=None) -> float:
        s = self.score(features, other)
        act = self._activation()
        if act is not None:
            return float(act(s))
        return float(self.loss.predict(s))

    def predicts(self, features, other=None) -> List[float]:
        s = np.asarray(self.scores(features, other))
        act = self._activation()
        out = act(s) if act is not None else self.loss.predict(s)
        return [float(v) for v in np.atleast_1d(out)]

    def loss_value(self, features, label, other=None) -> float:
        if self.K > 1:
            s = np.asarray(self.scores(features, other))
            return float(self.loss.loss(s, np.asarray(label)))
        return float(self.loss.loss(self.score(features, other), label))

    def predict_leaf(self, features: Dict[str, float]) -> List[int]:
        """Leaf node id per tree (reference: GBDTOnlinePredictor.predictLeaf:258)."""
        return [
            self._tree_walk(t, features)
            for t in self.model.trees[: self.use_rounds * self.K]
        ]


class GBSTPredictor(ContinuousPredictor):
    """gbmlr / gbsdt / gbhmlr / gbhsdt mixture score replay.

    score = base + lr·Σ_t fx_t(x) (GB) or the /treeNum average (RF);
    fx_t is the soft-tree output: softmax- or heap-sigmoid-gated mixture of
    per-feature linear experts (gbmlr/gbhmlr) or scalar leaves
    (gbsdt/gbhsdt). predict_leaf returns each tree's argmax gate
    (reference: GBMLROnlinePredictor.predictLeaf).

    The text parser here is deliberately independent of GBSTModel.load_tree
    (a name-keyed map vs index arrays) the same way the reference keeps
    GBMLROnlinePredictor's parser separate from GBMLRDataFlow's;
    tests/test_predict.py locks the two together."""

    supports_leaf = True

    def __init__(self, variant: str, config, fs: Optional[FileSystem] = None):
        assert variant in ("gbmlr", "gbsdt", "gbhmlr", "gbhsdt")
        self.variant = variant
        self.hier = variant in ("gbhmlr", "gbhsdt")
        self.scalar_leaves = variant in ("gbsdt", "gbhsdt")
        super().__init__(config, fs)

    def _load_model(self) -> None:
        p = self.params
        self.K = int(p.k)
        self.is_rf = p.gbst_type == "random_forest"
        self.lr = float(p.learning_rate)
        info_path = f"{p.model.data_path}/tree-info"
        self.base_score: float = float(
            self.loss.pred2score(p.uniform_base_prediction)
        )
        self.n_trees = int(p.tree_num)
        if self.fs.exists(info_path):
            with self.fs.open(info_path) as f:
                for line in f:
                    if ":" not in line:
                        continue
                    k, v = line.strip().split(":", 1)
                    if k == "finished_tree_num":
                        self.n_trees = int(float(v))
                    elif k == "uniform_base_prediction":
                        self.base_score = float(v)
        # per-tree per-feature blocks: name -> (n_trees, stride)
        K = self.K
        self.stride = (K - 1) if self.scalar_leaves else (2 * K - 1)
        self.leaves: List[np.ndarray] = []  # gbsdt family scalar leaves
        self.tree_maps: List[Dict[str, np.ndarray]] = []
        d = p.model.delim
        for t in range(self.n_trees):
            tree_dir = f"{p.model.data_path}/tree-{t:05d}"
            if not self.fs.exists(tree_dir):
                self.n_trees = t
                break
            tmap: Dict[str, np.ndarray] = {}
            leaf_vals = None
            for part in sorted(self.fs.recur_get_paths([tree_dir])):
                if is_tmp_path(part):
                    continue  # in-flight atomic_open temp from a writer
                with self.fs.open(part) as f:
                    expect_leaves = False
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        if line.startswith("k:"):
                            expect_leaves = self.scalar_leaves
                            continue
                        info = [s for s in line.split(d) if s != ""]
                        if expect_leaves:
                            leaf_vals = np.asarray(
                                [float(v) for v in info[:K]], np.float64
                            )
                            expect_leaves = False
                            continue
                        tmap[info[0]] = np.asarray(
                            [float(v) for v in info[1 : 1 + self.stride]], np.float64
                        )
            self.tree_maps.append(tmap)
            self.leaves.append(
                leaf_vals if leaf_vals is not None else np.zeros(K, np.float64)
            )

    # -- gating math (numpy mirror of models/gbst.py) ---------------------

    def _gate_probs(self, gate_in: np.ndarray) -> np.ndarray:
        K = self.K
        if self.hier:
            sig = 1.0 / (1.0 + np.exp(-gate_in))  # (K-1,) heap order
            level = np.ones(1, np.float64)
            for _ in range(int(math.log2(K))):
                n = len(level)
                gates = sig[n - 1 : 2 * n - 1]
                level = np.stack([level * gates, level * (1.0 - gates)], axis=-1).reshape(-1)
            return level
        z = np.concatenate([gate_in, [0.0]])
        z = z - z.max()
        e = np.exp(z)
        return e / e.sum()

    def _tree_fx_and_leaf(self, t: int, feats) -> tuple:
        """One tree's (fx, argmax leaf). feats: [(name, transformed val)]
        including the bias pseudo-feature when configured."""
        K = self.K
        tmap = self.tree_maps[t]
        gate_in = np.zeros(K - 1, np.float64)
        if self.scalar_leaves:
            experts = self.leaves[t]
            for name, val in feats:
                w = tmap.get(name)
                if w is not None:
                    gate_in += w * val
        else:
            experts = np.zeros(K, np.float64)
            for name, val in feats:
                w = tmap.get(name)
                if w is not None:
                    gate_in += w[: K - 1] * val
                    experts += w[K - 1 :] * val
        pi = self._gate_probs(gate_in)
        return float(np.dot(pi, experts)), int(np.argmax(pi))

    def _feats_with_bias(self, features) -> list:
        feats = self._prep(features)
        p = self.params.model
        if p.need_bias:
            feats.append((p.bias_feature_name, 1.0))
        return feats

    def score(self, features, other=None) -> float:
        feats = self._feats_with_bias(features)
        z = self.base_score
        if other is not None:
            # sample-dependent base ADDS to the uniform base score
            # (reference: GBMLROnlinePredictor lbias += pred2Score(other))
            z += float(self.loss.pred2score(float(other)))
        for t in range(self.n_trees):
            fx, _ = self._tree_fx_and_leaf(t, feats)
            z += self.lr * fx
        if self.is_rf:
            z /= max(self.n_trees, 1)
        return z

    def predict_leaf(self, features) -> List[int]:
        feats = self._feats_with_bias(features)
        return [
            self._tree_fx_and_leaf(t, feats)[1] for t in range(self.n_trees)
        ]
