from .data import GBDTIngest, GBDTData
from .binning import FeatureBins, build_bins, bin_matrix
from .tree import Tree, GBDTModel
from .trainer import GBDTTrainer
