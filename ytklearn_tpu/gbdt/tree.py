"""GBDT tree + model containers with the reference text format.

Rebuild of reference data/gbdt/Tree.java (node regexes :47-48, recursive
indent dump :255+), TreeNode.java (default-direction :78), GBDTModel.java
(header + tree list, dumpModel:63 / loadModel:79, genFeatureDict:99,
getFeatureImportance:108).

Text format (byte-compatible):
    base_prediction=<f>
    class_num=<int>
    obj=<loss name>
    tree_num=<int>
    booster[i] depth=<d>,node_num=<n>,leaf_cnt=<l>
    <indented node lines>
      inner: nid:[f_NAME<=VAL] yes=L,no=R,missing=M,gain=G,hess_sum=H,sample_cnt=C
      leaf:  nid:leaf=V,hess_sum=H,sample_cnt=C
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

# Reference Tree.java:47-48 uses greedy \S+ everywhere but its stats fields
# are mandatory, so the regex engine backtracks into place. Ours makes the
# stats suffix OPTIONAL (dump(with_stats=False) support), so every capture
# that a comma terminates must be comma-safe or `missing=` swallows
# ",gain=...,hess_sum=..." whole.
INNER_RE = re.compile(
    r"(\S+):\[f_(\S+)<=(\S+)\] yes=([^,\s]+),no=([^,\s]+),missing=([^,\s]+)"
    r"(?:,gain=([^,\s]+),hess_sum=([^,\s]+),sample_cnt=([^,\s]+))?"
)
LEAF_RE = re.compile(
    r"(\S+):leaf=([^,\s]+)(?:,hess_sum=([^,\s]+),sample_cnt=([^,\s]+))?"
)


@dataclass
class Tree:
    """Flat-array regression tree. Node 0 is the root; children allocated in
    pairs. Leaves have feat == -1."""

    feat: List[int] = field(default_factory=lambda: [-1])
    feat_name: List[str] = field(default_factory=lambda: [""])
    split: List[float] = field(default_factory=lambda: [0.0])  # cond (or slot pre-convert)
    left: List[int] = field(default_factory=lambda: [-1])
    right: List[int] = field(default_factory=lambda: [-1])
    default_left: List[bool] = field(default_factory=lambda: [True])
    leaf_value: List[float] = field(default_factory=lambda: [0.0])
    gain: List[float] = field(default_factory=lambda: [0.0])
    hess_sum: List[float] = field(default_factory=lambda: [0.0])
    sample_cnt: List[int] = field(default_factory=lambda: [0])
    # train-time: split slot interval for value conversion
    slot: List[int] = field(default_factory=lambda: [-1])

    def n_nodes(self) -> int:
        return len(self.feat)

    def is_leaf(self, nid: int) -> bool:
        return self.feat[nid] < 0

    def add_children(self, nid: int) -> Tuple[int, int]:
        l = self.n_nodes()
        for arr, d in (
            (self.feat, -1),
            (self.feat_name, ""),
            (self.split, 0.0),
            (self.left, -1),
            (self.right, -1),
            (self.default_left, True),
            (self.leaf_value, 0.0),
            (self.gain, 0.0),
            (self.hess_sum, 0.0),
            (self.sample_cnt, 0),
            (self.slot, -1),
        ):
            arr.append(d)
            arr.append(d)
        self.left[nid] = l
        self.right[nid] = l + 1
        return l, l + 1

    # -- stats ------------------------------------------------------------

    def max_depth(self) -> int:
        depth = [0] * self.n_nodes()
        best = 0
        for nid in range(self.n_nodes()):
            if not self.is_leaf(nid):
                for c in (self.left[nid], self.right[nid]):
                    depth[c] = depth[nid] + 1
                    best = max(best, depth[c])
        return best

    def leaf_cnt(self) -> int:
        return sum(1 for i in range(self.n_nodes()) if self.is_leaf(i))

    # -- predict (host, numpy) -------------------------------------------

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Dense (n, F) raw values -> leaf values; NaN routes to the default
        child (reference: Tree.java:136-168)."""
        n = X.shape[0]
        node = np.zeros((n,), np.int32)
        live = np.array([not self.is_leaf(0)] * n)
        feat = np.asarray(self.feat)
        split = np.asarray(self.split, np.float32)
        left = np.asarray(self.left)
        right = np.asarray(self.right)
        dleft = np.asarray(self.default_left)
        leaf = np.asarray(self.leaf_value, np.float32)
        while live.any():
            f = feat[node]
            v = X[np.arange(n), np.maximum(f, 0)]
            isnan = np.isnan(v)
            go_left = np.where(isnan, dleft[node], v <= split[node])
            nxt = np.where(go_left, left[node], right[node])
            node = np.where(live, nxt, node)
            live = feat[node] >= 0
        return leaf[node]

    # -- device arrays for jitted ensemble predict -----------------------

    def as_arrays(self, max_nodes: int) -> Dict[str, np.ndarray]:
        pad = max_nodes - self.n_nodes()

        def p(a, dtype, fill):
            return np.pad(np.asarray(a, dtype), (0, pad), constant_values=fill)

        return {
            "feat": p(self.feat, np.int32, -1),
            "split": p(self.split, np.float32, 0.0),
            "left": p(self.left, np.int32, -1),
            "right": p(self.right, np.int32, -1),
            "dleft": p(np.asarray(self.default_left, np.int32), np.int32, 1),
            "leaf": p(self.leaf_value, np.float32, 0.0),
        }

    def heap_arrays(
        self, depth: int, feat_ids: Optional[List[int]] = None
    ) -> Dict[str, np.ndarray]:
        """Kernel-layout (perfect-heap) export for the serve-side fused
        traversal kernels (serve/kernels.py): node at heap slot p has its
        children at 2p+1 / 2p+2, so a fixed-depth walk needs no child
        pointers — `slot = 2*slot + 2 - go_left` — and the leaf value is
        read from the last heap level only. Leaves above `depth` become
        always-go-left pad chains (split=+inf, dleft=1) whose leftmost
        last-level descendant carries the value; unreachable last-level
        slots hold -0.0 so a padded accumulation is a bit-exact no-op.

        depth     heap depth (>= self.max_depth(), >= 1)
        feat_ids  resolved column id per node (serve vocab); defaults to
                  self.feat (train-time resolved ids)

        Returns {feat (H,) i32, split (H,) f64, dleft (H,) i32,
        inner (H,) bool, leaf (LL,) f64} with H = 2^(depth+1)-1 and
        LL = 2^depth."""
        if depth < max(self.max_depth(), 1):
            raise ValueError(
                f"heap depth {depth} < tree depth {self.max_depth()}"
            )
        H = (1 << (depth + 1)) - 1
        LL = 1 << depth
        feat = np.zeros(H, np.int32)
        split = np.full(H, np.inf, np.float64)
        dleft = np.ones(H, np.int32)
        inner = np.zeros(H, bool)
        leaf = np.full(LL, -0.0, np.float64)
        ids = feat_ids if feat_ids is not None else self.feat

        stack = [(0, 0, 0)]  # (orig nid, heap pos, depth)
        while stack:
            nid, pos, d = stack.pop()
            if self.is_leaf(nid):
                # descend leftmost through the pad chain (already
                # initialized to always-left) to the last level
                for _ in range(depth - d):
                    pos = 2 * pos + 1
                leaf[pos - (LL - 1)] = float(self.leaf_value[nid])
                continue
            feat[pos] = int(ids[nid])
            split[pos] = float(self.split[nid])
            dleft[pos] = int(bool(self.default_left[nid]))
            inner[pos] = True
            stack.append((self.left[nid], 2 * pos + 1, d + 1))
            stack.append((self.right[nid], 2 * pos + 2, d + 1))
        return {
            "feat": feat, "split": split, "dleft": dleft,
            "inner": inner, "leaf": leaf,
        }

    # -- text I/O ---------------------------------------------------------

    def dump(self, booster_id: int, with_stats: bool = True) -> str:
        lines = [
            f"booster[{booster_id + 1}] depth={self.max_depth()},"
            f"node_num={self.n_nodes()},leaf_cnt={self.leaf_cnt()}"
        ]

        def rec(nid: int, depth: int):
            ind = "\t" * depth
            if self.is_leaf(nid):
                s = f"{ind}{nid}:leaf={_jfloat(self.leaf_value[nid])}"
                if with_stats:
                    s += (
                        f",hess_sum={_jfloat(self.hess_sum[nid])}"
                        f",sample_cnt={self.sample_cnt[nid]}"
                    )
                lines.append(s)
            else:
                missing = self.left[nid] if self.default_left[nid] else self.right[nid]
                s = (
                    f"{ind}{nid}:[f_{self.feat_name[nid]}<={_jfloat(self.split[nid])}]"
                    f" yes={self.left[nid]},no={self.right[nid]},missing={missing}"
                )
                if with_stats:
                    s += (
                        f",gain={_jfloat(self.gain[nid])}"
                        f",hess_sum={_jfloat(self.hess_sum[nid])}"
                        f",sample_cnt={self.sample_cnt[nid]}"
                    )
                lines.append(s)
                rec(self.left[nid], depth + 1)
                rec(self.right[nid], depth + 1)

        rec(0, 0)
        return "\n".join(lines) + "\n"

    @classmethod
    def parse(cls, lines: List[str]) -> "Tree":
        """Parse the node lines of one booster (reference: Tree.loadModel:192)."""
        t = cls()
        # first pass: find max nid to allocate
        entries = []
        for raw in lines:
            line = raw.strip()
            if not line:
                continue
            m = LEAF_RE.match(line) if ":leaf=" in line else INNER_RE.match(line)
            if m is None:
                raise ValueError(f"bad tree node line: {line!r}")
            entries.append((":leaf=" in line, m))
        max_nid = 0
        for is_leaf, m in entries:
            nid = int(m.group(1))
            max_nid = max(max_nid, nid)
            if not is_leaf:
                max_nid = max(max_nid, int(m.group(4)), int(m.group(5)))
        n = max_nid + 1
        t.feat = [-1] * n
        t.feat_name = [""] * n
        t.split = [0.0] * n
        t.left = [-1] * n
        t.right = [-1] * n
        t.default_left = [True] * n
        t.leaf_value = [0.0] * n
        t.gain = [0.0] * n
        t.hess_sum = [0.0] * n
        t.sample_cnt = [0] * n
        t.slot = [-1] * n
        for is_leaf, m in entries:
            nid = int(m.group(1))
            if is_leaf:
                t.leaf_value[nid] = float(m.group(2))
                if m.group(3) is not None:
                    t.hess_sum[nid] = float(m.group(3))
                    t.sample_cnt[nid] = int(float(m.group(4)))
            else:
                t.feat_name[nid] = m.group(2)
                try:
                    t.feat[nid] = int(m.group(2))
                except ValueError:
                    t.feat[nid] = 0  # resolved later via feature dict
                t.split[nid] = float(m.group(3))
                t.left[nid] = int(m.group(4))
                t.right[nid] = int(m.group(5))
                t.default_left[nid] = int(m.group(6)) == int(m.group(4))
                if m.group(7) is not None:
                    t.gain[nid] = float(m.group(7))
                    t.hess_sum[nid] = float(m.group(8))
                    t.sample_cnt[nid] = int(float(m.group(9)))
        return t

    def feature_importance(self, acc: Dict[str, Tuple[int, float]]) -> None:
        """Accumulate (split_count, gain_sum) per feature name (reference:
        data/gbdt/Tree.featureImportance feeding GBDTModel:108-114)."""
        for nid in range(self.n_nodes()):
            if not self.is_leaf(nid):
                name = self.feat_name[nid]
                cnt, gain = acc.get(name, (0, 0.0))
                acc[name] = (cnt + 1, gain + float(self.gain[nid]))


def unbundle_tree(tree: "Tree", plan) -> None:
    """Rewrite a tree grown on an EFB-bundled bin matrix back into
    ORIGINAL feature space, in place: every inner node's column id and
    slot interval (`feat`, `slot`, `split` — still slot-space, pre value
    conversion) map through `plan.unbundle_split`, so the downstream
    split-value conversion, dumps, feature importance, and serving see
    only real features. `plan` is a gbdt.binning.BundlePlan (duck-typed
    here to keep tree.py free of a binning import)."""
    for nid in range(tree.n_nodes()):
        if tree.is_leaf(nid):
            continue
        fid, slot_l, slot_r = plan.unbundle_split(
            tree.feat[nid], tree.slot[nid], int(tree.split[nid])
        )
        tree.feat[nid] = fid
        tree.slot[nid] = slot_l
        tree.split[nid] = float(slot_r)


def _jfloat(v: float) -> str:
    """Java Float.toString-ish rendering (shortest round-trip of float32)."""
    return repr(float(np.float32(v)))


@dataclass
class GBDTModel:
    """Header + tree list (reference: data/gbdt/GBDTModel.java)."""

    base_prediction: float = 0.5
    num_tree_in_group: int = 1
    obj_name: str = "sigmoid"
    trees: List[Tree] = field(default_factory=list)

    def dumps(self, with_stats: bool = True) -> str:
        out = [
            f"base_prediction={_jfloat(self.base_prediction)}",
            f"class_num={self.num_tree_in_group}",
            f"obj={self.obj_name}",
            f"tree_num={len(self.trees)}",
        ]
        for i, t in enumerate(self.trees):
            out.append(t.dump(i, with_stats).rstrip("\n"))
        return "\n".join(out) + "\n"

    @classmethod
    def loads(cls, text: str) -> "GBDTModel":
        lines = text.split("\n")
        m = cls(
            base_prediction=float(lines[0].split("=")[1]),
            num_tree_in_group=int(lines[1].split("=")[1]),
            obj_name=lines[2].split("=")[1],
        )
        tree_num = int(lines[3].split("=")[1])
        blocks: List[List[str]] = []
        cur: Optional[List[str]] = None
        for line in lines[4:]:
            if line.strip().startswith("booster["):
                cur = []
                blocks.append(cur)
            elif cur is not None and line.strip():
                cur.append(line)
        if len(blocks) != tree_num:
            raise ValueError(f"expected {tree_num} trees, found {len(blocks)}")
        m.trees = [Tree.parse(b) for b in blocks]
        return m

    def feature_importance(self) -> Dict[str, Tuple[int, float]]:
        """name -> (sum_split_count, sum_gain), gain-descending (the
        reference returns an unordered HashMap, GBDTModel.java:108-114;
        a deterministic order makes the dump reproducible)."""
        acc: Dict[str, Tuple[int, float]] = {}
        for t in self.trees:
            t.feature_importance(acc)
        return dict(sorted(acc.items(), key=lambda kv: (-kv[1][1], kv[0])))

    def predict_scores(self, X: np.ndarray) -> np.ndarray:
        """Raw ensemble scores (host numpy; the trainer keeps a faster
        on-device path). Multi-group (softmax): (n, K) scores."""
        K = self.num_tree_in_group
        n = X.shape[0]
        if K == 1:
            s = np.full((n,), self.base_prediction, np.float32)
            for t in self.trees:
                s += t.predict(X)
            return s
        s = np.full((n, K), self.base_prediction, np.float32)
        for i, t in enumerate(self.trees):
            s[:, i % K] += t.predict(X)
        return s
