"""Feature-parallel exact-greedy tree maker — columns sharded over the mesh.

Rebuild of reference optimizer/gbdt/FeatureParallelTreeMakerByLevel.java:147
(threads own column ranges; gradients allgathered :274; per-node best split
merged across owners :407; positions shared :443), re-architected for the
mesh: the bin matrix lives transposed (F_pad, n) with the FEATURE axis
sharded over the mesh's data axis, every device holds all samples of its
feature slice, and the per-node best-split merge is `pargmax_tuple` — the
dense-tuple replacement for the reference's Kryo SplitInfo object-allreduce
(data/gbdt/SplitInfo.needReplace:99 tie-break: equal gains go to the lower
rank, i.e. the lower global feature id, matching the data-parallel maker's
first-max flat argmax).

Gradients/positions arrive replicated: entering shard_map with in_spec P()
on row-sharded g/h is XLA's all_gather — the same wire traffic the
reference issued by hand at :274/:443.

Growth is level-synchronous on the host (one jitted sharded step per
level), mirroring GBDTTrainer.build_tree_level_wise so the two makers grow
identical trees on identical inputs.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.collectives import pargmax_tuple
from ..parallel.mesh import DATA_AXIS, shard_map_compat
from .engine import split_kernel
from .hist import hist_wave
from .tree import Tree


def shard_features(mesh, bins_np: np.ndarray):
    """(n, F) host bin matrix -> (F_pad, n) device array, features sharded.

    F pads to a mesh-size multiple with all-zero pseudo-features (masked out
    of split search; reference pads column ranges the same way via avgAssign,
    dataflow/GBDTDataFlow.java:240-279)."""
    D = mesh.devices.size
    n, F = bins_np.shape
    F_pad = (F + D - 1) // D * D
    bt = np.zeros((F_pad, n), np.int32)
    bt[:F] = bins_np.T
    return jax.device_put(bt, NamedSharding(mesh, P(DATA_AXIS, None))), F_pad


_PROGRAMS: dict = {}


def _cached(kind: str, key, builder):
    full = (kind,) + key
    if full not in _PROGRAMS:
        _PROGRAMS[full] = builder()
    return _PROGRAMS[full]


def _make_level_step(mesh, F_pad: int, B: int, cfg, n_nodes: int):
    """One level: local hist over owned features -> local best split per
    node -> global pargmax merge. Returns per-node global split fields."""
    D = mesh.devices.size
    F_loc = F_pad // D

    def step(bins_local, pos, g, h, feat_mask_local):
        node_ids = jnp.arange(n_nodes, dtype=jnp.int32)
        # f32 accumulation: this maker is the exactness-focused one (bf16
        # would desync its gains from the data-parallel maker's f32 scatter)
        hist = hist_wave(
            bins_local, pos, g, h, node_ids, B, use_bf16=False, force_dense=True
        )  # (N, F_loc, B, 3)
        out = split_kernel(hist, feat_mask_local, cfg)
        (chg, flat, slotl, GL, HL, CL, GR, HR, CR) = out
        off = jax.lax.axis_index(DATA_AXIS) * F_loc
        fid_global = (off + flat // B).astype(jnp.int32)
        slot_r = (flat % B).astype(jnp.int32)
        best, payload = pargmax_tuple(
            chg, (fid_global, slot_r, slotl, GL, HL, CL, GR, HR, CR)
        )
        return (best,) + payload

    specs_in = (
        P(DATA_AXIS, None),  # bins_local
        P(),  # pos (replicated; all_gather on entry if row-sharded)
        P(),  # g
        P(),  # h
        P(DATA_AXIS),  # feat_mask
    )
    return jax.jit(
        shard_map_compat(
            step,
            mesh=mesh,
            in_specs=specs_in,
            out_specs=tuple([P()] * 10),
            check_vma=False,
        )
    )


def _make_router(mesh, F_pad: int, n_nodes: int):
    """Share each splitting node's feature row across the mesh (the owner
    contributes, psum broadcasts — reference position allgather :443) and
    route samples to next-level-local child slots."""
    D = mesh.devices.size
    F_loc = F_pad // D

    def route(bins_local, pos, node_feat, node_slot, node_child_base):
        off = jax.lax.axis_index(DATA_AXIS) * F_loc
        fl = node_feat - off
        mine = (node_feat >= 0) & (fl >= 0) & (fl < F_loc)
        safe = jnp.maximum(pos, 0)
        # each sample needs ONE bin: its node's split feature, contributed by
        # the shard owning that feature — a per-sample (n,) psum, never the
        # (N, n) row matrix (5 GB at Higgs level widths)
        r = jnp.clip(fl[safe], 0, F_loc - 1)  # (n,) local row per sample
        b_local = jnp.take_along_axis(bins_local, r[None, :], axis=0)[0]
        b = jax.lax.psum(jnp.where(mine[safe], b_local, 0), DATA_AXIS)
        base = node_child_base[safe]
        go_right = b > node_slot[safe]
        new = jnp.where(base >= 0, base + go_right.astype(jnp.int32), -1)
        return jnp.where(pos >= 0, new, -1)

    return jax.jit(
        shard_map_compat(
            route,
            mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(), P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def build_tree_level_feature_parallel(
    trainer,
    mesh,
    bins_t,
    F_pad: int,
    g,
    h,
    pos0,
    F: int,
    B: int,
    feat_mask,
    names,
) -> Tree:
    """Level-synchronous exact-greedy growth with feature-sharded search.

    Mirrors GBDTTrainer.build_tree_level_wise's host loop; only the
    histogram/split/route kernels differ (sharded + merged)."""
    p = trainer.params
    tree = Tree()
    pos = pos0
    level_nids = [0]
    fmask_pad = jnp.concatenate(
        [jnp.asarray(feat_mask), jnp.zeros((F_pad - F,), bool)]
    )

    lr = np.float32(p.learning_rate)
    max_leaves = p.max_leaf_cnt if p.max_leaf_cnt > 0 else 1 << 30
    max_depth = p.max_depth if p.max_depth > 0 else 1 << 30

    for depth in range(max_depth):
        n_nodes = len(level_nids)
        if n_nodes == 0:
            break
        n_pad = 1 << (n_nodes - 1).bit_length()
        step = _cached(
            "step",
            (mesh, F_pad, B, trainer._cfg(), n_pad),
            lambda: _make_level_step(mesh, F_pad, B, trainer._cfg(), n_pad),
        )
        out = tuple(np.asarray(o) for o in step(bins_t, pos, g, h, fmask_pad))
        (chg, fid, slot_r, slot_l, GL, HL, CL, GR, HR, CR) = out

        if depth == 0:
            # root stats ride the first level pass (GL+GR = node totals even
            # when no valid split exists: flat argmax over all -inf picks
            # slot 0 where the exclusive left cumsum is 0)
            Gt, Ht, Ct = GL[0] + GR[0], HL[0] + HR[0], CL[0] + CR[0]
            tree.hess_sum[0], tree.sample_cnt[0] = float(Ht), int(round(Ct))
            tree.leaf_value[0] = float(
                np.float32(trainer.node_value_fn(Gt, Ht)) * lr
            )

        node_feat = np.full((n_pad,), -1, np.int32)
        node_slot = np.full((n_pad,), 0, np.int32)
        child_base = np.full((n_pad,), -1, np.int32)
        next_nids: List[int] = []
        leaves_after = tree.leaf_cnt()
        for k in range(n_nodes):
            nid = level_nids[k]
            can = (
                depth < max_depth
                and leaves_after + 1 < max_leaves + 1
                and trainer._decide_split(chg[k], CL[k], CR[k], HL[k], HR[k])
            )
            if not can:
                continue
            left, right = trainer._finish_split(
                tree,
                names,
                nid,
                int(fid[k]),
                int(slot_l[k]),
                int(slot_r[k]),
                (GL[k], HL[k], CL[k], GR[k], HR[k], CR[k]),
            )
            tree.gain[nid] = float(chg[k])
            tree.slot[nid] = int(slot_l[k])
            tree.split[nid] = float(slot_r[k])
            node_feat[k] = int(fid[k])
            node_slot[k] = int(slot_l[k])
            child_base[k] = len(next_nids)
            next_nids.extend([left, right])
            leaves_after = tree.leaf_cnt()
        if not next_nids:
            break
        router = _cached(
            "route",
            (mesh, F_pad, n_pad),
            lambda: _make_router(mesh, F_pad, n_pad),
        )
        pos = router(
            bins_t,
            pos,
            jnp.asarray(node_feat),
            jnp.asarray(node_slot),
            jnp.asarray(child_base),
        )
        level_nids = next_nids

    return tree
