"""Streaming weighted quantile sketch — the memory-bounded binning path.

Rebuild of reference utils/WeightApproximateQuantile.java (the GK-style
weighted quantile summary behind sample_by_quantile and the distributed
binning merge, SampleManager.java:128-143):

  Summary   — (value, rmin, rmax, w) entries where [rmin, rmax] bound the
              true weighted rank of each value (Summary fields at
              WeightApproximateQuantile.java:237-251). Built exactly from
              a chunk (sort + cumsum), merged by the two-pointer rank
              combination (merge:476 — here one vectorized searchsorted
              per side), pruned by querying evenly spaced ranks
              (compress:418).
  WeightedQuantileSketch — the level-cascade driver (update:93-117): a
              binary counter of summaries, each level holding the merge
              of 2^l chunks, so prune error stays O(eps * log(n/chunk))
              instead of compounding linearly as sequential re-pruning
              would.

Error bound: an exact chunk summary has rank error 0; merge adds none;
each prune to `b` entries adds <= B/(2b) rank error (midpoint query of
interval bounds). With the cascade, a value's total error is bounded by
(levels + 1) * B/(2b).

numpy-only on purpose: this runs at load time on the host, streaming
chunks that never materialize the full column (the reference's reader
threads feed update() the same way).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np


@dataclass
class Summary:
    """Weighted rank summary; `value` sorted ascending, ranks in weight
    units: rmin[i] = lower bound of sum(w of entries < value[i]) plus this
    entry's own start, rmax[i] = upper bound including the entry."""

    value: np.ndarray  # (k,) f64 sorted
    rmin: np.ndarray  # (k,) f64
    rmax: np.ndarray  # (k,) f64
    w: np.ndarray  # (k,) f64
    total: float  # B: total pushed weight

    @property
    def size(self) -> int:
        return len(self.value)

    @classmethod
    def from_exact(cls, values: np.ndarray, weights: Optional[np.ndarray] = None) -> "Summary":
        """Exact summary of a chunk: duplicates grouped, rmin/rmax tight
        (reference Summary.sort:303-310 after an insert phase)."""
        v = np.asarray(values, np.float64)
        if weights is None:
            w = np.ones_like(v)
        else:
            w = np.asarray(weights, np.float64)
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        uv, start = np.unique(v, return_index=True)
        gw = np.add.reduceat(w, start) if len(v) else np.zeros(0)
        cum = np.cumsum(gw)
        return cls(
            value=uv,
            rmin=cum - gw,
            rmax=cum,
            w=gw,
            total=float(cum[-1]) if len(cum) else 0.0,
        )

    def query_values(self, max_cnt: int) -> np.ndarray:
        """Candidates at max_cnt evenly spaced weighted ranks, midpoint
        rule on [rmin, rmax] (SampleByQuantile.java:60-105 query loop)."""
        if self.size == 0:
            return np.zeros(0, np.float32)
        ranks = (np.arange(1, max_cnt + 1) / max_cnt) * self.total
        mid = 0.5 * (self.rmin + self.rmax)
        pos = np.searchsorted(mid, ranks, side="left").clip(0, self.size - 1)
        return np.unique(self.value[pos].astype(np.float32))


def merge_summaries(a: Summary, b: Summary) -> Summary:
    """Rank-combining merge (reference merge:476-560, vectorized).

    For each entry of one side, the other side contributes
      rmin += rmin[last entry with value <= v]      (0 if none)
      rmax += rmax[first entry with value >= v] - w[that entry]
              (or its full rmax when no larger entry exists)
    which keeps [rmin, rmax] true bounds of the combined rank."""
    if a.size == 0:
        return b
    if b.size == 0:
        return a

    def deltas(v, other: Summary):
        ls = np.searchsorted(other.value, v, side="right") - 1
        ls_c = np.maximum(ls, 0)
        # strictly-smaller entries contribute their own point mass to the
        # lower bound too (tight variant — the reference's rmin[LS] alone
        # is valid but loose; cf. merge:476 thatLSPointer delta)
        eq = (ls >= 0) & (other.value[ls_c] == v)
        rmin_d = np.where(
            ls >= 0,
            other.rmin[ls_c] + np.where(eq, 0.0, other.w[ls_c]),
            0.0,
        )
        sl = np.searchsorted(other.value, v, side="left")
        in_range = sl < other.size
        sl_c = np.minimum(sl, other.size - 1)
        rmax_d = np.where(
            in_range, other.rmax[sl_c] - other.w[sl_c], other.rmax[-1]
        )
        return rmin_d, rmax_d

    # exact-tie handling: an entry of `a` with the same value as one in `b`
    # coalesces (both sides' mass belongs to the same value)
    a_rmin_d, a_rmax_d = deltas(a.value, b)
    b_rmin_d, b_rmax_d = deltas(b.value, a)
    v = np.concatenate([a.value, b.value])
    rmin = np.concatenate([a.rmin + a_rmin_d, b.rmin + b_rmin_d])
    rmax = np.concatenate([a.rmax + a_rmax_d, b.rmax + b_rmax_d])
    w = np.concatenate([a.w, b.w])
    order = np.argsort(v, kind="stable")
    v, rmin, rmax, w = v[order], rmin[order], rmax[order], w[order]
    # coalesce duplicate values: they represent the same point mass; keep
    # the widest valid bounds and the summed weight
    uv, start = np.unique(v, return_index=True)
    if len(uv) != len(v):
        rmin = np.minimum.reduceat(rmin, start)
        rmax = np.maximum.reduceat(rmax, start)
        w = np.add.reduceat(w, start)
        v = uv
        # twin entries each excluded the other's mass AT the value from
        # their rmax (reference SL-pointer convention); restore the upper
        # bound so rmax >= rmin + own mass stays true after coalescing
        rmax = np.maximum(rmax, rmin + w)
    return Summary(value=v, rmin=rmin, rmax=rmax, w=w, total=a.total + b.total)


def prune_summary(s: Summary, b: int) -> Summary:
    """Keep entries at ~b evenly spaced ranks (+ both extremes), the
    compress step (reference compress:418-473). Adds <= B/(2b) rank error."""
    if s.size <= b + 1:
        return s
    mid = 0.5 * (s.rmin + s.rmax)
    ranks = (np.arange(1, b) / b) * s.total
    keep = np.searchsorted(mid, ranks, side="left").clip(0, s.size - 1)
    keep = np.unique(np.concatenate([[0], keep, [s.size - 1]]))
    return Summary(
        value=s.value[keep],
        rmin=s.rmin[keep],
        rmax=s.rmax[keep],
        w=s.w[keep],
        total=s.total,
    )


class WeightedQuantileSketch:
    """Chunked streaming sketch with the reference's level cascade
    (update:93-117): level l holds a pruned summary of 2^l chunks; pushing
    a chunk carry-merges like a binary counter."""

    def __init__(self, b: int = 1024, chunk_rows: int = 1 << 20):
        self.b = int(b)
        self.chunk_rows = int(chunk_rows)
        self.levels: List[Optional[Summary]] = []
        self._buf_v: List[np.ndarray] = []
        self._buf_w: List[np.ndarray] = []
        self._buffered = 0
        self._pruned = False  # True once any prune actually dropped entries

    @property
    def is_exact(self) -> bool:
        """True while no prune has dropped entries — every distinct pushed
        value is still in the summary with exact rank bounds (low-
        cardinality columns never overflow b, so their sketch stays a
        perfect distinct-value table)."""
        return not self._pruned

    def _prune(self, s: Summary) -> Summary:
        out = prune_summary(s, self.b)
        if out.size < s.size:
            self._pruned = True
        return out

    def push(self, values: np.ndarray, weights: Optional[np.ndarray] = None) -> None:
        values = np.asarray(values)
        self._buf_v.append(values)
        self._buf_w.append(
            np.asarray(weights)
            if weights is not None
            else np.ones(len(values), np.float64)
        )
        self._buffered += len(values)
        while self._buffered >= self.chunk_rows:
            self._flush_chunk()

    def _take_chunk(self):
        out_v: List[np.ndarray] = []
        out_w: List[np.ndarray] = []
        need = self.chunk_rows
        while need > 0 and self._buf_v:
            v, w = self._buf_v[0], self._buf_w[0]
            if len(v) <= need:
                out_v.append(v)
                out_w.append(w)
                self._buf_v.pop(0)
                self._buf_w.pop(0)
                need -= len(v)
            else:
                out_v.append(v[:need])
                out_w.append(w[:need])
                self._buf_v[0] = v[need:]
                self._buf_w[0] = w[need:]
                need = 0
        self._buffered -= sum(len(v) for v in out_v)
        return np.concatenate(out_v), np.concatenate(out_w)

    def _flush_chunk(self) -> None:
        v, w = self._take_chunk()
        s = self._prune(Summary.from_exact(v, w))
        lvl = 0
        while True:
            if lvl == len(self.levels):
                self.levels.append(s)
                break
            if self.levels[lvl] is None:
                self.levels[lvl] = s
                break
            s = self._prune(merge_summaries(self.levels[lvl], s))
            self.levels[lvl] = None
            lvl += 1

    def summary(self) -> Summary:
        """Merge every level + the partial buffer (mergeAll:118-131).
        Does not consume the sketch."""
        parts: List[Summary] = [s for s in self.levels if s is not None]
        if self._buffered:
            v = np.concatenate(self._buf_v)
            w = np.concatenate(self._buf_w)
            parts.append(self._prune(Summary.from_exact(v, w)))
        if not parts:
            return Summary.from_exact(np.zeros(0), np.zeros(0))
        out = parts[0]
        for p in parts[1:]:
            out = merge_summaries(out, p)
        return out

    def query_values(self, max_cnt: int) -> np.ndarray:
        return self.summary().query_values(max_cnt)
