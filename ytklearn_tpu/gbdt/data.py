"""GBDT data ingest — dense feature matrix + missing-value fill.

Rebuild of reference dataflow/GBDTCoreData.java (dense int-bits matrix
`x[sample*maxFeatureDim+fid]`, missing = NaN bits) +
feature/gbdt/missing/* (mean / quantile@q / value@v fill computed globally
and written into the matrix; the fill values later decide each split's
default direction for NaN at predict time, GBDTOptimizer.addFeatureNameInModel).

TPU shape: X is a plain (n, F) float32 ndarray with NaN marking missing —
a single device_put away from the mesh.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config.params import GBDTParams
from ..io.fs import FileSystem, LocalFileSystem
from ..io.reader import parse_line


@dataclass
class GBDTData:
    X: np.ndarray  # (n, F) f32, NaN = missing until filled
    y: np.ndarray  # (n,) or (n, K) f32
    weight: np.ndarray  # (n,) f32
    n_real: int
    feature_names: List[str]  # index -> name
    missing_fill: Optional[np.ndarray] = None  # (F,) fill values used

    @property
    def n(self) -> int:
        return self.X.shape[0]

    @property
    def n_features(self) -> int:
        return self.X.shape[1]

    def pad_rows(self, multiple: int) -> "GBDTData":
        n = self.X.shape[0]
        target = (n + multiple - 1) // multiple * multiple
        if target == n:
            return self
        pad = target - n
        return GBDTData(
            X=np.pad(self.X, ((0, pad), (0, 0))),
            y=np.pad(self.y, ((0, pad),) + ((0, 0),) * (self.y.ndim - 1)),
            weight=np.pad(self.weight, (0, pad)),
            n_real=self.n_real,
            feature_names=self.feature_names,
            missing_fill=self.missing_fill,
        )


class GBDTIngest:
    """Parse ytklearn lines into the dense matrix; compute + apply the
    missing-value fill (reference: FillMissingValue.java:49,61)."""

    def __init__(
        self,
        params: GBDTParams,
        fs: Optional[FileSystem] = None,
        transform_hook=None,
    ):
        self.params = params
        self.fs = fs or LocalFileSystem()
        self.transform_hook = transform_hook
        if params.data.max_feature_dim <= 0:
            raise ValueError("gbdt requires data.max_feature_dim")
        self.F = params.data.max_feature_dim
        self.K = params.class_num if params.loss_function == "softmax" else 1

    def _lines(self, paths):
        """Raw lines for THIS process's shard, optionally expanded through
        the python transform hook (reference: Jython transform,
        dataflow/CoreData.java:298-311; sharding: DataFlow.java:391-410
        lines_avg / files_avg, mirroring io.reader.DataIngest.load)."""
        from ..io.reader import shard_read_lines

        for raw in shard_read_lines(self.fs, self.params.data, paths):
            if self.transform_hook is None:
                yield raw
            else:
                yield from self.transform_hook(raw.encode())

    def _parse(
        self,
        paths,
        max_error_tol: int,
        fmap: Optional[Dict[str, int]] = None,
        frozen: bool = False,
    ) -> GBDTData:
        """fmap: feature name -> dense column, grown in first-seen order while
        parsing train data, frozen for test data — the reference's
        OnlineFeatureMap (GBDTCoreData.java:371-381: unseen test features are
        skipped, train overflow past max_feature_dim is a checked error).

        Dispatches to the native C++ parser (io.native) when available and no
        python transform hook is configured; both paths produce identical
        output (tests/test_native_ingest.py)."""
        if self.transform_hook is None:
            from ..io import native

            if (native.native_available()
                    and native.supports_delims(self.params.data.delim)):
                return self._parse_native(paths, max_error_tol, fmap, frozen)
        return self._parse_python(paths, max_error_tol, fmap, frozen)

    def _parse_native(
        self,
        paths,
        max_error_tol: int,
        fmap: Optional[Dict[str, int]] = None,
        frozen: bool = False,
    ) -> GBDTData:
        """Columnar native parse -> vectorized dense-matrix assembly."""
        from ..io import native
        from ..io.reader import shard_plan

        dp = self.params.data
        paths, divisor, remainder = shard_plan(self.fs, dp, paths)
        d = dp.delim
        blk = native.parse_paths(
            self.fs, paths, d.x_delim, d.y_delim, d.features_delim,
            d.feature_name_val_delim, divisor=divisor, remainder=remainder,
        )

        # label expansion + shape validation (python path: errors per bad row)
        n_errors = blk.n_errors
        bad, y_all = native.expand_labels_columnar(
            blk.label_ptr, blk.labels, blk.n, self.K
        )
        n_errors += int(bad.sum())
        keep = ~bad

        # feature-name -> column map, continuing any existing dict. Bad-label
        # rows claim no columns (python path: fmap.update happens only after
        # the whole line validates). Names go in by first-seen (row, in-row
        # position) order over kept rows.
        if fmap is None:
            fmap = {}
        rows_all = np.repeat(np.arange(blk.n), np.diff(blk.row_ptr))
        kept_feat = keep[rows_all]
        col_of_local = np.full(len(blk.names), -1, np.int64)
        unknown = []
        for lid, name in enumerate(blk.names):
            idx = fmap.get(name)
            if idx is not None:
                col_of_local[lid] = idx
            else:
                unknown.append(lid)
        if unknown and not frozen:
            unknown = np.asarray(unknown, np.int64)
            unk_mask = np.zeros(len(blk.names), bool)
            unk_mask[unknown] = True
            sel = kept_feat & unk_mask[blk.feat_ids]
            u_rows = rows_all[sel]
            u_ids = blk.feat_ids[sel]
            # restrict to names actually used by kept rows
            present = np.unique(u_ids)
            if len(fmap) + len(present) <= self.F:
                # fast path: everything fits — assign by global first-seen
                # order, fully vectorized (the common case)
                first_idx = np.full(len(blk.names), np.iinfo(np.int64).max)
                np.minimum.at(first_idx, u_ids, np.arange(len(u_ids)))
                for lid in present[np.argsort(first_idx[present], kind="stable")]:
                    fmap[blk.names[lid]] = len(fmap)
                    col_of_local[lid] = fmap[blk.names[lid]]
            else:
                # overflow: emulate the python path row-by-row — a row whose
                # staging would exceed max_feature_dim is an ERROR LINE (it
                # claims no columns, counts toward max_error_tol, and later
                # rows may still claim its other names)
                bad_cap = np.zeros(blk.n, bool)
                last_name = ""
                boundaries = np.flatnonzero(np.diff(u_rows)) + 1
                for g in np.split(np.arange(len(u_rows)), boundaries):
                    if len(g) == 0:
                        continue
                    staged: List[int] = []
                    seen = set()
                    ok = True
                    for occ in g:
                        lid = int(u_ids[occ])
                        if col_of_local[lid] >= 0 or lid in seen:
                            continue
                        if len(fmap) + len(staged) >= self.F:
                            ok = False
                            last_name = blk.names[lid]
                            break
                        seen.add(lid)
                        staged.append(lid)
                    if ok:
                        for lid in staged:
                            fmap[blk.names[lid]] = len(fmap)
                            col_of_local[lid] = fmap[blk.names[lid]]
                    else:
                        bad_cap[u_rows[g[0]]] = True
                n_errors += int(bad_cap.sum())
                if n_errors > max_error_tol:
                    raise ValueError(
                        f"max_feature_dim({self.F}) smaller than real "
                        f"feature number in data set (feature {last_name!r})"
                    )
                keep &= ~bad_cap
                kept_feat = keep[rows_all]
        if n_errors > max_error_tol:
            raise ValueError(
                f"data error lines ({n_errors}) exceed max_error_tol "
                f"({max_error_tol})"
            )
        self._fmap = fmap

        # assemble dense matrix over kept rows. numpy fancy assignment with
        # duplicate (row, col) pairs has unspecified winner, but the python
        # path's sequential store makes the LAST in-row occurrence win —
        # dedup keep-last before the scatter
        new_row = np.cumsum(keep) - 1
        n = int(keep.sum())
        X = np.full((n, self.F), np.nan, np.float32)
        cols = col_of_local[blk.feat_ids]
        m = kept_feat & (cols >= 0)
        r = new_row[rows_all[m]]
        c = cols[m]
        v = blk.feat_vals[m]
        flat = r * np.int64(self.F) + c
        last = len(flat) - 1 - np.unique(flat[::-1], return_index=True)[1]
        X[r[last], c[last]] = v[last]
        weight = blk.weights[keep].astype(np.float32)
        y = y_all[keep]
        return GBDTData(X=X, y=y, weight=weight, n_real=n,
                        feature_names=self._names_from_fmap(fmap))

    def _parse_python(
        self,
        paths,
        max_error_tol: int,
        fmap: Optional[Dict[str, int]] = None,
        frozen: bool = False,
    ) -> GBDTData:
        """Pure-python reference path (also the transform-hook path)."""
        delim = self.params.data.delim
        if fmap is None:
            fmap = {}
        rows: List[Tuple[float, List[float], List[Tuple[int, float]]]] = []
        errors = 0
        for line in self._lines(paths):
            if not line.strip():
                continue
            try:
                pl = parse_line(line, delim)
                feats = []
                staged: Dict[str, int] = {}  # new names held until the whole
                for name, v in pl.feats:  # line parses clean (error-tol lines
                    idx = fmap.get(name)  # must not claim dense columns)
                    if idx is None:
                        idx = staged.get(name)
                    if idx is None:
                        if frozen:
                            continue  # test-only feature: ignored
                        idx = len(fmap) + len(staged)
                        if idx >= self.F:
                            raise ValueError(
                                f"max_feature_dim({self.F}) smaller than real "
                                f"feature number in data set (feature {name!r})"
                            )
                        staged[name] = idx
                    feats.append((idx, v))
                labels = pl.labels
                if self.K > 1:
                    if len(labels) == 1:
                        c = int(labels[0])
                        labels = [0.0] * self.K
                        labels[c] = 1.0
                    elif len(labels) != self.K:
                        raise ValueError("label width mismatch")
            except Exception:
                errors += 1
                if errors > max_error_tol:
                    raise
                continue
            fmap.update(staged)
            rows.append((pl.weight, labels, feats))

        self._fmap = fmap
        n = len(rows)
        X = np.full((n, self.F), np.nan, np.float32)
        weight = np.empty((n,), np.float32)
        if self.K > 1:
            y = np.zeros((n, self.K), np.float32)
        else:
            y = np.zeros((n,), np.float32)
        for i, (wei, labels, feats) in enumerate(rows):
            weight[i] = wei
            if self.K > 1:
                y[i] = labels
            else:
                y[i] = labels[0]
            for fid, v in feats:
                X[i, fid] = v
        return GBDTData(X=X, y=y, weight=weight, n_real=n,
                        feature_names=self._names_from_fmap(fmap))

    def _names_from_fmap(self, fmap: Dict[str, int]) -> List[str]:
        """index -> name, unclaimed dense columns keeping numeric names."""
        names = [str(i) for i in range(self.F)]
        for name, idx in fmap.items():
            names[idx] = name
        return names

    def compute_missing_fill(self, X: np.ndarray) -> np.ndarray:
        """(F,) fill values per the configured strategy, globally merged
        across processes (reference: ComputeMean.java:71 allreduce,
        ComputeQuantile.java:72 sketch allreduce, ComputeValue —
        `mean` | `quantile@q` | `value@v`)."""
        from ..parallel.collectives import host_allgather_objects

        spec = self.params.missing_value
        base, _, arg = str(spec).partition("@")
        base = base.lower()
        if base == "value":
            v = float(arg) if arg else 0.0
            return np.full((X.shape[1],), v, np.float32)
        if base == "mean":
            # exact across processes: allreduce of (nansum, non-nan count)
            sums = np.nansum(X, axis=0, dtype=np.float64)
            cnts = np.sum(~np.isnan(X), axis=0, dtype=np.int64)
            merged = host_allgather_objects((sums, cnts))
            tot = np.sum([m[0] for m in merged], axis=0)
            cnt = np.sum([m[1] for m in merged], axis=0)
            with np.errstate(invalid="ignore", divide="ignore"):
                fill = np.where(cnt > 0, tot / np.maximum(cnt, 1), 0.0)
            return fill.astype(np.float32)
        if base == "quantile":
            import jax

            q = float(arg) if arg else 0.5
            if jax.process_count() == 1:
                with np.errstate(invalid="ignore", all="ignore"):
                    fill = np.nanquantile(X, q, axis=0)
                return np.nan_to_num(fill, nan=0.0).astype(np.float32)
            # local per-feature quantile grids merge as weighted sketches
            # (approximate, like the reference's GK summaries)
            from .binning import merge_quantile_candidates

            grid = np.linspace(0.0, 1.0, 257)
            with np.errstate(invalid="ignore", all="ignore"):
                local = np.nanquantile(X, grid, axis=0)  # (257, F)
            cnts = np.sum(~np.isnan(X), axis=0, dtype=np.int64)
            merged = host_allgather_objects((local, cnts))
            F = X.shape[1]
            fill = np.zeros((F,), np.float32)
            for f in range(F):
                pairs = []
                for m in merged:
                    vals = m[0][:, f]
                    vals = vals[~np.isnan(vals)]
                    mass = float(m[1][f])
                    if len(vals) and mass > 0:
                        pairs.append((vals, mass))
                if not pairs:
                    continue
                cand = merge_quantile_candidates(
                    [p[0] for p in pairs], [p[1] for p in pairs], 257
                )
                fill[f] = cand[min(int(q * (len(cand) - 1) + 0.5), len(cand) - 1)]
            return fill
        raise ValueError(f"unknown missing_value strategy: {spec!r}")

    def _merge_fmap_multihost(self, train: GBDTData) -> GBDTData:
        """Reconcile per-process first-seen feature dicts into one global
        name->column map and remap the local matrix (reference:
        DataFlow.handleLocalIdx:413-446 local->global index rewrite)."""
        from ..parallel.collectives import host_allgather_objects

        gathered = host_allgather_objects(sorted(self._fmap))
        if len(gathered) == 1:
            return train
        names = sorted(set().union(*[set(g) for g in gathered]))
        if len(names) > self.F:
            raise ValueError(
                f"max_feature_dim({self.F}) smaller than global feature "
                f"number {len(names)}"
            )
        gmap = {n: i for i, n in enumerate(names)}
        X = np.full_like(train.X, np.nan)
        for name, old in self._fmap.items():
            X[:, gmap[name]] = train.X[:, old]
        self._fmap = gmap
        return GBDTData(
            X=X, y=train.y, weight=train.weight, n_real=train.n_real,
            feature_names=self._names_from_fmap(gmap),
        )

    def load(self) -> Tuple[GBDTData, Optional[GBDTData]]:
        import jax

        from ..obs import inc as obs_inc, span as obs_span

        p = self.params
        with obs_span("ingest.parse", split="train", path="gbdt"):
            train = self._parse(p.data.train_paths, p.data.train_max_error_tol)
        obs_inc("ingest.rows", train.n_real)
        # raise on ALL ranks (a single-rank raise would leave the peers
        # blocked inside the next allgather collective)
        from ..parallel.collectives import host_allgather_objects

        counts = host_allgather_objects(train.n_real)
        if min(counts) == 0:
            raise ValueError(
                f"process(es) {[i for i, c in enumerate(counts) if c == 0]} got "
                f"an empty training shard ({p.data.unassigned_mode} over "
                f"{len(p.data.train_paths)} path(s)) — use lines_avg sharding "
                "or fewer processes"
            )
        train = self._merge_fmap_multihost(train)
        fill = self.compute_missing_fill(train.X)
        train.missing_fill = fill
        _apply_fill(train.X, fill)
        test = None
        if p.data.test_paths:
            with obs_span("ingest.parse", split="test", path="gbdt"):
                test = self._parse(
                    p.data.test_paths, p.data.test_max_error_tol,
                    fmap=self._fmap, frozen=True,
                )
            obs_inc("ingest.rows", test.n_real)
            test.missing_fill = fill
            _apply_fill(test.X, fill)
        return train, test


def column_stats(
    X: np.ndarray, chunk: int = 1 << 20
) -> Tuple[np.ndarray, np.ndarray]:
    """(nonzero counts, mins) per column of a dense (n, F) matrix, chunked
    over rows so the boolean nonzero pattern never materializes whole —
    the host-side feed for EFB candidate selection (gbdt.binning
    .efb_candidates; the device path reduces on the accelerator instead)."""
    n, F = X.shape
    nnz = np.zeros((F,), np.int64)
    mins = np.full((F,), np.inf, X.dtype)
    for i in range(0, n, chunk):
        blk = X[i : i + chunk]
        nnz += np.count_nonzero(blk, axis=0)
        np.minimum(mins, blk.min(axis=0), out=mins)
    return nnz, mins


def _apply_fill(X: np.ndarray, fill: np.ndarray) -> None:
    """In-place NaN -> per-feature fill (reference: FillMissingValue.java:49)."""
    nan_rows, nan_cols = np.where(np.isnan(X))
    X[nan_rows, nan_cols] = fill[nan_cols]
