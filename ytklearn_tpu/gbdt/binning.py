"""Feature binning — samplers + value->bin conversion.

Rebuild of reference feature/gbdt/approximate/* (SampleManager + 5 samplers)
and data/gbdt/FeatureApprData.java:179 (convertFeaVal2ApprFeaIndex).

Bins are *representative values*: each feature's sampler emits a set of
candidate values, sorted; a raw value maps to the NEAREST representative
(last <=, then pulled down if closer to the previous one — exactly the
reference's BinarySearch.findLastEqualOrUpper + midpoint adjustment).
Split "slot s" means bins <= s go left; the dumped split value is the
mean/median of the two adjacent representatives (feature/gbdt/FeatureSplitType.java).

Samplers (feature/gbdt/approximate/sampler/*):
  sample_by_quantile   weighted quantiles at max_cnt even ranks, weights
                       raised to alpha (SampleByQuantile.java:105); the
                       reference's distributed GK sketch becomes an exact
                       sort-based weighted quantile on device/host
  sample_by_cnt        distinct values; if too many, values at max_cnt
                       uniformly-sampled rows
  sample_by_rate       distinct values of a Bernoulli(sample_rate) row sample
                       (if distinct count > min_cnt)
  sample_by_precision  values rounded to dot_precision decimals after
                       optional log / min-max normalization, then inverted
  no_sample            all distinct values (exact greedy)
"""

from __future__ import annotations

import fnmatch
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import knobs
from ..config.params import ApproximateSpec, GBDTParams

# Columns longer than this stream through the weighted GK sketch instead
# of the full-sort quantile path (sort+cumsum temporaries cost ~4x the
# column; the sketch is O(b log(n/chunk))). Override: YTK_SKETCH_ROWS.
SKETCH_ROWS = knobs.get_int("YTK_SKETCH_ROWS")


@dataclass
class FeatureBins:
    """Per-feature sorted representative values, padded to a common width.

    values[f, :counts[f]] are real; padding slots repeat the last value so
    searchsorted stays monotone."""

    values: np.ndarray  # (F, B) f32 sorted per row
    counts: np.ndarray  # (F,) int32
    max_bins: int
    # exact[f]: the sampler kept every distinct value (all-distinct path);
    # None when unknown (device-built bins don't track it)
    exact: Optional[np.ndarray] = None

    def split_value(
        self, fid: int, lo: int, hi: Optional[int] = None,
        split_type: str = "mean",
    ) -> float:
        """Split cond for 'bins <= lo go left', where [lo, hi] is the split
        interval: last nonempty slot strictly before the boundary, and the
        boundary slot itself (reference: GBDTOptimizer.convertModel:669 +
        FeatureSplitType mean/median). hi=None means the adjacent interval
        [lo, lo+1]. The ONE split-value conversion — the trainer's tree
        conversion and any tooling must route through here (r3 Weak #3)."""
        v = self.values[fid]
        cnt = int(self.counts[fid])
        if hi is None:
            hi = lo + 1
        hi = min(hi, cnt - 1)  # boundary slots are nonempty, so < cnt; clamp
        if split_type == "median":
            s = lo + hi
            if s % 2 == 0:
                return float(v[s // 2])
            return 0.5 * (float(v[(s - 1) // 2]) + float(v[(s + 1) // 2]))
        return 0.5 * (float(v[lo]) + float(v[hi]))


def _sample_feature(
    col: np.ndarray, weight: np.ndarray, spec: ApproximateSpec, rng: np.random.RandomState
) -> Tuple[np.ndarray, bool]:
    """-> (sorted candidate values, kept-all-distinct flag)."""
    kind = spec.type
    if kind == "no_sample":
        return np.unique(col), True
    if kind == "sample_by_cnt":
        vals = np.unique(col)
        if len(vals) > spec.max_cnt:
            picks = rng.choice(len(col), size=spec.max_cnt, replace=False)
            return np.unique(col[picks]), False
        return vals, True
    if kind == "sample_by_rate":
        vals = np.unique(col)
        if len(vals) > spec.min_cnt:
            mask = rng.rand(len(col)) <= spec.sample_rate
            if mask.any():
                return np.unique(col[mask]), False
        return vals, True
    if kind == "sample_by_precision":
        x = col.astype(np.float64)
        lo = hi = None
        if spec.use_min_max:
            lo, hi = float(x.min()), float(x.max())
            x = (x - lo) / (hi - lo) if hi > lo else np.zeros_like(x)
        if spec.use_log:
            x = np.sign(x) * np.log1p(np.abs(x))
        r = np.unique(np.round(x, spec.dot_precision))
        # invert the normalization chain (reference: Sampler.reverse)
        if spec.use_log:
            r = np.sign(r) * (np.expm1(np.abs(r)))
        if spec.use_min_max and lo is not None and hi > lo:
            r = r * (hi - lo) + lo
        return np.unique(r.astype(np.float32)), False
    if kind == "sample_by_quantile":
        w = (
            np.power(np.maximum(weight, 0.0), spec.alpha)
            if spec.use_sample_weight
            else np.ones_like(col)
        )
        if len(col) > SKETCH_ROWS:
            # memory-bounded streaming path (reference: the GK sketch of
            # WeightApproximateQuantile.java behind SampleByQuantile) —
            # the full-sort temporaries below cost ~4x the column; the
            # sketch holds O(b log(n/chunk)) entries instead
            from .quantile_sketch import WeightedQuantileSketch

            sk = WeightedQuantileSketch(b=max(4 * spec.max_cnt, 256))
            cs = 1 << 22
            for i in range(0, len(col), cs):
                sk.push(col[i : i + cs], w[i : i + cs])
            # low-cardinality giant column: if no prune ever dropped
            # entries, the summary is a perfect distinct-value table —
            # keep the exact flag the sub-SKETCH_ROWS path would have set
            # (it buys the multihost merge the cheap exact-union path)
            summ = sk.summary()
            if sk.is_exact and summ.size <= spec.max_cnt:
                return summ.value.astype(np.float32), True
            return summ.query_values(spec.max_cnt), False
        vals = np.unique(col)
        if len(vals) <= spec.max_cnt:
            return vals, True
        order = np.argsort(col, kind="stable")
        sv, sw = col[order], w[order]
        cw = np.cumsum(sw)
        total = cw[-1]
        # max_cnt evenly spaced quantile ranks (the GK query points)
        ranks = (np.arange(1, spec.max_cnt + 1) / spec.max_cnt) * total
        pos = np.searchsorted(cw, ranks, side="left").clip(0, len(sv) - 1)
        return np.unique(sv[pos]), False
    raise ValueError(f"unknown sampler type: {kind!r}")


def _spec_for(fid: int, name: str, specs: Sequence[ApproximateSpec]) -> ApproximateSpec:
    """Column matching: `cols` is 'default' or a comma list of names/globs
    (reference: SampleManager sampler assignment)."""
    default = None
    for s in specs:
        if s.cols == "default":
            default = s
            continue
        for pat in str(s.cols).split(","):
            pat = pat.strip()
            if pat and (pat == name or fnmatch.fnmatch(name, pat)):
                return s
    return default or specs[0]


def build_bins(
    X: np.ndarray,
    weight: np.ndarray,
    params: GBDTParams,
    feature_names: Optional[Sequence[str]] = None,
    seed: int = 20170425,
) -> FeatureBins:
    """Run the configured sampler per feature; pad to a common bin width."""
    rng = np.random.RandomState(seed)
    F = X.shape[1]
    names = feature_names or [str(i) for i in range(F)]
    per_feature: List[np.ndarray] = []
    exact = np.zeros((F,), bool)
    for f in range(F):
        spec = _spec_for(f, names[f], params.approximate)
        vals, exact[f] = _sample_feature(X[:, f], weight, spec, rng)
        vals = vals.astype(np.float32)
        if len(vals) == 0:
            vals = np.zeros((1,), np.float32)
        per_feature.append(np.sort(vals))
    out = _to_feature_bins(per_feature)
    out.exact = exact
    return out


def _to_feature_bins(per_feature: List[np.ndarray]) -> "FeatureBins":
    """Pad per-feature sorted candidate lists to a common width (padding
    repeats the last value so searchsorted stays monotone)."""
    max_bins = max(len(v) for v in per_feature)
    F = len(per_feature)
    values = np.empty((F, max_bins), np.float32)
    counts = np.empty((F,), np.int32)
    for f, v in enumerate(per_feature):
        values[f, : len(v)] = v
        values[f, len(v):] = v[-1]
        counts[f] = len(v)
    return FeatureBins(values=values, counts=counts, max_bins=max_bins)


def merge_quantile_candidates(
    values_list: List[np.ndarray], mass_list: List[float], max_cnt: int
) -> np.ndarray:
    """Merge per-process quantile candidate sets into global candidates.

    Each process's candidates are (approximately) equal-mass quantile points
    of its local distribution, so the merged multiset with per-point mass
    total_i/len(values_i) is a compressed sketch of the global distribution;
    querying max_cnt even ranks of it is the TPU-host equivalent of the
    reference's GK summary merge + query (SampleManager.java:129-143,
    WeightApproximateQuantile.merge:476)."""
    vals = np.concatenate([np.asarray(v, np.float64) for v in values_list])
    mass = np.concatenate(
        [
            np.full(len(v), m / max(len(v), 1), np.float64)
            for v, m in zip(values_list, mass_list)
        ]
    )
    order = np.argsort(vals, kind="stable")
    sv, sm = vals[order], mass[order]
    cw = np.cumsum(sm)
    total = cw[-1]
    # midpoint rule: candidate k summarizes the local mass interval ending at
    # it, so its representative rank is the interval's center — without the
    # -mass/2 shift every merged quantile reads ~half a rank high
    ranks = (np.arange(1, max_cnt + 1) / max_cnt) * total
    pos = np.searchsorted(cw - 0.5 * sm, ranks, side="left").clip(0, len(sv) - 1)
    return np.unique(sv[pos].astype(np.float32))


def merge_bins_multihost(
    local: "FeatureBins",
    local_exact: np.ndarray,
    local_mass: np.ndarray,
    max_cnt_arr: np.ndarray,
    discrete: np.ndarray,
    local_summaries: Optional[Dict[int, "object"]] = None,
) -> "FeatureBins":
    """Cross-process merge of per-feature bin candidates.

    discrete[f]: non-quantile sampler — merges by uncapped set union (the
    allreduceMapSetUnion path of SampleManager.java:128; no_sample keeps
    exact-greedy semantics across hosts). Quantile features stay exact as a
    union while every process kept all distinct values AND the union fits
    that feature's max_cnt. Otherwise, when every process supplies a GK
    summary for the feature (local_summaries), the summaries merge with
    bounded rank error (the reference's Kryo'd Summary allreduce,
    SampleManager.java:129-143 + WeightApproximateQuantile.merge:476);
    the candidate-union approximation remains only as a fallback."""
    from ..parallel.collectives import host_allgather_objects

    payload = (
        [local.values[f, : local.counts[f]] for f in range(len(local.counts))],
        local_exact,
        local_mass,
        local_summaries or {},
    )
    gathered = host_allgather_objects(payload)
    if len(gathered) == 1:
        return local
    from .quantile_sketch import merge_summaries

    F = len(local.counts)
    per_feature: List[np.ndarray] = []
    for f in range(F):
        sets = [g[0][f] for g in gathered]
        exacts = [bool(g[1][f]) for g in gathered]
        masses = [float(g[2][f]) for g in gathered]
        union = np.unique(np.concatenate(sets))
        if discrete[f] or (all(exacts) and len(union) <= int(max_cnt_arr[f])):
            per_feature.append(union.astype(np.float32))
        elif all(f in g[3] for g in gathered):
            merged = gathered[0][3][f]
            for g in gathered[1:]:
                merged = merge_summaries(merged, g[3][f])
            per_feature.append(merged.query_values(int(max_cnt_arr[f])))
        else:
            per_feature.append(
                merge_quantile_candidates(sets, masses, int(max_cnt_arr[f]))
            )
    return _to_feature_bins(per_feature)


def build_bins_global(
    X: np.ndarray,
    weight: np.ndarray,
    params: GBDTParams,
    feature_names: Optional[Sequence[str]] = None,
    seed: int = 20170425,
) -> FeatureBins:
    """build_bins + multi-host candidate merge (no-op single-process)."""
    import jax

    local = build_bins(X, weight, params, feature_names, seed)
    if jax.process_count() == 1:
        return local
    from .quantile_sketch import Summary, WeightedQuantileSketch, prune_summary

    F = X.shape[1]
    names = feature_names or [str(i) for i in range(F)]
    exact = np.zeros((F,), bool)
    discrete = np.zeros((F,), bool)
    mass = np.zeros((F,), np.float64)
    max_cnt_arr = np.zeros((F,), np.int64)
    summaries: Dict[int, Summary] = {}
    for f in range(F):
        spec = _spec_for(f, names[f], params.approximate)
        max_cnt_arr[f] = spec.max_cnt
        if spec.type == "sample_by_quantile":
            # exact iff the sampler took the all-distinct path (tracked by
            # build_bins; candidate count alone misclassifies dedup'd picks)
            exact[f] = bool(local.exact[f]) if local.exact is not None else False
            w = (
                np.power(np.maximum(weight, 0.0), spec.alpha)
                if spec.use_sample_weight
                else np.ones_like(weight)
            )
            mass[f] = float(np.sum(w))
            # local GK summary for the bounded-error cross-process merge
            # (pruned to 4*max_cnt: rank error <= B/(8*max_cnt), an eighth
            # of the candidate spacing). Giant columns build one even when
            # locally exact — another host's shard may be inexact, and
            # without a summary on every host the merge would degrade to
            # the unbounded candidate-union fallback.
            b = max(4 * int(spec.max_cnt), 256)
            col = X[:, f]
            if len(col) > SKETCH_ROWS:
                sk = WeightedQuantileSketch(b=b)
                cs = 1 << 22
                for i in range(0, len(col), cs):
                    sk.push(col[i : i + cs], w[i : i + cs])
                summaries[f] = prune_summary(sk.summary(), b)
            else:
                # unconditional: a locally-exact shard still needs a summary
                # — another host's shard of the same column may be inexact,
                # and the bounded-error merge requires summaries on EVERY
                # host (exact Summaries are small and exact by construction)
                summaries[f] = prune_summary(Summary.from_exact(col, w), b)
        else:
            discrete[f] = True  # discrete samplers merge by set union
            exact[f] = True
            mass[f] = float(len(X))
    return merge_bins_multihost(
        local, exact, mass, max_cnt_arr, discrete, summaries
    )


# ---------------------------------------------------------------------------
# Serve-side bin-edge export: the trainer dumps each feature's sorted
# representative values next to the model (`<data_path>.bins.json`), so the
# serving layer can bin request rows ONCE per batch with the exact same
# nearest-representative rule the training matrix used (bin_matrix) and
# traverse the ensemble on small integer bin indices instead of float
# compares (serve/kernels.py, docs/serving.md "Precision rungs"). The
# sidecar rides the continual shadow/promote/archive moves (driver._roots)
# and the serving fingerprint (registry._sidecar_paths).
# ---------------------------------------------------------------------------

BIN_EDGES_SCHEMA = "ytk-bin-edges"


def bin_edges_path(data_path: str) -> str:
    return data_path + ".bins.json"


def model_text_digest(text: str) -> str:
    """sha256 of the dumped model text — pairs a bin-edges sidecar with
    the EXACT ensemble it was trained with (splits are midpoints, not
    edge members, so no per-value check can detect a mismatched grid)."""
    import hashlib

    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def dump_bin_edges(fs, path: str, names: Sequence[str], bins: FeatureBins,
                   split_type: str = "mean",
                   model_digest: Optional[str] = None) -> None:
    """Atomically dump per-feature representative values, name-keyed (the
    dumped trees are name-keyed too). Written BEFORE the model file so a
    fingerprint-watch reload never pairs a new ensemble with stale edges;
    `model_digest` (sha256 of the model text about to land) lets serving
    verify the pairing even across a crash between the two writes."""
    import json

    payload = {
        "schema": BIN_EDGES_SCHEMA,
        "version": 1,
        "split_type": split_type,
        "features": {
            str(names[f]): [
                float(v) for v in bins.values[f, : int(bins.counts[f])]
            ]
            for f in range(len(bins.counts))
        },
    }
    if model_digest is not None:
        payload["model_digest"] = model_digest
    with fs.atomic_open(path) as f:
        json.dump(payload, f)


def load_bin_edges(
    fs, path: str, model_digest: Optional[str] = None
) -> Optional[Dict[str, np.ndarray]]:
    """{feature name: sorted (cnt,) f64 edges} or None when the sidecar is
    missing/unreadable (serving then derives thresholds from the ensemble
    itself — serve/kernels.build_bin_table). When the caller passes the
    served model's text digest, a sidecar carrying a DIFFERENT digest is
    rejected — the new-edges/old-model window a crash between the trainer's
    two writes can leave behind would otherwise misroute interior rows."""
    import json
    import logging

    if not fs.exists(path):
        return None
    try:
        with fs.open(path) as f:
            payload = json.load(f)
        if payload.get("schema") != BIN_EDGES_SCHEMA:
            raise ValueError(f"not a bin-edges sidecar: {path}")
        want = payload.get("model_digest")
        if model_digest is not None and want is not None \
                and want != model_digest:
            logging.getLogger(__name__).warning(
                "bin-edges sidecar %s was dumped for a different model "
                "(digest mismatch); serving falls back to ensemble-derived "
                "thresholds", path,
            )
            return None
        return {
            str(name): np.asarray(vals, np.float64)
            for name, vals in payload["features"].items()
        }
    except (OSError, ValueError, KeyError, TypeError) as e:
        logging.getLogger(__name__).warning(
            "bin-edges sidecar %s unreadable (%s: %s); serving falls back "
            "to ensemble-derived thresholds", path, type(e).__name__, e,
        )
        return None


# ---------------------------------------------------------------------------
# Exclusive feature bundling (EFB, LightGBM §5): merge mutually-exclusive
# sparse columns into one offset-binned column at binning time, shrinking
# the bin matrix's feature axis before it ever reaches HBM.
# ---------------------------------------------------------------------------
#
# Bundle-column bin layout: bin 0 is the shared DEFAULT (every member at
# its zero value); member j's NONZERO bins 1..B_j-1 land at
# [lo_j, lo_j + B_j - 2] with lo offsets accumulating member widths.
# Candidates are restricted to columns with min >= 0 whose lowest
# representative is exactly 0, so "original bin 0" == "value 0" and the
# encoding is invertible. Conflict rows (two members nonzero) keep the
# higher-offset member's value — deterministic, and identical for train
# and test transforms. With conflict budget 0 the transform is lossless:
# the engine's range-corrected split enumeration (engine.split_kernel
# `ranges`) recovers exactly the per-original-feature splits, and
# `unbundle_split` maps a chosen (bundle, slot) back to the original
# feature id + bin interval, so dumped models and serving are unchanged.

#: candidate pre-filter: a column this dense can never bundle usefully
#: (and keeps the pairwise conflict matmul off dense features entirely)
EFB_MAX_DENSITY = 0.5
#: skip EFB planning past this many candidate columns (the conflict
#: matrix is O(C^2) memory)
EFB_MAX_CANDIDATES = 4096


@dataclass
class BundlePlan:
    """Column plan for an EFB-bundled bin matrix.

    Column layout: the unbundled original features first (in original
    order, `col_fid[c]` = original fid), then one column per bundle.
    `member_lo[b][k]`/`member_hi[b][k]` give member k's nonzero slot
    range inside bundle b's column."""

    n_features: int  # original F
    col_fid: np.ndarray  # (U,) i32: unbundled column -> original fid
    bundles: List[List[int]]  # each: >= 2 original fids, offset order
    member_lo: List[List[int]]
    member_hi: List[List[int]]

    @property
    def n_cols(self) -> int:
        return len(self.col_fid) + len(self.bundles)

    @property
    def n_bundled_features(self) -> int:
        return sum(len(m) for m in self.bundles)

    def bundle_width(self, b: int) -> int:
        return self.member_hi[b][-1] + 1

    def range_tables(self, B: int, F_pad: Optional[int] = None):
        """(range_lo, range_hi) (F_pad, B) int32 for engine.split_kernel:
        plain columns (and padding) get [0, B-1]; a bundle column's slot s
        gets the member range containing s. Slots outside any member
        range (bin 0, tail padding) keep [0, B-1] — they are never valid
        split boundaries (bin 0 has no predecessor; tail slots are
        empty), so the value only has to be harmless."""
        F_pad = F_pad or self.n_cols
        rlo = np.zeros((F_pad, B), np.int32)
        rhi = np.full((F_pad, B), B - 1, np.int32)
        U = len(self.col_fid)
        for b in range(len(self.bundles)):
            for lo, hi in zip(self.member_lo[b], self.member_hi[b]):
                rlo[U + b, lo : hi + 1] = lo
                rhi[U + b, lo : hi + 1] = hi
        return rlo, rhi

    def member_of_slot(self, col: int, slot: int):
        """(original fid, member lo) of the member whose nonzero range
        contains `slot` in bundle column `col`."""
        b = col - len(self.col_fid)
        for fid, lo, hi in zip(
            self.bundles[b], self.member_lo[b], self.member_hi[b]
        ):
            if lo <= slot <= hi:
                return fid, lo
        raise ValueError(
            f"slot {slot} of bundle column {col} is in no member range"
        )

    def unbundle_split(self, col: int, slot_l: int, slot_r: int):
        """Map a chosen split (column, boundary interval [slot_l, slot_r])
        back to (original fid, original slot_l, original slot_r).

        Plain columns pass through. For a bundle, the boundary slot_r
        identifies the member; bundle slot s maps to original bin
        s - lo + 1 (member nonzero bins start at original bin 1), and a
        slot_l below the member's range (the lo-1 default encoding from
        split_kernel, or bin 0) maps to the original zero bin 0."""
        U = len(self.col_fid)
        if col < U:
            return int(self.col_fid[col]), slot_l, slot_r
        fid, lo = self.member_of_slot(col, slot_r)
        orig_r = slot_r - lo + 1
        orig_l = 0 if slot_l < lo else slot_l - lo + 1
        return fid, orig_l, orig_r

    def summary(self) -> str:
        sizes = ",".join(str(len(m)) for m in self.bundles)
        return (
            f"{self.n_bundled_features} of {self.n_features} features in "
            f"{len(self.bundles)} bundle(s) [{sizes}]: "
            f"{self.n_features} -> {self.n_cols} columns"
        )


def efb_candidates(
    nnz: np.ndarray,
    mins: np.ndarray,
    bins: FeatureBins,
    n_rows: int,
    max_density: float = EFB_MAX_DENSITY,
) -> np.ndarray:
    """Original fids eligible for bundling: sparse (nnz fraction under the
    density cap), non-negative, at least one nonzero bin, and binned so
    that value 0 IS bin 0 (lowest representative exactly 0 — the offset
    encoding's invertibility condition)."""
    F = len(nnz)
    out = []
    for f in range(F):
        cnt = int(bins.counts[f])
        if (
            cnt >= 2
            and nnz[f] > 0
            and nnz[f] <= max_density * n_rows
            and mins[f] >= 0
            and float(bins.values[f, 0]) == 0.0
        ):
            out.append(f)
    return np.asarray(out, np.int64)


def plan_bundles(
    cand: np.ndarray,
    conflicts: np.ndarray,
    bin_counts: np.ndarray,
    F: int,
    max_conflict: int,
    max_width: int,
) -> Optional[BundlePlan]:
    """Greedy graph-coloring over the candidate conflict counts
    (LightGBM Alg. 3): visit candidates by nonzero count (conflict-matrix
    diagonal) descending, place each into the first bundle whose total
    conflict stays within `max_conflict` and whose width (1 shared
    default bin + each member's nonzero bins) fits `max_width`. Bundles
    that end up with one member stay unbundled. Returns None when nothing
    bundles (the caller's no-op path)."""
    if len(cand) < 2:
        return None
    nnz = np.diag(conflicts)
    order = np.argsort(-nnz, kind="stable")  # dense-first, fid tie-break
    groups: List[List[int]] = []  # candidate-local indices
    g_conf: List[int] = []
    g_width: List[int] = []
    for ci in order:
        w = int(bin_counts[cand[ci]]) - 1  # nonzero bins
        placed = False
        for gi, members in enumerate(groups):
            add = int(sum(conflicts[ci, m] for m in members))
            if g_conf[gi] + add <= max_conflict and g_width[gi] + w <= max_width:
                members.append(int(ci))
                g_conf[gi] += add
                g_width[gi] += w
                placed = True
                break
        if not placed:
            groups.append([int(ci)])
            g_conf.append(0)
            g_width.append(1 + w)
    bundles = [
        sorted(int(cand[m]) for m in members)
        for members in groups
        if len(members) >= 2
    ]
    if not bundles:
        return None
    bundles.sort()  # deterministic column order by smallest member fid
    bundled = set()
    for members in bundles:
        bundled.update(members)
    col_fid = np.asarray(
        [f for f in range(F) if f not in bundled], np.int32
    )
    member_lo: List[List[int]] = []
    member_hi: List[List[int]] = []
    for members in bundles:
        lo_list, hi_list = [], []
        off = 1  # bin 0 = shared default
        for fid in members:
            w = int(bin_counts[fid]) - 1
            lo_list.append(off)
            hi_list.append(off + w - 1)
            off += w
        member_lo.append(lo_list)
        member_hi.append(hi_list)
    return BundlePlan(
        n_features=F,
        col_fid=col_fid,
        bundles=bundles,
        member_lo=member_lo,
        member_hi=member_hi,
    )


def build_bundle_plan(
    X_t,
    bins: FeatureBins,
    max_conflict: int,
    max_width: int,
    nnz: Optional[np.ndarray] = None,
    mins: Optional[np.ndarray] = None,
) -> Optional[BundlePlan]:
    """Plan EFB bundles from a transposed (F, n) matrix (device jnp array
    or host numpy — the nonzero-pattern reductions and the candidate
    conflict matmul run wherever the matrix lives). Host callers can pass
    precomputed (nnz, mins) from gbdt.data.column_stats to keep the
    full-matrix boolean pattern from materializing. Returns None when
    nothing bundles."""
    import jax.numpy as jnp

    is_dev = not isinstance(X_t, np.ndarray)
    xp = jnp if is_dev else np
    F, n = X_t.shape
    if nnz is None:
        nnz = np.asarray(xp.sum(X_t != 0, axis=1)).astype(np.int64)
    if mins is None:
        mins = np.asarray(xp.min(X_t, axis=1))
    cand = efb_candidates(nnz, mins, bins, n)
    if len(cand) < 2:
        return None
    C = len(cand)
    if C > EFB_MAX_CANDIDATES:
        return None  # O(C^2) conflict matrix would blow memory; skip
    # exact pairwise co-nonzero counts, chunked over rows so the (C, chunk)
    # f32 nonzero pattern stays within a fixed memory budget on either
    # backend (budget 0 MUST see every conflict — a sampled estimate could
    # silently bundle conflicting features)
    Xc = X_t[xp.asarray(cand)] if is_dev else X_t[np.asarray(cand)]
    # chunk cap 2^22 keeps per-chunk counts exactly representable in f32
    chunk = min(1 << 22, max(8192, (1 << 26) // max(C, 1)))
    conflicts = np.zeros((C, C), np.float64)
    for i in range(0, n, chunk):
        Zc = (Xc[:, i : i + chunk] != 0).astype(xp.float32)
        conflicts += np.asarray(Zc @ Zc.T, np.float64)
    conflicts = np.rint(conflicts).astype(np.int64)  # [i,j] = co-nonzero rows
    return plan_bundles(
        cand, conflicts, bins.counts, F, max_conflict, max_width
    )


def bundle_bin_matrix_t(bins_t, plan: BundlePlan):
    """Apply a BundlePlan to a transposed (F, n) BIN matrix -> (n_cols, n).

    Works on device (jnp) and host (np) arrays alike. Bundle encoding per
    row: member j nonzero (orig bin > 0) -> lo_j + bin_j - 1, all-default
    -> 0; the elementwise max picks the highest-offset member on conflict
    rows (the budgeted-conflict winner rule)."""
    import jax.numpy as jnp

    xp = np if isinstance(bins_t, np.ndarray) else jnp
    parts = [bins_t[np.asarray(plan.col_fid)]] if len(plan.col_fid) else []
    for b, members in enumerate(plan.bundles):
        acc = None
        for fid, lo in zip(members, plan.member_lo[b]):
            bf = bins_t[fid].astype(xp.int32)
            enc = xp.where(bf > 0, lo + bf - 1, 0)
            acc = enc if acc is None else xp.maximum(acc, enc)
        parts.append(acc[None].astype(bins_t.dtype))
    return xp.concatenate(parts, axis=0)


def quantile_bins_device(
    X_t,
    weight: Optional[np.ndarray],
    spec: ApproximateSpec,
) -> Tuple[np.ndarray, np.ndarray]:
    """sample_by_quantile on device: one sort per feature on the TPU instead
    of the host argsort/cumsum path of `_sample_feature` (which costs ~4s per
    feature at 10M rows). Same selection rule: candidates at max_cnt evenly
    spaced weighted ranks of the sorted column; features whose distinct count
    fits max_cnt keep every distinct value (reference:
    SampleByQuantile.java:60-105 — sketch query at even ranks).

    X_t: (F, n) device array. Returns (candidates (F, max_cnt) f32 with
    possible duplicates, distinct_counts (F,) int) on host; the caller
    dedupes/finalizes per feature.
    """
    import jax
    import jax.numpy as jnp

    F, n = X_t.shape
    mc = spec.max_cnt
    uniform = weight is None or (
        spec.alpha == 0.0
        or not spec.use_sample_weight
        or (np.min(weight) == np.max(weight))
    )
    ranks = jnp.asarray(np.arange(1, mc + 1) / mc, jnp.float32)
    # uniform weights: cw[i] = i+1 -> pos = ceil(rank*n) - 1, computed in
    # float64 on host (f32 loses integer precision above ~16M rows)
    pos_uniform = jnp.asarray(
        np.clip(np.ceil(np.arange(1, mc + 1) / mc * n).astype(np.int64) - 1, 0, n - 1),
        jnp.int32,
    )

    @jax.jit
    def run_uniform(X_t):
        sv = jnp.sort(X_t, axis=1)
        distinct = jnp.sum(sv[:, 1:] != sv[:, :-1], axis=1) + 1
        return sv[:, pos_uniform], distinct

    @jax.jit
    def run_weighted(X_t, w):
        ops = jax.vmap(lambda col: jax.lax.sort((col, w), num_keys=1))(X_t)
        sv, sw = ops
        cw = jnp.cumsum(sw.astype(jnp.float32), axis=1)
        total = cw[:, -1:]
        tgt = ranks[None, :] * total  # (F, mc)
        # first i with cw[i] >= tgt  == count of cw[i] < tgt
        pos = jax.vmap(lambda c, t: jnp.searchsorted(c, t, side="left"))(cw, tgt)
        pos = jnp.clip(pos, 0, n - 1)
        cand = jnp.take_along_axis(sv, pos, axis=1)
        distinct = jnp.sum(sv[:, 1:] != sv[:, :-1], axis=1) + 1
        return cand, distinct

    if uniform:
        cand, distinct = run_uniform(X_t)
    else:
        w_pow = jnp.asarray(
            np.power(np.maximum(weight, 0.0), spec.alpha).astype(np.float32)
        )
        cand, distinct = run_weighted(X_t, w_pow)
    return np.asarray(cand), np.asarray(distinct)


def build_bins_maybe_device(
    X: np.ndarray,
    X_t_dev,
    weight: np.ndarray,
    params: GBDTParams,
    feature_names: Optional[Sequence[str]] = None,
    seed: int = 20170425,
) -> FeatureBins:
    """build_bins, offloading the quantile sampler to the device when every
    feature uses one sample_by_quantile spec (the common/acceptance config).
    Falls back to the host path per feature otherwise, and for features
    whose distinct count fits max_cnt (those keep all distinct values)."""
    specs = params.approximate
    single_quantile = (
        X_t_dev is not None
        and len(specs) == 1
        and specs[0].type == "sample_by_quantile"
    )
    if not single_quantile:
        return build_bins(X, weight, params, feature_names, seed)
    spec = specs[0]
    cand, distinct = quantile_bins_device(X_t_dev, weight, spec)
    F = X.shape[1]
    per_feature: List[np.ndarray] = []
    for f in range(F):
        if distinct[f] <= spec.max_cnt:
            vals = np.unique(X[:, f])  # small-cardinality feature: keep all
        else:
            vals = np.unique(cand[f])
        if len(vals) == 0:
            vals = np.zeros((1,), np.float32)
        per_feature.append(np.sort(vals).astype(np.float32))
    return _to_feature_bins(per_feature)


def bin_matrix_device(X_t_dev, bins: FeatureBins):
    """Device-side value->bin conversion into the transposed (F, n) layout
    the growth engine wants (same rule as `bin_matrix`; the compare-count
    searchsorted fuses on TPU instead of a 28-feature host loop)."""
    import jax
    import jax.numpy as jnp

    values = jnp.asarray(bins.values)  # (F, B)
    counts = jnp.asarray(bins.counts)  # (F,)

    @jax.jit
    def run(X_t):
        def per_feature(col, v, cnt):
            last = v[cnt - 1]
            # first index with v[i] >= col == count of v[i] < col
            i = jnp.sum(v[None, :] < col[:, None], axis=1).astype(jnp.int32)
            # NaN (unfilled missing) -> last bin, matching host np.searchsorted
            # which sorts NaN above everything
            over = (col > last) | jnp.isnan(col)
            i = jnp.clip(i, 0, cnt - 1)
            prev = v[jnp.maximum(i - 1, 0)]
            mids = 0.5 * (prev + v[i])
            i = jnp.where((i >= 1) & (col < mids) & ~over, i - 1, i)
            return jnp.where(over, cnt - 1, i)

        return jax.vmap(per_feature)(X_t, values, counts)

    return run(X_t_dev)


def bin_matrix(X: np.ndarray, bins: FeatureBins) -> np.ndarray:
    """Raw values -> nearest-representative bin ids, vectorized
    (reference: FeatureApprData.convertFeaVal2ApprFeaIndex:179).

    rule: i = first index with values[i] >= v (v > max -> last bin);
          if i >= 1 and v < midpoint(values[i-1], values[i]) -> i-1
    i.e. round to the nearest representative, ties to the upper one."""
    n, F = X.shape
    out = np.empty((n, F), np.int32)
    for f in range(F):
        cnt = int(bins.counts[f])
        v = bins.values[f, :cnt]
        if cnt == 1:
            out[:, f] = 0
            continue
        col = X[:, f]
        i = np.searchsorted(v, col, side="left")  # ceil index
        over = col > v[-1]
        i = np.clip(i, 0, cnt - 1)
        mids = 0.5 * (v[np.maximum(i - 1, 0)] + v[i])
        i = np.where((i >= 1) & (col < mids) & ~over, i - 1, i)
        out[:, f] = np.where(over, cnt - 1, i)
    return out
