"""Device-resident GBDT tree growth — the whole tree as ONE XLA program.

Rebuild of reference optimizer/gbdt/DataParallelTreeMaker.java:229-653
(expand queue, histogram build + reduce-scatter, sibling subtraction via
HistogramPool, split enumeration, sample position update) re-architected
for the TPU's cost model: device->host transfers through this machine's
tunnel cost ~115 ms EACH, so the reference's host-driven expand loop
(host pops a queue node, launches a histogram, reads back split stats)
would spend seconds per tree in latency alone. Instead the full growth
loop runs on device inside lax.while_loop; the host enqueues one program
per tree and reads nothing back until training ends.

Growth is organized in WAVES of up to `spec.wave` node expansions:
  1. select expandable frontier nodes — by (depth, node id) for the level
     policy (exactly the reference's level order, including the leaf-
     budget count-off), by descending best-gain for the loss policy
     (wave=1 is exactly the reference's best-first; wave=T>1 relaxes the
     pop granularity to T for throughput — T gain-ordered splits per
     histogram pass instead of one)
  2. record the splits into fixed-size tree arrays, allocate children
  3. route samples: per wave node, one bins_t row slice + compare
     (SamplePositionData.resetPosition:115 without the re-sort)
  4. histogram the SMALLER child of each split via the Pallas one-hot
     matmul kernel; derive the sibling by pool subtraction
     (HistogramPool's trick, data/gbdt/HistogramPool.java)
  5. enumerate best splits for all new children (split_kernel) and
     refresh the frontier arrays.

All arrays are fixed-shape: tree fields are (max_nodes,), the histogram
pool is (max_nodes, F, B, 3), the wave is padded to `spec.wave` with
masked no-op slots (scatter mode="drop").
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .hist import (
    BMG_DEFAULT,
    compact_indices,
    hist_wave,
    hist_wave_gather,
    hist_wave_q,
)
from .route import route_wave

BIG32 = np.int32(2**31 - 1)


def wave_log_rows(max_nodes: int) -> int:
    """Rows of the per-tree wave log grow() returns (one per histogram
    pass: root + slow-start ramp + growth waves; trainer buffers and
    ablation scripts size their arrays with this)."""
    return max_nodes + 8


# ---------------------------------------------------------------------------
# Gain / leaf-value formulas (reference: UpdateStrategy.java:64-83)
# ---------------------------------------------------------------------------


def _threshold_l1(g, l1):
    return jnp.where(g > l1, g - l1, jnp.where(g < -l1, g + l1, 0.0))


def make_gain_fns(l1: float, l2: float, min_h: float, max_abs: float):
    def node_value(G, H):
        t = _threshold_l1(G, l1) if l1 > 0 else G
        val = -t / (H + l2)
        if max_abs > 0:
            val = jnp.clip(val, -max_abs, max_abs)
        return jnp.where(H < min_h, 0.0, val)

    def gain(G, H):
        if max_abs <= 0:
            t = _threshold_l1(G, l1) if l1 > 0 else G
            out = t * t / (H + l2)
        else:
            v = node_value(G, H)
            out = -2.0 * (G * v + 0.5 * (H + l2) * v * v + l1 * jnp.abs(v))
        return jnp.where(H < min_h, 0.0, out)

    return gain, node_value


@partial(jax.jit, static_argnames=("cfg",))
def split_kernel(hist, feat_mask, cfg, ranges=None):
    """Best split per node from (N, F, B, 3) histograms.

    Returns per-node: (loss_chg, flat_idx, slot_left, GL, HL, CL, GR, HR, CR)
    (reference: DataParallelTreeMaker.enumerateSplit:598-637 — empty slots
    skipped, split interval [last nonempty, current], child-hessian guards,
    gain vs root; first-max argmax reproduces SplitInfo.needReplace:99's
    lower-slot tie-break).

    ranges: optional (range_lo, range_hi) (F, B) int32 tables for EFB
    bundle columns — range_lo[f, s]/range_hi[f, s] bound the member
    feature's slot range containing s (lo=0/hi=B-1 for plain columns).
    A bundled column concatenates its members' nonzero bins after a
    shared default bin 0, so a boundary s inside member j must count the
    member's DEFAULT rows (node total minus j's nonzero-range sum) on the
    left — LightGBM's per-feature sub-histogram enumeration as a closed
    form over the bundle cumsum: left_j(s) = C(s) + (total - C(hi_j+1)).
    With hi = B-1 the correction is identically zero, so plain columns
    keep the original math bit-for-bit."""
    l1, l2, min_h, max_abs = cfg
    N, F, B, _ = hist.shape
    G, H, C = hist[..., 0], hist[..., 1], hist[..., 2]
    gain, _ = make_gain_fns(l1, l2, min_h, max_abs)

    # exclusive cumsums: stats strictly left of boundary slot j
    CGi = jnp.cumsum(G, axis=-1)  # inclusive
    CHi = jnp.cumsum(H, axis=-1)
    CCi = jnp.cumsum(C, axis=-1)
    GL = CGi - G
    HL = CHi - H
    CL = CCi - C
    Gt = jnp.sum(G, axis=-1, keepdims=True)
    Ht = jnp.sum(H, axis=-1, keepdims=True)
    Ct = jnp.sum(C, axis=-1, keepdims=True)

    nonempty = C > 0
    ne_incl = jnp.cumsum(nonempty.astype(jnp.int32), axis=-1)
    # ytklint: allow(host-sync-in-jit) reason=`ranges is None` is static pytree dispatch (None vs arrays picks the compiled variant), not a traced comparison
    if ranges is None:
        has_prev = (ne_incl - nonempty) > 0
    else:
        rlo, rhi = ranges  # (F, B) i32, broadcast over nodes

        def at_hi(A):  # inclusive cumsum at the member range's end == C(hi+1)
            return jnp.take_along_axis(
                A, jnp.broadcast_to(rhi[None], A.shape), axis=-1
            )

        def at_lo_excl(A_incl, A):  # exclusive cumsum at lo == C(lo)
            ex = A_incl - A
            return jnp.take_along_axis(
                ex, jnp.broadcast_to(rlo[None], ex.shape), axis=-1
            )

        # member-default stats fold into the left side: total - C(hi+1)
        GL = GL + (Gt - at_hi(CGi))
        HL = HL + (Ht - at_hi(CHi))
        CL = CL + (Ct - at_hi(CCi))
        # per-member has_prev: a nonempty slot in [lo, s), or a nonempty
        # member default bin (rows of this member's zero value + every
        # other member's rows)
        ne_in_range = (ne_incl - nonempty) - at_lo_excl(ne_incl, nonempty) > 0
        dflt_cnt = Ct - (at_hi(CCi) - at_lo_excl(CCi, C))
        has_prev = ne_in_range | (dflt_cnt > 0)
    GR, HR, CR = Gt - GL, Ht - HL, Ct - CL
    valid = nonempty & has_prev & (HL >= min_h) & (HR >= min_h)
    valid = valid & feat_mask[None, :, None]

    # node totals: every active sample hits every feature's histogram, so
    # feature 0's bin-sum is the node total
    root_gain = gain(Gt[:, 0:1, 0], Ht[:, 0:1, 0])

    loss_chg = gain(GL, HL) + gain(GR, HR) - root_gain[:, :, None]
    loss_chg = jnp.where(valid, loss_chg, -jnp.inf)

    flat = loss_chg.reshape(N, F * B)
    best = jnp.argmax(flat, axis=-1)  # first max -> lowest (f, slot) tie-break
    best_chg = jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0]

    # last nonempty slot strictly before j (the split interval's left end)
    idxs = jnp.where(nonempty, jnp.arange(B)[None, None, :], -1)
    lastne_incl = jax.lax.cummax(idxs, axis=2)
    lastne = jnp.concatenate(
        [jnp.full((N, F, 1), -1, lastne_incl.dtype), lastne_incl[:, :, :-1]], axis=2
    )
    # ytklint: allow(host-sync-in-jit) reason=`ranges is not None` is static pytree dispatch, not a traced comparison
    if ranges is not None:
        # clamp to the member range: lo-1 encodes "the member default bin"
        # (unbundles to the original feature's zero bin)
        lastne = jnp.maximum(lastne, (rlo - 1)[None])
    lastne = lastne.reshape(N, F * B)
    slot_left = jnp.take_along_axis(lastne, best[:, None], axis=-1)[:, 0]

    def pick(A):
        return jnp.take_along_axis(A.reshape(N, F * B), best[:, None], axis=-1)[:, 0]

    return (
        best_chg,
        best.astype(jnp.int32),
        slot_left.astype(jnp.int32),
        pick(GL),
        pick(HL),
        pick(CL),
        pick(GR),
        pick(HR),
        pick(CR),
    )


# ---------------------------------------------------------------------------
# The growth engine
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GrowSpec:
    """Static shape/config for one tree-growth program."""

    F: int
    B: int
    max_nodes: int  # tree array capacity (2*max_leaves-1 or full level tree)
    wave: int  # node expansions per wave (loss policy: best-first pop width)
    policy: str  # "level" | "loss"
    max_depth: int  # <=0 = unlimited
    max_leaves: int  # <=0 = unlimited
    lr: float
    l1: float
    l2: float
    min_h: float
    max_abs: float
    min_split_loss: float
    min_split_samples: float
    bm: int = 16384  # keep in sync with hist.BM_DEFAULT (trainer padding)
    use_bf16: bool = True
    force_dense: bool = False
    hist_mode: str = "mxu"  # "mxu" (bf16/f32 per use_bf16) | "int8"
    # leaf-partitioned histogram passes: once the frontier's waves need few
    # rows, compact the smaller-child rows into a static budget and
    # histogram only those — wave cost scales with rows-in-wave instead of
    # all n (the LightGBM data-partition idea; reference hot loop
    # HistogramBuilder.java:72-90 likewise iterates node intervals only).
    # `ladder` lists the budget divisors; growth runs as phase-separated
    # while_loops (full scan while waves are big, then each budget, then a
    # full-scan safety tail) because lax.cond around Mosaic kernels is a
    # compile catastrophe on the current toolchain.
    partition: bool = True
    ladder: Tuple[int, ...] = (8, 32)
    # fused compact+gather+histogram kernel (hist.hist_wave_gather): budget
    # rungs at or under `fused_max_rows` skip the XLA (R, F) row gather +
    # transpose entirely — the kernel DMAs each selected row HBM->VMEM and
    # accumulates in place. Rungs above the cap keep the XLA gather (the
    # fused kernel's per-row DMA issue loop is O(R) scalar work, so huge
    # budgets would pay more in descriptors than they save in MACs).
    # `fused_interpret` runs the fused kernel through the Pallas
    # interpreter off-TPU — equivalence tests of the REAL kernel logic on
    # the CPU mesh.
    fused: bool = True
    fused_max_rows: int = 1 << 18
    fused_interpret: bool = False
    bm_g: int = BMG_DEFAULT
    # GOSS (gradient-based one-side sampling, LightGBM §4): per tree,
    # keep the top goss_a fraction of rows by |g| (jax.lax.top_k), sample
    # the remainder at rate goss_b with a deterministic counter-based
    # draw (threefry fold_in on the round/group key — no host RNG), and
    # amplify the sampled rows' g/h by 1/goss_b. The kept set is
    # compacted into a static (a + b(1-a))-sized fit matrix that the
    # whole growth program runs on, so every histogram pass — full-scan
    # phases included — costs O(sampled rows); the full matrix rides
    # along as an aux set purely for final leaf assignment. goss_a >= 1
    # disables (the bit-identical unsampled path). goss_scale is the
    # caller's real-row fraction of the padded sample axis (top_k needs a
    # STATIC k, so the fractions apply to scale*n instead of the padded
    # n — without it a heavily-padded shard would "sample" every real
    # row); the include re-mask guarantees padding is never selected
    # either way.
    goss_a: float = 1.0
    goss_b: float = 0.0
    goss_scale: float = 1.0

    @property
    def depth_cap(self) -> int:
        return self.max_depth if self.max_depth > 0 else self.max_nodes

    @property
    def leaf_cap(self) -> int:
        # unlimited -> whatever fits the fixed arrays (nodes = 2*leaves-1)
        return self.max_leaves if self.max_leaves > 0 else (self.max_nodes + 1) // 2


class TreeArrays(NamedTuple):
    """Fixed-shape device tree (mirrors the host Tree fields that training
    needs; converted to gbdt.tree.Tree after the final fetch)."""

    feat: jnp.ndarray  # (M,) i32, -1 = leaf
    slot: jnp.ndarray  # (M,) i32 routing threshold (last nonempty before split)
    slot_r: jnp.ndarray  # (M,) i32 split interval right end (value conversion)
    left: jnp.ndarray  # (M,) i32
    right: jnp.ndarray  # (M,) i32
    leaf: jnp.ndarray  # (M,) f32 (lr-scaled)
    gain: jnp.ndarray  # (M,) f32
    hess: jnp.ndarray  # (M,) f32
    cnt: jnp.ndarray  # (M,) f32
    depth: jnp.ndarray  # (M,) i32
    n_nodes: jnp.ndarray  # () i32


class _Frontier(NamedTuple):
    chg: jnp.ndarray  # (M,) f32, -inf = none
    flat: jnp.ndarray  # (M,) i32 best f*B+slot
    slotl: jnp.ndarray  # (M,) i32
    GL: jnp.ndarray
    HL: jnp.ndarray
    CL: jnp.ndarray
    GR: jnp.ndarray
    HR: jnp.ndarray
    CR: jnp.ndarray
    active: jnp.ndarray  # (M,) bool


def _route_wave(
    bins_t, pos, sel_valid, sel_nid, sel_feat, sel_slot, sel_lo, sel_hi,
    sel_l, sel_r, NW,
):
    """Move samples of each wave node to its children: one bins_t row
    dynamic-slice + compare per wave slot (masked no-op when invalid).

    sel_lo/sel_hi bound the split's EFB member range: a row goes right
    only when its bin is inside [lo, hi] AND above the slot — bins
    outside the range are other bundle members (the split feature's
    default/zero value, which sits left). Plain columns pass lo=0,
    hi=B-1, reducing to the original `bin > slot` compare."""
    n = pos.shape[0]

    def body(i, pos):
        f = jnp.maximum(sel_feat[i], 0)
        row = jax.lax.dynamic_slice(bins_t, (f, jnp.zeros((), f.dtype)), (1, n))[0]
        row = row.astype(jnp.int32)
        go_right = (row > sel_slot[i]) & (row >= sel_lo[i]) & (row <= sel_hi[i])
        child = jnp.where(go_right, sel_r[i], sel_l[i])
        upd = jnp.where(pos == sel_nid[i], child, pos)
        return jnp.where(sel_valid[i], upd, pos)

    return jax.lax.fori_loop(0, NW, body, pos)


def make_grow_tree(spec: GrowSpec, mesh=None, axis: str = "data", ranges=None):
    """Build the jittable grow(bins_t, include, g, h, feat_mask[, aux, key]) fn.

    aux: optional (bins_t_extra, ...) tuple of extra transposed bin
    matrices (e.g. the test set) whose row positions are routed through
    the same splits; their final leaf assignment comes back alongside.
    key: PRNG key for the GOSS remainder draw (required semantics only
    when spec.goss_a < 1 and goss_b > 0; defaults to PRNGKey(0)). Under a
    mesh each shard folds in its axis index, so per-shard draws are
    independent and deterministic.
    ranges: optional (range_lo, range_hi) GLOBAL (F, B) int32 EFB member-
    range tables (see split_kernel); sliced per shard for enumeration,
    used whole for routing.

    With spec.goss_a < 1 the returned pos is the leaf assignment of the
    COMPACTED fit rows; the full training matrix is routed as the first
    aux entry, so callers read the train positions from aux_pos[0] and
    their own aux sets from aux_pos[1:].

    Returns (TreeArrays, pos_final, aux_pos_final, wave_log) where
    wave_log (max_nodes+8, 5) f32 records per histogram pass
    [rows_scanned, rows_needed, splits, hist_width, rows_sampled] — the
    roofline and O(wave rows) ablation record (row 0 = root pass; rows
    with hist_width 0 are unused slots; rows_sampled is the GOSS-kept
    row count, == the included-row count when GOSS is off; row counts
    are per-shard under a mesh, exact on one device).

    With a mesh of >1 devices the SAME growth program runs under
    `shard_map` over row shards — each device feeds its local rows to the
    SAME Pallas/dense histogram and routing kernels as mesh=1, partial
    histograms are combined by `psum_scatter` so each device owns a
    contiguous feature slice of every node histogram (the reduce-scatter
    ownership of reference HistogramBuilder.java:95), split enumeration
    runs only on the owned slice (DataParallelTreeMaker.java:598-653),
    and the global best split per node is merged with `pargmax_tuple`
    (SplitInfo.needReplace semantics: lower rank = lower global feature
    block on ties, reproducing single-device first-max tie-breaks).
    Caller contract for mesh>1: spec.F divisible by the device count
    (pad features + feat_mask), sample axis divisible by (devices x
    spec.bm) on TPU.
    """
    n_shards = 1 if mesh is None else int(mesh.devices.size)
    grow = _build_grow(spec, n_shards, axis, ranges)
    if n_shards == 1:
        return grow

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    def grow_sharded(bins_t, include, g, h, feat_mask, aux=(), key=None):
        if key is None:
            key = jax.random.PRNGKey(0)

        def f(bins_t, include, g, h, feat_mask, aux, key):
            return grow(bins_t, include, g, h, feat_mask, aux=aux, key=key)

        return shard_map_compat(
            f,
            mesh=mesh,
            in_specs=(
                P(None, axis), P(axis), P(axis), P(axis), P(axis),
                P(None, axis), P(),
            ),
            # wave_log is replicated: rows/splits/width are static or come
            # from the globally-merged frontier stats
            out_specs=(P(), P(axis), P(axis), P()),
            check_vma=False,
        )(bins_t, include, g, h, feat_mask, tuple(aux), key)

    return grow_sharded


def _build_grow(spec: GrowSpec, n_shards: int = 1, axis: str = "data", ranges=None):
    """The growth program body; n_shards>1 = running inside shard_map."""
    M, NW, F, B = spec.max_nodes, spec.wave, spec.F, spec.B
    F_loc = F // max(n_shards, 1)
    assert F_loc * max(n_shards, 1) == F, (F, n_shards)
    cfg = (spec.l1, spec.l2, spec.min_h, spec.max_abs)
    _, node_value = make_gain_fns(*cfg)
    iota_m = jnp.arange(M, dtype=jnp.int32)
    if ranges is not None:
        rlo_g = jnp.asarray(ranges[0], jnp.int32)  # (F, B) global tables
        rhi_g = jnp.asarray(ranges[1], jnp.int32)
        assert rlo_g.shape == (F, B), (rlo_g.shape, F, B)
    else:
        rlo_g = rhi_g = None

    if n_shards > 1:
        from ..parallel.collectives import pargmax_tuple, psum_scatter

        def combine_hist(local):
            """Partial (N, F, B, 3|i32) -> globally-summed owned F-slice."""
            return psum_scatter(local, axis, tiled=True, scatter_dimension=1)

        def local_ranges():
            """This shard's contiguous F-slice of the EFB range tables
            (hi/lo values are slot indices WITHIN a column's own bin
            axis, so slicing along F needs no re-offsetting)."""
            if rlo_g is None:
                return None
            dev = jax.lax.axis_index(axis)
            start = (dev * F_loc, jnp.zeros((), jnp.int32))
            return (
                jax.lax.dynamic_slice(rlo_g, start, (F_loc, B)),
                jax.lax.dynamic_slice(rhi_g, start, (F_loc, B)),
            )

        def best_splits(hists, fmask_loc, ranges_loc=None):
            """split_kernel on the owned slice + global pargmax merge.

            Local flat indices are offset into global (f, slot) coords;
            pargmax's lower-rank tie-break equals the single-device
            first-max tie-break because feature slices are contiguous."""
            out = split_kernel(hists, fmask_loc, cfg, ranges_loc)
            dev = jax.lax.axis_index(axis)
            gflat = out[1] + dev * (F_loc * B)
            chg, payload = pargmax_tuple(out[0], (gflat,) + out[2:], axis)
            return (chg,) + payload
    else:

        def combine_hist(local):
            return local

        def local_ranges():
            return None if rlo_g is None else (rlo_g, rhi_g)

        def best_splits(hists, fmask_loc, ranges_loc=None):
            return split_kernel(hists, fmask_loc, cfg, ranges_loc)

    def can_split(fr: _Frontier, tr: TreeArrays, leaves):
        ok = fr.active & jnp.isfinite(fr.chg) & (fr.chg > spec.min_split_loss)
        ok &= (fr.CL + fr.CR) >= spec.min_split_samples
        ok &= (fr.HL + fr.HR) >= 2.0 * spec.min_h
        ok &= tr.depth < spec.depth_cap
        # capacity: children must fit the fixed arrays
        return ok & (leaves < spec.leaf_cap)

    def select(ok, fr: _Frontier, tr: TreeArrays, nw: int):
        if spec.policy == "level":
            k1 = jnp.where(ok, tr.depth, BIG32)
            _, sel = jax.lax.sort((k1, iota_m), num_keys=2)
        else:
            k1 = jnp.where(ok, -fr.chg, jnp.inf)
            _, sel = jax.lax.sort((k1, iota_m), num_keys=2)
        sel = sel[:nw]
        return sel, ok[sel]

    def grow(bins_t, include, g, h, feat_mask, aux=(), key=None):
        ranges_loc = local_ranges()
        goss_on = 0.0 < spec.goss_a < 1.0
        goss_rows = None  # per-shard GOSS-kept row count (wave-log col 4)
        if goss_on:
            n_full = bins_t.shape[1]
            gunit = 128 if spec.force_dense else spec.bm
            # static top/remainder counts over the REAL rows (goss_scale
            # discounts padding; re-masked below so padding never leaks)
            n_eff = max(1, min(n_full, int(np.ceil(spec.goss_scale * n_full))))
            k_a = max(1, min(n_eff, int(np.ceil(spec.goss_a * n_eff))))
            k_b = 0
            if spec.goss_b > 0.0:
                k_b = min(
                    n_eff - k_a, int(np.ceil(spec.goss_b * (n_eff - k_a)))
                )
            if key is None:
                key = jax.random.PRNGKey(0)
            if n_shards > 1:
                # independent, deterministic per-shard draws
                key = jax.random.fold_in(key, jax.lax.axis_index(axis))
            # top-a by |g|: exact-k via index scatter (top_k's lowest-index
            # tie-break keeps this deterministic); padding/excluded rows
            # carry -1 and sort last, the & include re-mask drops any that
            # leaked in when a*n_pad exceeds the real row count
            absg = jnp.where(include, jnp.abs(g), -1.0)
            _, idx_top = jax.lax.top_k(absg, k_a)
            keep = (
                jnp.zeros((n_full,), bool).at[idx_top].set(True) & include
            )
            if k_b > 0:
                u = jax.random.uniform(key, (n_full,))
                rest = include & ~keep
                _, idx_r = jax.lax.top_k(jnp.where(rest, u, -1.0), k_b)
                rmask = jnp.zeros((n_full,), bool).at[idx_r].set(True) & rest
                amp = jnp.float32(1.0 / spec.goss_b)
                g = jnp.where(rmask, g * amp, g)
                h = jnp.where(rmask, h * amp, h)
                keep = keep | rmask
            # compact the kept rows into the static fit matrix (order-
            # preserving, so int8 histogram sums stay bit-stable); the
            # full matrix becomes aux[0] purely for final leaf assignment
            R_fit = max(gunit, -(-(k_a + k_b) // gunit) * gunit)
            R_fit = min(R_fit, n_full)
            idx_fit, goss_rows = compact_indices(keep, R_fit)
            valid_fit = jnp.arange(R_fit, dtype=jnp.int32) < goss_rows
            aux = (bins_t,) + tuple(aux)
            bins_t = jnp.take(bins_t, idx_fit, axis=1)
            g = jnp.where(valid_fit, jnp.take(g, idx_fit), 0.0)
            h = jnp.where(valid_fit, jnp.take(h, idx_fit), 0.0)
            include = valid_fit

        n = bins_t.shape[1]
        pos = jnp.zeros((n,), jnp.int32)
        aux_pos = tuple(jnp.zeros((bt.shape[1],), jnp.int32) for bt in aux)
        if goss_rows is None:
            goss_rows = jnp.sum(include, dtype=jnp.float32)

        # leaf-partition budget ladder (static shapes, ascending): a wave
        # hists only smaller children, so ceil(n/2) always fits budget 0.
        # Each rung carries its implementation: "fused" (compact+gather+
        # histogram in one Pallas kernel, small budgets) or "xla" (explicit
        # row gather + the full-scan kernel, the only option above
        # fused_max_rows where per-row DMA issue would dominate).
        use_part = spec.partition
        can_fuse = spec.fused and (not spec.force_dense or spec.fused_interpret)
        unit_xla = 128 if spec.force_dense else spec.bm
        if use_part:
            rungs = []  # ascending [(R, impl)]
            for div in spec.ladder:
                want = -(-n // div)  # ceil(n / div)
                fuse = can_fuse and want <= spec.fused_max_rows
                unit = spec.bm_g if fuse else unit_xla
                R = max(-(-want // unit) * unit, unit)
                if R < n and R not in [r for r, _ in rungs]:
                    rungs.append((R, "fused" if fuse else "xla"))
            rungs.sort()
            use_part = bool(rungs)
        if use_part:
            # row-major copy for the per-wave row gather (shard-local under
            # shard_map; materialized once per tree, ~n*F bytes at u8)
            bins_rows = jnp.transpose(bins_t)
            if spec.B <= 256:
                bins_rows = bins_rows.astype(jnp.uint8)

        # tile once per tree: the Pallas kernels want (F, nblk, 1, bm); done
        # inside the wave loop XLA re-materializes the tiled copy EVERY wave
        # (~10 ms x 20 waves per tree at 10M rows, seen in xprof)
        if not spec.force_dense:
            bins_k = bins_t.reshape(F, n // spec.bm, 1, spec.bm)
            aux_k = tuple(
                bt.reshape(F, bt.shape[1] // spec.bm, 1, spec.bm) for bt in aux
            )
        else:
            bins_k = bins_t
            aux_k = aux

        if spec.hist_mode == "int8":
            # per-tree symmetric int8 quantization of the (weighted) grads;
            # one-hot selection and counts stay exact, G/H sums carry a
            # bounded ~|g|max/(2*qmax)-per-sample rounding error in exchange
            # for the int8 MXU path. qmax shrinks above ~16.9M rows so the
            # worst-case i32 column accumulation (qmax * n_global) cannot
            # overflow — sharded, the i32 psum_scatter spans all shards.
            n_global = n * max(n_shards, 1)
            qmax = float(min(127, (2**31 - 1) // max(n_global, 1)))
            gmax = jnp.max(jnp.abs(g))
            hmax = jnp.max(jnp.abs(h))
            if n_shards > 1:
                # one global scale pair so quantized partials sum exactly
                from ..parallel.collectives import pmax

                gmax = pmax(gmax, axis)
                hmax = pmax(hmax, axis)
            sg = qmax / jnp.maximum(gmax, 1e-12)
            sh = qmax / jnp.maximum(hmax, 1e-12)
            gq = jnp.clip(jnp.round(g * sg), -qmax, qmax)  # f32 integers:
            hq = jnp.clip(jnp.round(h * sh), -qmax, qmax)  # kernel casts to i8
            inv = jnp.stack([1.0 / sg, 1.0 / sh, jnp.asarray(1.0)])
            G_, H_ = gq, hq

            def hist_partial(bins_in, pos_v, g_v, h_v, ids):
                return hist_wave_q(
                    bins_in, pos_v, g_v, h_v, ids, B,
                    bm=spec.bm, force_dense=spec.force_dense,
                )  # (N, F, B, 3) i32 partial

            def hist_finish(partial_h):
                summed = combine_hist(partial_h)  # (N, F_loc, B, 3) global
                return summed.astype(jnp.float32) * inv[None, None, None, :]

        else:
            G_, H_ = g, h

            def hist_partial(bins_in, pos_v, g_v, h_v, ids):
                return hist_wave(
                    bins_in, pos_v, g_v, h_v, ids, B,
                    bm=spec.bm, use_bf16=spec.use_bf16,
                    force_dense=spec.force_dense,
                )

            def hist_finish(partial_h):
                return combine_hist(partial_h)

        def hist_call(pos_fit, ids):
            """Full-scan histogram (root + slow start + big-wave phases)."""
            return hist_finish(hist_partial(bins_k, pos_fit, G_, H_, ids))

        def hist_budget(R: int, impl: str = "xla"):
            """Leaf-partitioned histogram at static budget R: compact the
            rows belonging to the wave's nodes and histogram only those —
            R rows instead of n. The phase loop's condition guarantees the
            wave needs <= R rows. (This is deliberately cond-free: lax.cond
            around a Mosaic kernel takes >10 min to compile on this
            toolchain — phase-separated while_loops select the budget.)

            impl="fused": the row-index list goes straight into the fused
            Pallas kernel (per-row DMA gather + in-kernel accumulation) —
            no (R, F) XLA gather, no transpose. impl="xla": the original
            explicit gather + full-scan kernel (large budgets)."""

            def call(pos_fit, ids):
                mask = jnp.zeros(pos_fit.shape, bool)
                for k in range(int(ids.shape[0])):  # static width unroll
                    mask = mask | (pos_fit == ids[k])
                idx, cnt = compact_indices(mask, R)
                valid = jnp.arange(R, dtype=jnp.int32) < cnt
                pg = jnp.where(valid, jnp.take(pos_fit, idx), -1)
                gg = jnp.take(G_, idx)
                hg = jnp.take(H_, idx)
                if impl == "fused":
                    part = hist_wave_gather(
                        bins_rows, idx, pg, gg, hg, ids, B,
                        mode=spec.hist_mode if spec.hist_mode == "int8" else "mxu",
                        use_bf16=spec.use_bf16, bm_g=spec.bm_g,
                        force_dense=spec.force_dense and not spec.fused_interpret,
                        interpret=spec.fused_interpret,
                    )
                    return hist_finish(part)
                bg = jnp.take(bins_rows, idx, axis=0)  # (R, F) u8
                bt = jnp.transpose(bg).astype(jnp.int32)
                if not spec.force_dense:
                    bt = bt.reshape(F, R // spec.bm, 1, spec.bm)
                return hist_finish(hist_partial(bt, pg, gg, hg, ids))

            return call

        tr = TreeArrays(
            feat=jnp.full((M,), -1, jnp.int32),
            slot=jnp.zeros((M,), jnp.int32),
            slot_r=jnp.zeros((M,), jnp.int32),
            left=jnp.full((M,), -1, jnp.int32),
            right=jnp.full((M,), -1, jnp.int32),
            leaf=jnp.zeros((M,), jnp.float32),
            gain=jnp.zeros((M,), jnp.float32),
            hess=jnp.zeros((M,), jnp.float32),
            cnt=jnp.zeros((M,), jnp.float32),
            depth=jnp.zeros((M,), jnp.int32),
            n_nodes=jnp.asarray(1, jnp.int32),
        )

        # root histogram + stats + frontier. Sharded: hist0 is the owned
        # F-slice of the GLOBAL histogram, so any owned feature's bin-sum
        # (even an all-padding feature: every sample lands in bin 0) gives
        # the node totals — but each device sums a DIFFERENT feature's
        # column, so f32 rounding could diverge by a ULP across devices;
        # broadcast rank0's value so the "replicated" root stats really
        # are bit-identical (out_specs P() + check_vma=False would
        # otherwise silently ship device 0's copy while in-program scores
        # used per-device ones).
        ids0 = jnp.asarray([0], jnp.int32)  # root wave: one real slot
        pos_fit = jnp.where(include, pos, -1)
        hist0 = hist_call(pos_fit, ids0)  # (1, F_loc, B, 3)
        root_ghc = jnp.sum(hist0[0, 0], axis=0)  # feature 0 bin-sum = totals
        if n_shards > 1:
            from ..parallel.collectives import psum

            root_ghc = psum(
                jnp.where(jax.lax.axis_index(axis) == 0, root_ghc, 0.0), axis
            )
        tr = tr._replace(
            hess=tr.hess.at[0].set(root_ghc[1]),
            cnt=tr.cnt.at[0].set(root_ghc[2]),
            leaf=tr.leaf.at[0].set(node_value(root_ghc[0], root_ghc[1]) * spec.lr),
        )
        pool = jnp.zeros((M, F_loc, B, 3), jnp.float32)
        pool = pool.at[0].set(hist0[0])

        out0 = best_splits(hist0[:1], feat_mask, ranges_loc)
        f32 = jnp.float32
        fr = _Frontier(
            chg=jnp.full((M,), -jnp.inf, f32).at[0].set(out0[0][0]),
            flat=jnp.zeros((M,), jnp.int32).at[0].set(out0[1][0]),
            slotl=jnp.zeros((M,), jnp.int32).at[0].set(out0[2][0]),
            GL=jnp.zeros((M,), f32).at[0].set(out0[3][0]),
            HL=jnp.zeros((M,), f32).at[0].set(out0[4][0]),
            CL=jnp.zeros((M,), f32).at[0].set(out0[5][0]),
            GR=jnp.zeros((M,), f32).at[0].set(out0[6][0]),
            HR=jnp.zeros((M,), f32).at[0].set(out0[7][0]),
            CR=jnp.zeros((M,), f32).at[0].set(out0[8][0]),
            active=jnp.zeros((M,), bool).at[0].set(True),
        )
        leaves0 = jnp.asarray(1, jnp.int32)

        # wave log: [rows_scanned (static hist cost), rows_needed (exact
        # smaller-child sum), splits made, hist width N, rows_sampled
        # (GOSS-kept rows; included rows when GOSS is off)] per wave — the
        # roofline/ablation record (fetched once per tree, a few KB).
        # Row 0 is the root histogram pass. ALL row counts are PER-SHARD
        # (rows_scanned is the local n / local budget R already; the need
        # columns divide the globally-merged frontier counts by the shard
        # count) so scanned-vs-needed comparisons and per-chip utilization
        # stay unit-consistent on a mesh. Exact on one device.
        MW = wave_log_rows(M)  # waves <= splits + slow-start ramp + root
        inv_shards = 1.0 / float(max(n_shards, 1))
        goss_rows_f = goss_rows.astype(jnp.float32)
        if n_shards > 1:
            # the wave log ships replicated (out_specs P()): per-shard kept
            # counts can differ, so col 4 carries the cross-shard MEAN —
            # the same per-shard units as the other row columns
            from ..parallel.collectives import psum as _psum

            goss_rows_f = _psum(goss_rows_f, axis) * inv_shards
        wlog0 = jnp.zeros((MW, 5), jnp.float32)
        wlog0 = wlog0.at[0].set(
            jnp.stack([
                jnp.float32(n), root_ghc[2] * inv_shards,
                jnp.float32(0.0), jnp.float32(1.0), goss_rows_f,
            ])
        )
        wcnt0 = jnp.asarray(1, jnp.int32)

        def cond(state):
            tr, fr, pool, pos, aux_pos, leaves, wlog, wcnt = state
            return jnp.any(can_split(fr, tr, leaves))

        def wave_need(state):
            """Exact row count the NEXT wave's histograms touch: the sum of
            smaller-child counts over the nodes the selection would pick.
            Drives the phase-loop budget transitions (computed from frontier
            stats — C-channel counts match the compaction mask exactly)."""
            tr, fr, pool, pos, aux_pos, leaves, wlog, wcnt = state
            ok = can_split(fr, tr, leaves)
            sel, sel_ok = select(ok, fr, tr, NW)
            order_cum = jnp.cumsum(sel_ok.astype(jnp.int32), dtype=jnp.int32)
            sel_ok &= (leaves + order_cum) <= spec.leaf_cap
            small_cnt = jnp.minimum(fr.CL[sel], fr.CR[sel])
            return jnp.sum(jnp.where(sel_ok, small_cnt, 0.0))

        def make_body(nw: int, hist_fn=None, hist_rows: int = None):
            return lambda state: wave_body(state, nw, hist_fn, hist_rows)

        def wave_body(state, nw: int, hist_fn=None, hist_rows: int = None):
            tr, fr, pool, pos, aux_pos, leaves, wlog, wcnt = state
            ok = can_split(fr, tr, leaves)
            sel, sel_ok = select(ok, fr, tr, nw)

            # leaf budget count-off in selection order (level: node order
            # within the level; loss: gain order) — reference semantics
            order_cum = jnp.cumsum(sel_ok.astype(jnp.int32), dtype=jnp.int32)
            sel_ok &= (leaves + order_cum) <= spec.leaf_cap
            k_cnt = jnp.sum(sel_ok, dtype=jnp.int32)

            # children allocation in selection order
            prefix = jnp.cumsum(
                sel_ok.astype(jnp.int32), dtype=jnp.int32
            ) - sel_ok.astype(jnp.int32)
            lch = tr.n_nodes + 2 * prefix
            rch = lch + 1
            nid = sel
            scatter_id = jnp.where(sel_ok, nid, M)  # M = dropped
            lch_id = jnp.where(sel_ok, lch, M)
            rch_id = jnp.where(sel_ok, rch, M)

            f_best = fr.flat[nid] // B
            slot_r = fr.flat[nid] % B
            slot_l = fr.slotl[nid]
            if rlo_g is not None:
                # EFB member range of the chosen boundary slot (global
                # tables: f_best is a global column id) — bounds routing
                # so other bundle members' rows stay on the default side
                sel_lo = rlo_g[f_best, slot_r]
                sel_hi = rhi_g[f_best, slot_r]
            else:
                sel_lo = jnp.zeros_like(f_best)
                sel_hi = jnp.full_like(f_best, B - 1)
            GLs, HLs, CLs = fr.GL[nid], fr.HL[nid], fr.CL[nid]
            GRs, HRs, CRs = fr.GR[nid], fr.HR[nid], fr.CR[nid]
            child_depth = tr.depth[nid] + 1

            drop = dict(mode="drop")
            tr = tr._replace(
                feat=tr.feat.at[scatter_id].set(f_best, **drop),
                slot=tr.slot.at[scatter_id].set(slot_l, **drop),
                slot_r=tr.slot_r.at[scatter_id].set(slot_r, **drop),
                left=tr.left.at[scatter_id].set(lch, **drop),
                right=tr.right.at[scatter_id].set(rch, **drop),
                gain=tr.gain.at[scatter_id].set(fr.chg[nid], **drop),
                leaf=tr.leaf.at[lch_id]
                .set(node_value(GLs, HLs) * spec.lr, **drop)
                .at[rch_id]
                .set(node_value(GRs, HRs) * spec.lr, **drop),
                hess=tr.hess.at[lch_id].set(HLs, **drop).at[rch_id].set(HRs, **drop),
                cnt=tr.cnt.at[lch_id].set(CLs, **drop).at[rch_id].set(CRs, **drop),
                depth=tr.depth.at[lch_id]
                .set(child_depth, **drop)
                .at[rch_id]
                .set(child_depth, **drop),
                n_nodes=(tr.n_nodes + 2 * k_cnt).astype(jnp.int32),
            )

            # routing (train + any aux sets)
            if spec.force_dense:
                pos = _route_wave(
                    bins_t, pos, sel_ok, nid, f_best, slot_l, sel_lo, sel_hi,
                    lch, rch, nw,
                )
                aux_pos = tuple(
                    _route_wave(
                        bt, ap, sel_ok, nid, f_best, slot_l, sel_lo, sel_hi,
                        lch, rch, nw,
                    )
                    for bt, ap in zip(aux, aux_pos)
                )
            else:
                pos = route_wave(
                    bins_k, pos, sel_ok, nid, f_best, slot_l, lch, rch,
                    bm=spec.bm, lo=sel_lo, hi=sel_hi,
                )
                aux_pos = tuple(
                    route_wave(
                        bt, ap, sel_ok, nid, f_best, slot_l, lch, rch,
                        bm=spec.bm, lo=sel_lo, hi=sel_hi,
                    )
                    for bt, ap in zip(aux_k, aux_pos)
                )

            # smaller-child histogram + sibling subtraction
            small = jnp.where(CLs <= CRs, lch, rch)
            big = jnp.where(CLs <= CRs, rch, lch)
            ids = jnp.where(sel_ok, small, -2)
            pos_fit = jnp.where(include, pos, -1)
            h_small = (hist_fn or hist_call)(pos_fit, ids)
            parent_h = pool[nid]
            h_big = parent_h - h_small
            pool = pool.at[jnp.where(sel_ok, small, M)].set(h_small, **drop)
            pool = pool.at[jnp.where(sel_ok, big, M)].set(h_big, **drop)

            # frontier refresh for the 2*NW children
            child_ids = jnp.concatenate([small, big])
            child_ok = jnp.concatenate([sel_ok, sel_ok])
            hists = jnp.concatenate([h_small, h_big], axis=0)
            out = best_splits(hists, feat_mask, ranges_loc)
            cids = jnp.where(child_ok, child_ids, M)
            fr = _Frontier(
                chg=fr.chg.at[scatter_id].set(-jnp.inf, **drop).at[cids].set(out[0], **drop),
                flat=fr.flat.at[cids].set(out[1], **drop),
                slotl=fr.slotl.at[cids].set(out[2], **drop),
                GL=fr.GL.at[cids].set(out[3], **drop),
                HL=fr.HL.at[cids].set(out[4], **drop),
                CL=fr.CL.at[cids].set(out[5], **drop),
                GR=fr.GR.at[cids].set(out[6], **drop),
                HR=fr.HR.at[cids].set(out[7], **drop),
                CR=fr.CR.at[cids].set(out[8], **drop),
                active=fr.active.at[scatter_id]
                .set(False, **drop)
                .at[cids]
                .set(True, **drop),
            )
            need = jnp.sum(
                jnp.where(sel_ok, jnp.minimum(CLs, CRs), 0.0)
            ) * inv_shards
            rows_f = jnp.float32(n if hist_rows is None else hist_rows)
            wlog = wlog.at[wcnt].set(
                jnp.stack([
                    rows_f, need, k_cnt.astype(jnp.float32), jnp.float32(nw),
                    goss_rows_f,
                ]),
                mode="drop",
            )
            return (
                tr, fr, pool, pos, aux_pos,
                (leaves + k_cnt).astype(jnp.int32),
                wlog, (wcnt + 1).astype(jnp.int32),
            )

        state = (tr, fr, pool, pos, aux_pos, leaves0, wlog0, wcnt0)
        # slow start: after k waves at most 2^k nodes are expandable, so the
        # first waves run right-sized (N = 1, 2, 4, ...) — identical split
        # decisions to full-width waves at a fraction of the one-hot matmul
        # rows (each wave's hist cost is proportional to its slot count)
        nw_ss = 1
        while nw_ss < NW:
            state = wave_body(state, nw_ss)
            nw_ss *= 2

        if use_part:
            # phase-separated growth: full scans while waves are big, then
            # tighter partitioned budgets as the frontier's row need
            # shrinks, then a full-scan tail for any non-monotone leftovers
            # (need is near-monotone decreasing under gain-ordered
            # selection; the tail keeps pathological orders correct)
            Rs = sorted(rungs, reverse=True)  # big -> small [(R, impl)]

            def mk_cond(lo, hi):
                # `need` is the GLOBAL wave row count (frontier stats are
                # merged/replicated across shards) compared against the
                # LOCAL budget R: global need <= local budget implies every
                # shard's local rows fit — conservative under a mesh (a
                # shard transitions ~D x later than its own load requires)
                # but never drops rows, and exact on one device.
                def cond_fn(state):
                    c = cond(state)
                    need = wave_need(state)
                    if hi is not None:
                        c &= need <= hi
                    if lo is not None:
                        c &= need > lo
                    return c

                return cond_fn

            state = jax.lax.while_loop(
                mk_cond(Rs[0][0], None), make_body(NW), state
            )
            for i, (R, impl) in enumerate(Rs):
                nxt = Rs[i + 1][0] if i + 1 < len(Rs) else None
                state = jax.lax.while_loop(
                    mk_cond(nxt, R),
                    make_body(NW, hist_budget(R, impl), hist_rows=R),
                    state,
                )
            state = jax.lax.while_loop(cond, make_body(NW), state)
        else:
            state = jax.lax.while_loop(cond, make_body(NW), state)
        tr, fr, pool, pos, aux_pos, leaves, wlog, wcnt = state
        return tr, pos, aux_pos, wlog

    return grow
