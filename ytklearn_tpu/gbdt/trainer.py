"""GBDT boosting trainer — histogram trees on the TPU.

Rebuild of reference optimizer/GBDTOptimizer.java (boosting driver,
:174-530) + optimizer/gbdt/DataParallelTreeMaker.java:229-653 (histogram
build, split enumeration, position update) + UpdateStrategy.java:64-83
(gain / leaf-value formulas incl. L1 soft-threshold + leaf clamp) +
TreeRefiner.java (LAD weighted-median leaves).

TPU-first design:
  - the bin matrix (n, F) int32 lives on device, rows sharded over the mesh
  - histograms are one fused segment-sum per level (channels g/h/count);
    under jit with sharded rows XLA reduces partial histograms with a psum
    — the reduce-scatter of HistogramBuilder.java:95 without hand-rolling
  - split enumeration is a cumulative-sum scan over all (node, feature,
    bin) at once; the global best per node is an argmax whose first-max
    semantics reproduce SplitInfo.needReplace's lower-slot tie-break
  - empty bins are skipped exactly like the reference: the split interval
    is [last nonempty slot, current slot] and the dumped split value is
    their mean/median (FeatureSplitType)
  - level-wise growth runs one device program per level; loss-wise growth
    keeps per-frontier-node histograms and computes each smaller child by
    a masked scan, deriving the sibling by subtraction (the HistogramPool
    trick, data/gbdt/HistogramPool.java)
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.params import GBDTParams
from ..eval import EvalSet
from ..io.fs import FileSystem, LocalFileSystem
from ..losses import create_loss
from ..parallel.mesh import row_sharding
from .binning import FeatureBins, bin_matrix, build_bins
from .data import GBDTData, GBDTIngest
from .tree import GBDTModel, Tree

log = logging.getLogger("ytklearn_tpu.gbdt")


# ---------------------------------------------------------------------------
# Gain / leaf-value formulas (reference: UpdateStrategy.java)
# ---------------------------------------------------------------------------


def _threshold_l1(g, l1):
    return jnp.where(g > l1, g - l1, jnp.where(g < -l1, g + l1, 0.0))


def make_gain_fns(params: GBDTParams):
    l1, l2 = params.l1, params.l2
    min_h = params.min_child_hessian_sum
    max_abs = params.max_abs_leaf_val

    def node_value(G, H):
        t = _threshold_l1(G, l1) if l1 > 0 else G
        val = -t / (H + l2)
        if max_abs > 0:
            val = jnp.clip(val, -max_abs, max_abs)
        return jnp.where(H < min_h, 0.0, val)

    def gain(G, H):
        if max_abs <= 0:
            t = _threshold_l1(G, l1) if l1 > 0 else G
            out = t * t / (H + l2)
        else:
            v = node_value(G, H)
            out = -2.0 * (G * v + 0.5 * (H + l2) * v * v + l1 * jnp.abs(v))
        return jnp.where(H < min_h, 0.0, out)

    return gain, node_value


# ---------------------------------------------------------------------------
# Device kernels (data passed as args — no captured constants)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_nodes", "F", "B"))
def hist_kernel(bins, pos, g, h, n_nodes: int, F: int, B: int):
    """(n_nodes, F, B, 3) histogram of (g, h, count) by level-local node.

    pos < 0 = inactive sample -> dump segment. One fused scatter-add — the
    hottest loop of the reference (HistogramBuilder.java:72-90) as a single
    XLA op; with rows sharded, XLA psums the partial histograms
    (the reduceScatterArray at :95)."""
    n = bins.shape[0]
    active = pos >= 0
    base = jnp.where(active, pos, n_nodes) * (F * B)
    ids = base[:, None] + jnp.arange(F)[None, :] * B + bins  # (n, F)
    vals = jnp.stack(
        [g, h, jnp.where(active, 1.0, 0.0)], axis=1
    )  # (n, 3)
    flat = jnp.zeros(((n_nodes + 1) * F * B, 3), jnp.float32)
    flat = flat.at[ids.reshape(-1)].add(
        jnp.repeat(vals, F, axis=0).reshape(n, F, 3).reshape(-1, 3)
    )
    return flat[: n_nodes * F * B].reshape(n_nodes, F, B, 3)


@partial(jax.jit, static_argnames=("cfg",))
def split_kernel(hist, feat_mask, cfg):
    """Best split per node from (N, F, B, 3) histograms.

    Returns per-node: (loss_chg, flat_idx, slot_left, GL, HL, CL, GR, HR, CR)
    (reference: enumerateSplit:598-637 — empty slots skipped, split interval
    [last nonempty, current], child-hessian guards, gain vs root)."""
    l1, l2, min_h, max_abs = cfg
    N, F, B, _ = hist.shape
    G, H, C = hist[..., 0], hist[..., 1], hist[..., 2]

    def node_value(Gv, Hv):
        t = _threshold_l1(Gv, l1) if l1 > 0 else Gv
        val = -t / (Hv + l2)
        if max_abs > 0:
            val = jnp.clip(val, -max_abs, max_abs)
        return jnp.where(Hv < min_h, 0.0, val)

    def gain(Gv, Hv):
        if max_abs <= 0:
            t = _threshold_l1(Gv, l1) if l1 > 0 else Gv
            out = t * t / (Hv + l2)
        else:
            v = node_value(Gv, Hv)
            out = -2.0 * (Gv * v + 0.5 * (Hv + l2) * v * v + l1 * jnp.abs(v))
        return jnp.where(Hv < min_h, 0.0, out)

    # exclusive cumsums: stats strictly left of boundary slot j
    GL = jnp.cumsum(G, axis=-1) - G
    HL = jnp.cumsum(H, axis=-1) - H
    CL = jnp.cumsum(C, axis=-1) - C
    Gt = jnp.sum(G, axis=-1, keepdims=True)
    Ht = jnp.sum(H, axis=-1, keepdims=True)
    Ct = jnp.sum(C, axis=-1, keepdims=True)
    GR, HR, CR = Gt - GL, Ht - HL, Ct - CL

    nonempty = C > 0
    has_prev = (jnp.cumsum(nonempty.astype(jnp.int32), axis=-1) - nonempty) > 0
    valid = nonempty & has_prev & (HL >= min_h) & (HR >= min_h)
    valid = valid & feat_mask[None, :, None]

    # node totals: every active sample hits every feature's histogram, so
    # feature 0's bin-sum is the node total
    root_gain = gain(jnp.sum(G, axis=-1)[:, 0:1], jnp.sum(H, axis=-1)[:, 0:1])

    loss_chg = gain(GL, HL) + gain(GR, HR) - root_gain[:, :, None]
    loss_chg = jnp.where(valid, loss_chg, -jnp.inf)

    flat = loss_chg.reshape(N, F * B)
    best = jnp.argmax(flat, axis=-1)  # first max -> lowest (f, slot): tie-break
    best_chg = jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0]

    # last nonempty slot strictly before j (the split interval's left end)
    idxs = jnp.where(nonempty, jnp.arange(B)[None, None, :], -1)
    lastne_incl = jax.lax.cummax(idxs, axis=2)
    lastne = jnp.concatenate(
        [jnp.full((N, F, 1), -1, lastne_incl.dtype), lastne_incl[:, :, :-1]], axis=2
    ).reshape(N, F * B)
    slot_left = jnp.take_along_axis(lastne, best[:, None], axis=-1)[:, 0]

    def pick(A):
        return jnp.take_along_axis(A.reshape(N, F * B), best[:, None], axis=-1)[:, 0]

    return (
        best_chg,
        best.astype(jnp.int32),
        slot_left.astype(jnp.int32),
        pick(GL),
        pick(HL),
        pick(CL),
        pick(GR),
        pick(HR),
        pick(CR),
    )


@jax.jit
def pos_update_kernel(bins, pos, node_feat, node_slot, node_child_base):
    """Route samples to next-level-local child indices.

    node_child_base[k] = left-child index among next level's nodes, or -1 if
    node k became a leaf (reference: SamplePositionData.resetPosition:115)."""
    safe = jnp.maximum(pos, 0)
    f = node_feat[safe]
    slot = node_slot[safe]
    base = node_child_base[safe]
    b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
    go_right = b > slot
    new = jnp.where(base >= 0, base + go_right.astype(jnp.int32), -1)
    return jnp.where(pos >= 0, new, -1)


@jax.jit
def tree_predict_kernel(bins_f32_scores, pos, leaf_vals):
    """Add each active sample's leaf value to its score."""
    safe = jnp.maximum(pos, 0)
    return bins_f32_scores + jnp.where(pos >= 0, leaf_vals[safe], 0.0)


@partial(jax.jit, static_argnames=("F", "B"))
def node_hist_kernel(bins, in_node, g, h, F: int, B: int):
    """(F, B, 3) histogram for one node's samples (loss-wise growth)."""
    ids = jnp.where(in_node[:, None], jnp.arange(F)[None, :] * B + bins, F * B)
    vals = jnp.stack([g, h, jnp.where(in_node, 1.0, 0.0)], axis=1)
    n = bins.shape[0]
    flat = jnp.zeros((F * B + 1, 3), jnp.float32)
    flat = flat.at[ids.reshape(-1)].add(
        jnp.repeat(vals, F, axis=0).reshape(n, F, 3).reshape(-1, 3)
    )
    return flat[: F * B].reshape(F, B, 3)


# ---------------------------------------------------------------------------
# The trainer
# ---------------------------------------------------------------------------


@dataclass
class GBDTResult:
    model: GBDTModel
    train_loss: float
    test_loss: Optional[float]
    train_metrics: Dict[str, float] = field(default_factory=dict)
    test_metrics: Dict[str, float] = field(default_factory=dict)
    round_log: List[Dict] = field(default_factory=list)


class GBDTTrainer:
    def __init__(
        self,
        params: GBDTParams,
        mesh=None,
        fs: Optional[FileSystem] = None,
    ):
        self.params = params
        self.mesh = mesh
        self.fs = fs or LocalFileSystem()
        self.loss = create_loss(
            params.loss_function, {"sigmoid_zmax": params.sigmoid_zmax}
        )
        self.gain_fn, self.node_value_fn = make_gain_fns(params)
        self.K = params.num_tree_in_group

    def _put(self, arr):
        if self.mesh is None:
            return jax.device_put(arr)
        return jax.device_put(arr, row_sharding(self.mesh))

    # -- tree building ----------------------------------------------------

    def _cfg(self):
        p = self.params
        return (p.l1, p.l2, p.min_child_hessian_sum, p.max_abs_leaf_val)

    def _decide_split(self, chg, cl, cr, hl, hr) -> bool:
        p = self.params
        return (
            np.isfinite(chg)
            and chg > p.min_split_loss
            and cl + cr >= p.min_split_samples
            and (hl + hr) >= p.min_child_hessian_sum * 2.0
        )

    def _finish_split(self, tree, bins_meta, nid, fid, slot_l, slot_r, stats):
        """Record a split on the host tree (slot-space; converted at dump)."""
        gl, hl, cl, gr, hr, cr = stats
        tree.feat[nid] = fid
        tree.feat_name[nid] = bins_meta[fid] if bins_meta else str(fid)
        tree.slot[nid] = slot_l
        tree.split[nid] = float(slot_l)  # slot until convert
        left, right = tree.add_children(nid)
        lr = self.params.learning_rate
        tree.leaf_value[left] = float(self.node_value_fn(gl, hl)) * lr
        tree.leaf_value[right] = float(self.node_value_fn(gr, hr)) * lr
        tree.hess_sum[left], tree.sample_cnt[left] = float(hl), int(cl)
        tree.hess_sum[right], tree.sample_cnt[right] = float(hr), int(cr)
        return left, right

    def build_tree_level_wise(
        self, bins_dev, g, h, pos0, F: int, B: int, feat_mask, names
    ) -> Tuple[Tree, jnp.ndarray]:
        """Level-synchronous growth: one histogram scan + one split search +
        one position update per level (reference level policy,
        DataParallelTreeMaker.make with TreeGrowPolicy.LEVEL)."""
        p = self.params
        tree = Tree()
        pos = pos0  # level-local node index per sample (-1 inactive)
        level_nids = [0]  # tree nid per level-local index
        # root stats
        root_hist = hist_kernel(bins_dev, pos, g, h, 1, F, B)
        ghc = np.asarray(jnp.sum(root_hist, axis=(1, 2)))[0] / F  # sums counted F times
        tree.hess_sum[0], tree.sample_cnt[0] = float(ghc[1]), int(round(ghc[2]))
        tree.leaf_value[0] = float(self.node_value_fn(ghc[0], ghc[1])) * p.learning_rate
        cfg = self._cfg()
        max_leaves = p.max_leaf_cnt if p.max_leaf_cnt > 0 else 1 << 30

        for depth in range(p.max_depth):
            n_nodes = len(level_nids)
            if n_nodes == 0:
                break
            n_pad = 1 << (n_nodes - 1).bit_length()  # pad node count: few shapes
            hist = hist_kernel(bins_dev, pos, g, h, n_pad, F, B)
            out = split_kernel(hist, feat_mask, cfg)
            (chg, flat_idx, slot_l, GL, HL, CL, GR, HR, CR) = (
                np.asarray(o) for o in out
            )

            node_feat = np.full((n_pad,), -1, np.int32)
            node_slot = np.full((n_pad,), 0, np.int32)
            child_base = np.full((n_pad,), -1, np.int32)
            next_nids: List[int] = []
            leaves_after = tree.leaf_cnt()
            for k in range(n_nodes):
                nid = level_nids[k]
                can = (
                    depth < p.max_depth
                    and leaves_after + 1 < max_leaves + 1
                    and self._decide_split(chg[k], CL[k], CR[k], HL[k], HR[k])
                )
                if not can:
                    continue
                fid = int(flat_idx[k]) // B
                slot_right = int(flat_idx[k]) % B
                left, right = self._finish_split(
                    tree,
                    names,
                    nid,
                    fid,
                    int(slot_l[k]),
                    slot_right,
                    (GL[k], HL[k], CL[k], GR[k], HR[k], CR[k]),
                )
                tree.gain[nid] = float(chg[k])
                # store the interval's right end for split-value conversion
                tree.slot[nid] = int(slot_l[k])
                tree.split[nid] = float(slot_right)
                node_feat[k] = fid
                node_slot[k] = int(slot_l[k])
                child_base[k] = len(next_nids)
                next_nids.extend([left, right])
                leaves_after = tree.leaf_cnt()
            if not next_nids:
                break
            pos = pos_update_kernel(
                bins_dev,
                pos,
                jnp.asarray(node_feat),
                jnp.asarray(node_slot),
                jnp.asarray(child_base),
            )
            level_nids = next_nids

        return tree

    def build_tree_loss_wise(
        self, bins_dev, g, h, pos_active, F: int, B: int, feat_mask, names
    ) -> Tuple[Tree, jnp.ndarray]:
        """Best-first growth with per-node histograms + sibling subtraction
        (reference TreeGrowPolicy.LOSS + HistogramPool)."""
        p = self.params
        tree = Tree()
        cfg = self._cfg()
        # tree_pos: tree nid per sample (-1 = excluded by instance sampling)
        tree_pos = jnp.where(pos_active >= 0, 0, -1)

        root_hist = node_hist_kernel(bins_dev, tree_pos >= 0, g, h, F, B)
        hists: Dict[int, jnp.ndarray] = {0: root_hist}
        s = np.asarray(jnp.sum(root_hist[..., :], axis=(0, 1)))  # counted once per f
        Gt, Ht, Ct = s[0] / F, s[1] / F, s[2] / F
        tree.hess_sum[0], tree.sample_cnt[0] = float(Ht), int(round(Ct))
        tree.leaf_value[0] = float(self.node_value_fn(Gt, Ht)) * p.learning_rate

        def best_of(nid):
            out = split_kernel(hists[nid][None], feat_mask, cfg)
            return tuple(np.asarray(o)[0] for o in out)

        frontier = {0: best_of(0)}
        max_leaves = p.max_leaf_cnt if p.max_leaf_cnt > 0 else 1 << 30
        depth_of = {0: 0}

        while tree.leaf_cnt() < max_leaves:
            # pick the best expandable frontier node
            cand = [
                (v[0], nid)
                for nid, v in frontier.items()
                if depth_of[nid] < p.max_depth
                and self._decide_split(v[0], v[5], v[8], v[4], v[7])
            ]
            if not cand:
                break
            chg, nid = max(cand, key=lambda t: (t[0], -t[1]))
            (c, flat_idx, slot_l, GL, HL, CL, GR, HR, CR) = frontier.pop(nid)
            fid = int(flat_idx) // B
            slot_right = int(flat_idx) % B
            left, right = self._finish_split(
                tree, names, nid, fid, int(slot_l), slot_right, (GL, HL, CL, GR, HR, CR)
            )
            tree.gain[nid] = float(c)
            tree.slot[nid] = int(slot_l)
            tree.split[nid] = float(slot_right)
            depth_of[left] = depth_of[right] = depth_of[nid] + 1

            # route samples of nid to children
            b = jnp.take_along_axis(bins_dev, jnp.full((bins_dev.shape[0], 1), fid), 1)[:, 0]
            in_nid = tree_pos == nid
            tree_pos = jnp.where(
                in_nid, jnp.where(b > int(slot_l), right, left), tree_pos
            )

            # smaller child by scan; sibling by subtraction (HistogramPool)
            small, big = (left, right) if CL <= CR else (right, left)
            small_hist = node_hist_kernel(bins_dev, tree_pos == small, g, h, F, B)
            parent_hist = hists.pop(nid)
            hists[small] = small_hist
            hists[big] = parent_hist - small_hist
            frontier[small] = best_of(small)
            frontier[big] = best_of(big)

        return tree

    def _tree_scores_dev(self, tree: Tree, bins_dev) -> jnp.ndarray:
        """Slot-space tree traversal on device (bin <= slot goes left)."""
        feat = jnp.asarray(np.asarray(tree.feat, np.int32))
        slot = jnp.asarray(np.asarray(tree.slot, np.int32))
        left = jnp.asarray(np.asarray(tree.left, np.int32))
        right = jnp.asarray(np.asarray(tree.right, np.int32))
        leaf = jnp.asarray(np.asarray(tree.leaf_value, np.float32))
        depth = max(tree.max_depth(), 1)
        return _traverse_kernel(bins_dev, feat, slot, left, right, leaf, depth)

    # -- boosting ---------------------------------------------------------

    def train(
        self,
        train: Optional[GBDTData] = None,
        test: Optional[GBDTData] = None,
    ) -> GBDTResult:
        p = self.params
        t0 = time.time()
        if train is None:
            train, test = GBDTIngest(p, self.fs).load()
        if self.mesh is not None:
            train = train.pad_rows(self.mesh.devices.size)
            test = test.pad_rows(self.mesh.devices.size) if test else None
        n, F = train.X.shape
        K = self.K

        self._missing_fill = train.missing_fill
        log.info("building bins (%d features)...", F)
        bins = build_bins(train.X, train.weight, p, train.feature_names)
        B = bins.max_bins
        bins_train = self._put(bin_matrix(train.X, bins))
        y = self._put(train.y)
        weight = self._put(train.weight)
        log.info(
            "load+preprocess %.1fs: %d rows, %d features, %d max bins",
            time.time() - t0,
            train.n_real,
            F,
            B,
        )

        # base score (reference: initPred — uniform or sample-dependent)
        if p.sample_dependent_base_prediction:
            if K > 1:
                mean = np.average(
                    np.asarray(train.y[: train.n_real]),
                    axis=0,
                    weights=np.asarray(train.weight[: train.n_real]),
                )
                base = self.loss.pred2score(jnp.asarray(mean))
                base_np = np.asarray(base, np.float32)
            else:
                mean = float(
                    np.average(
                        train.y[: train.n_real], weights=train.weight[: train.n_real]
                    )
                )
                base_np = np.float32(self.loss.pred2score(mean))
        else:
            base_np = np.float32(self.loss.pred2score(p.uniform_base_prediction))

        model = GBDTModel(
            base_prediction=float(np.mean(base_np)),
            num_tree_in_group=K,
            obj_name=self.loss.name,
        )

        # continue_train: reload + replay scores
        start_round = 0
        model_path = p.model.data_path
        if p.model.continue_train and self.fs.exists(model_path):
            with self.fs.open(model_path) as f:
                model = GBDTModel.loads(f.read())
            start_round = len(model.trees) // K
            log.info("continue_train: loaded %d trees", len(model.trees))

        if K > 1:
            scores = jnp.full((n, K), base_np, jnp.float32)
        else:
            scores = jnp.full((n,), float(base_np), jnp.float32)
        for i, t in enumerate(model.trees):
            add = self._tree_scores_from_raw(t, bins, bins_train)
            if K > 1:
                scores = scores.at[:, i % K].add(add)
            else:
                scores = scores + add

        eval_set = EvalSet(p.eval_metric, K=max(K, 2)) if p.eval_metric else None
        rng = np.random.RandomState(20170425)
        feat_names = train.feature_names
        round_log: List[Dict] = []

        test_state = None
        if test is not None:
            bins_test = self._put(bin_matrix(test.X, bins))
            y_t = self._put(test.y)
            w_t = self._put(test.weight)
            if K > 1:
                scores_t = jnp.full((test.n, K), base_np, jnp.float32)
            else:
                scores_t = jnp.full((test.n,), float(base_np), jnp.float32)
            for i, t in enumerate(model.trees):
                add = self._tree_scores_from_raw(t, bins, bins_test)
                if K > 1:
                    scores_t = scores_t.at[:, i % K].add(add)
                else:
                    scores_t = scores_t + add
            test_state = (bins_test, y_t, w_t, scores_t)

        if p.just_evaluate:
            return self._finalize(
                model, scores, y, weight, test_state, eval_set, round_log, bins
            )

        for rnd in range(start_round, p.round_num):
            # fast-path grads from predictions (reference:
            # ILossFunction.getDerivativeFast, GBDTOptimizer:513)
            preds = self.loss.predict(scores)
            gs, hs = self.loss.grad_hess(preds, y)
            # instance sampling + weight fold-in
            inst = (rng.rand(n) <= p.instance_sample_rate).astype(np.float32)
            inst[train.n_real :] = 0.0
            pos0 = jnp.asarray(np.where(inst > 0, 0, -1).astype(np.int32))
            fmask = (rng.rand(F) <= p.feature_sample_rate).astype(bool)
            if not fmask.any():
                fmask[rng.randint(F)] = True
            fmask_dev = jnp.asarray(fmask)

            for grp in range(K):
                g = (gs[:, grp] if K > 1 else gs) * weight
                h = (hs[:, grp] if K > 1 else hs) * weight
                if p.tree_grow_policy == "loss":
                    tree = self.build_tree_loss_wise(
                        bins_train, g, h, pos0, F, B, fmask_dev, feat_names
                    )
                else:
                    tree = self.build_tree_level_wise(
                        bins_train, g, h, pos0, F, B, fmask_dev, feat_names
                    )
                if self.loss.name == "l1" and K == 1:
                    self._refine_lad(tree, bins_train, y, scores, weight)
                add = self._tree_scores_dev(tree, bins_train)
                if K > 1:
                    scores = scores.at[:, grp].add(add)
                else:
                    scores = scores + add
                if test_state is not None:
                    add_t = self._tree_scores_dev(tree, test_state[0])
                    bins_test, y_t, w_t, scores_t = test_state
                    if K > 1:
                        scores_t = scores_t.at[:, grp].add(add_t)
                    else:
                        scores_t = scores_t + add_t
                    test_state = (bins_test, y_t, w_t, scores_t)
                self._convert_tree(tree, bins)
                model.trees.append(tree)

            rec = {"round": rnd, "elapsed": time.time() - t0}
            rec["train_loss"] = self._avg_loss(scores, y, weight)
            if test_state is not None:
                rec["test_loss"] = self._avg_loss(
                    test_state[3], test_state[1], test_state[2]
                )
            if eval_set is not None and (p.watch_train or p.watch_test or rnd == p.round_num - 1):
                if p.watch_train:
                    rec["train_metrics"] = eval_set.evaluate(
                        self.loss.predict(scores), y, weight
                    )
                if p.watch_test and test_state is not None:
                    rec["test_metrics"] = eval_set.evaluate(
                        self.loss.predict(test_state[3]), test_state[1], test_state[2]
                    )
            round_log.append(rec)
            log.info(
                "[round=%d] %.1fs train loss=%.6f%s",
                rnd,
                rec["elapsed"],
                rec["train_loss"],
                f" test loss={rec['test_loss']:.6f}" if "test_loss" in rec else "",
            )

            if p.model.dump_freq > 0 and (rnd + 1) % p.model.dump_freq == 0:
                self._dump_model(model)

        self._dump_model(model)
        return self._finalize(
            model, scores, y, weight, test_state, eval_set, round_log, bins
        )

    # -- helpers ----------------------------------------------------------

    def _avg_loss(self, scores, y, weight) -> float:
        per = jnp.where(weight > 0, self.loss.loss(scores, y), 0.0)
        return float(jnp.sum(weight * per) / jnp.sum(weight))

    def _convert_tree(self, tree: Tree, bins: FeatureBins) -> None:
        """Slot interval -> real split value + default direction
        (reference: GBDTOptimizer.convertModel:669 + addDefaultDirection)."""
        st = self.params.split_type
        for nid in range(tree.n_nodes()):
            if tree.is_leaf(nid):
                continue
            fid = tree.feat[nid]
            lo = tree.slot[nid]
            hi = int(tree.split[nid])
            v = bins.values[fid]
            if st == "median":
                s = lo + hi
                cond = (
                    float(v[s // 2])
                    if s % 2 == 0
                    else 0.5 * (float(v[(s - 1) // 2]) + float(v[(s + 1) // 2]))
                )
            else:
                cond = 0.5 * (float(v[lo]) + float(v[hi]))
            tree.split[nid] = cond
            # missing-value default direction from the fill value
            fill = self._missing_fill
            if fill is not None:
                tree.default_left[nid] = bool(fill[fid] <= cond)

    _missing_fill: Optional[np.ndarray] = None

    def _tree_scores_from_raw(self, tree: Tree, bins: FeatureBins, bins_dev):
        """Score a converted (value-space) tree against the bin matrix by
        re-deriving slot thresholds: bin b goes left iff its representative
        value <= cond."""
        feat = np.asarray(tree.feat, np.int32)
        slot = np.full(tree.n_nodes(), -1, np.int32)
        for nid in range(tree.n_nodes()):
            if tree.is_leaf(nid):
                continue
            fid = tree.feat[nid]
            cnt = int(bins.counts[fid])
            v = bins.values[fid, :cnt]
            slot[nid] = int(np.searchsorted(v, tree.split[nid], side="right")) - 1
        depth = max(tree.max_depth(), 1)
        return _traverse_kernel(
            bins_dev,
            jnp.asarray(feat),
            jnp.asarray(slot),
            jnp.asarray(np.asarray(tree.left, np.int32)),
            jnp.asarray(np.asarray(tree.right, np.int32)),
            jnp.asarray(np.asarray(tree.leaf_value, np.float32)),
            depth,
        )

    def _refine_lad(self, tree: Tree, bins_dev, y, scores, weight) -> None:
        """LAD leaf refinement: leaf value = lr * weighted median of
        (y - current score) over the leaf's samples (reference:
        optimizer/gbdt/TreeRefiner.java:72-123, precise mode)."""
        pos = np.asarray(self._tree_leaf_assignment(tree, bins_dev))
        resid = np.asarray(y) - np.asarray(scores)
        w = np.asarray(weight)
        lr = self.params.learning_rate
        for nid in range(tree.n_nodes()):
            if not tree.is_leaf(nid):
                continue
            m = (pos == nid) & (w > 0)
            if not m.any():
                continue
            r, ww = resid[m], w[m]
            order = np.argsort(r, kind="stable")
            cw = np.cumsum(ww[order])
            cut = 0.5 * cw[-1]
            tree.leaf_value[nid] = float(r[order][np.searchsorted(cw, cut)]) * lr

    def _tree_leaf_assignment(self, tree: Tree, bins_dev):
        feat = jnp.asarray(np.asarray(tree.feat, np.int32))
        slot = jnp.asarray(np.asarray(tree.slot, np.int32))
        left = jnp.asarray(np.asarray(tree.left, np.int32))
        right = jnp.asarray(np.asarray(tree.right, np.int32))
        depth = max(tree.max_depth(), 1)
        return _assign_kernel(bins_dev, feat, slot, left, right, depth)

    def _dump_model(self, model: GBDTModel) -> None:
        p = self.params
        with self.fs.open(p.model.data_path, "w") as f:
            f.write(model.dumps(with_stats=True))
        if p.model.feature_importance_path:
            imp = model.feature_importance()
            with self.fs.open(p.model.feature_importance_path, "w") as f:
                for name, gain in imp.items():
                    f.write(f"f_{name}:{gain}\n")

    def _finalize(
        self, model, scores, y, weight, test_state, eval_set, round_log, bins
    ) -> GBDTResult:
        res = GBDTResult(
            model=model,
            train_loss=self._avg_loss(scores, y, weight),
            test_loss=None,
            round_log=round_log,
        )
        if eval_set is not None:
            res.train_metrics = eval_set.evaluate(self.loss.predict(scores), y, weight)
        if test_state is not None:
            _, y_t, w_t, scores_t = test_state
            res.test_loss = self._avg_loss(scores_t, y_t, w_t)
            if eval_set is not None:
                res.test_metrics = eval_set.evaluate(
                    self.loss.predict(scores_t), y_t, w_t
                )
        return res


@partial(jax.jit, static_argnames=("depth",))
def _traverse_kernel(bins, feat, slot, left, right, leaf, depth: int):
    """Fixed-depth slot-space traversal: leaves self-loop via feat<0."""
    n = bins.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        f = feat[node]
        is_leaf = f < 0
        b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(b <= slot[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    node = jax.lax.fori_loop(0, depth, step, node)
    return leaf[node]


@partial(jax.jit, static_argnames=("depth",))
def _assign_kernel(bins, feat, slot, left, right, depth: int):
    n = bins.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        f = feat[node]
        is_leaf = f < 0
        b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(b <= slot[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    return jax.lax.fori_loop(0, depth, step, node)
