"""GBDT boosting trainer — histogram trees on the TPU.

Rebuild of reference optimizer/GBDTOptimizer.java (boosting driver,
:174-530) + optimizer/gbdt/DataParallelTreeMaker.java:229-653 (histogram
build, split enumeration, position update) + UpdateStrategy.java:64-83
(gain / leaf-value formulas incl. L1 soft-threshold + leaf clamp) +
TreeRefiner.java (LAD weighted-median leaves).

Two growth engines share the split/gain kernels (gbdt/engine.py):

  device (default) — the whole tree grows inside one XLA program
    (engine.make_grow_tree): Pallas one-hot-matmul histograms, on-device
    frontier selection, sibling subtraction in a device histogram pool,
    and per-round score/loss updates — zero host round-trips per round.
    Built for this machine's cost model (D2H ~115 ms per transfer).
  host — the original per-level/per-split host loop. Kept as the
    reference implementation for equivalence tests, and used
    automatically for l1 loss (LAD leaf refinement is a host-side
    weighted median, reference TreeRefiner.java:72-123).

TPU-first design notes:
  - the bin matrix lives transposed (F, n) so routing is a row
    dynamic-slice + lane compare, and the Pallas kernel reads lane-major
  - histograms are one fused MXU pass per wave; with rows sharded over a
    mesh XLA psums the partial histograms (the reduceScatterArray of
    HistogramBuilder.java:95 without hand-rolling)
  - split enumeration is a cumulative-sum scan over all (node, feature,
    bin) at once; first-max argmax reproduces SplitInfo.needReplace's
    lower-slot tie-break
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import knobs
from ..config.params import GBDTParams
from ..eval import EvalSet
from ..io.fs import FileSystem, LocalFileSystem
from ..losses import create_loss
from ..obs import (
    enabled as obs_enabled,
    event as obs_event,
    gauge as obs_gauge,
    health,
    inc as obs_inc,
    profiler,
    recorder,
    span as obs_span,
)
from ..parallel.mesh import row_sharding
from ..resilience import chaos_point, trainer_guard
from .binning import (
    FeatureBins,
    bin_matrix,
    bin_matrix_device,
    build_bins_global,
    build_bins_maybe_device,
    build_bundle_plan,
    bundle_bin_matrix_t,
)
from .data import GBDTData, GBDTIngest, column_stats
from .engine import (
    GrowSpec,
    make_gain_fns,
    make_grow_tree,
    split_kernel,
    wave_log_rows,
)
from .hist import BM_DEFAULT, pad_inputs
from .tree import GBDTModel, Tree, unbundle_tree

log = logging.getLogger("ytklearn_tpu.gbdt")


# ---------------------------------------------------------------------------
# Host-path device kernels (the original level/loss-wise implementation)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_nodes", "F", "B"))
def hist_kernel(bins, pos, g, h, n_nodes: int, F: int, B: int):
    """(n_nodes, F, B, 3) histogram of (g, h, count) by level-local node.

    pos < 0 = inactive sample -> dump segment. Scatter-add formulation —
    fine on CPU, slow on TPU (the device engine uses gbdt/hist.py)."""
    n = bins.shape[0]
    active = pos >= 0
    base = jnp.where(active, pos, n_nodes) * (F * B)
    ids = base[:, None] + jnp.arange(F)[None, :] * B + bins  # (n, F)
    vals = jnp.stack(
        [g, h, jnp.where(active, 1.0, 0.0)], axis=1
    )  # (n, 3)
    flat = jnp.zeros(((n_nodes + 1) * F * B, 3), jnp.float32)
    flat = flat.at[ids.reshape(-1)].add(
        jnp.repeat(vals, F, axis=0).reshape(n, F, 3).reshape(-1, 3)
    )
    return flat[: n_nodes * F * B].reshape(n_nodes, F, B, 3)


@jax.jit
def pos_update_kernel(bins, pos, node_feat, node_slot, node_child_base):
    """Route samples to next-level-local child indices.

    node_child_base[k] = left-child index among next level's nodes, or -1 if
    node k became a leaf (reference: SamplePositionData.resetPosition:115)."""
    safe = jnp.maximum(pos, 0)
    f = node_feat[safe]
    slot = node_slot[safe]
    base = node_child_base[safe]
    b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
    go_right = b > slot
    new = jnp.where(base >= 0, base + go_right.astype(jnp.int32), -1)
    return jnp.where(pos >= 0, new, -1)


@partial(jax.jit, static_argnames=("F", "B"))
def node_hist_kernel(bins, in_node, g, h, F: int, B: int):
    """(F, B, 3) histogram for one node's samples (host loss-wise growth)."""
    ids = jnp.where(in_node[:, None], jnp.arange(F)[None, :] * B + bins, F * B)
    vals = jnp.stack([g, h, jnp.where(in_node, 1.0, 0.0)], axis=1)
    n = bins.shape[0]
    flat = jnp.zeros((F * B + 1, 3), jnp.float32)
    flat = flat.at[ids.reshape(-1)].add(
        jnp.repeat(vals, F, axis=0).reshape(n, F, 3).reshape(-1, 3)
    )
    return flat[: F * B].reshape(F, B, 3)


# ---------------------------------------------------------------------------
# The trainer
# ---------------------------------------------------------------------------


@dataclass
class _DevInputs:
    """Device-resident training inputs prepared once per run (the device
    engine's CoreData equivalent): transposed/padded bin matrices, labels,
    weights, and the program shapes they were padded for."""

    bins: FeatureBins
    bins_t: jnp.ndarray  # (F_prog, n_pad) transposed bin matrix
    y: jnp.ndarray
    weight: jnp.ndarray
    real_mask: jnp.ndarray
    n_score: int  # global (cross-process) padded row count
    F: int  # engine-visible real column count (EFB-bundled when active)
    F_prog: int  # feature axis padded to the mesh device count
    B: int  # bin axis padded to a power of two
    D: int  # mesh device count
    aux_bins: tuple  # () or (bins_t of the test set,)
    y_t: Optional[jnp.ndarray]
    w_t: Optional[jnp.ndarray]
    nt_score: int


@dataclass
class GBDTResult:
    model: GBDTModel
    train_loss: float
    test_loss: Optional[float]
    train_metrics: Dict[str, float] = field(default_factory=dict)
    test_metrics: Dict[str, float] = field(default_factory=dict)
    round_log: List[Dict] = field(default_factory=list)


class GBDTTrainer:
    def __init__(
        self,
        params: GBDTParams,
        mesh=None,
        fs: Optional[FileSystem] = None,
        engine: str = "auto",
        wave: Optional[int] = None,
        use_bf16_hist: bool = True,
        hist_precision: Optional[str] = None,  # bf16 | f32 | int8
        goss: Optional[Tuple[float, float]] = None,  # (a, b); a >= 1 = off
        efb: Optional[bool] = None,  # None = YTK_EFB knob
    ):
        self.params = params
        self.mesh = mesh
        self.fs = fs or LocalFileSystem()
        self.loss = create_loss(
            params.loss_function, {"sigmoid_zmax": params.sigmoid_zmax}
        )
        cfg = self._cfg()
        self.gain_fn, self.node_value_fn = make_gain_fns(*cfg)
        self.K = params.num_tree_in_group
        if engine == "auto":
            # precise LAD leaf refinement (lad_refine_appr=false) is a
            # host-side sort, so it rides the host engine; the approximate
            # default runs inside the device engine's jitted round. The
            # feature-parallel maker is a host-loop maker by design.
            engine = (
                "host"
                if (params.loss_function == "l1" and self.K == 1
                    and not params.lad_refine_appr)
                or params.tree_maker == "feature"
                else "device"
            )
        self.engine = engine
        self.wave = wave
        if hist_precision is None:
            hist_precision = "bf16" if use_bf16_hist else "f32"
        if hist_precision not in ("bf16", "f32", "int8"):
            raise ValueError(
                f"hist_precision must be bf16|f32|int8, got {hist_precision!r}"
            )
        self.hist_precision = hist_precision
        self.use_bf16_hist = hist_precision != "f32"
        # GOSS (device engine): explicit ctor pair wins, else the knobs.
        # a >= 1 disables — the engine then takes the bit-identical
        # unsampled path.
        if goss is None:
            goss = (
                knobs.get_float("YTK_GOSS_A"),
                knobs.get_float("YTK_GOSS_B"),
            )
        a, b = float(goss[0]), float(goss[1])
        if not (0.0 < a <= 1.0) or not (0.0 <= b <= 1.0):
            raise ValueError(
                f"goss=(a, b) needs 0 < a <= 1 and 0 <= b <= 1, got {goss!r}"
            )
        self.goss = (a, b)
        if a < 1.0 and self.engine == "host":
            log.warning(
                "GOSS (goss_a=%.3f) is a device-engine feature; the host "
                "engine trains unsampled", a,
            )
        self.efb = knobs.get_bool("YTK_EFB") if efb is None else bool(efb)
        if self.efb and self.engine == "host":
            # warn only on an explicit request — the knob defaults to on,
            # so every host-engine run would otherwise nag
            (log.warning if efb else log.info)(
                "EFB is a device-engine feature; the host engine trains "
                "on the unbundled bin matrix"
            )

    def _put(self, arr):
        """Row-shard dim 0. Multi-process: `arr` is this process's shard."""
        if self.mesh is None:
            return jax.device_put(arr)
        from ..parallel.mesh import put_row_sharded

        return put_row_sharded(arr, self.mesh)

    def _put_cols(self, arr):
        """Shard the trailing (sample) axis of a transposed matrix;
        multi-process: `arr` carries this process's sample columns."""
        if self.mesh is None:
            return jax.device_put(arr)
        from ..parallel.mesh import put_col_sharded

        return put_col_sharded(arr, self.mesh)

    def _cfg(self):
        p = self.params
        return (p.l1, p.l2, p.min_child_hessian_sum, p.max_abs_leaf_val)

    def _load_resume_model(self, model: GBDTModel, K: int, feature_names=None):
        """continue_train reload (reference: GBDTOptimizer.java:408 resume at
        trees/K). Rank0 reads, every rank resumes from rank0's text — dumps
        are rank0-only, so on non-shared storage other ranks would
        otherwise silently start from scratch and corrupt the run.

        Tree.parse leaves `feat` at 0 for non-numeric feature names
        ("resolved later via feature dict"); the resolution happens HERE
        against the ingest column order — without it every resumed score
        replay routed through column 0, so warm starts trained against a
        corrupted residual (found by the preemption bit-identity pin,
        tests/test_resilience.py)."""
        p = self.params
        if not p.model.continue_train:
            return model, 0
        from ..parallel.collectives import load_on_rank0

        def read():
            if not self.fs.exists(p.model.data_path):
                return None
            with self.fs.open(p.model.data_path) as f:
                return f.read()

        text = load_on_rank0(read)
        if text is None:
            return model, 0
        model = GBDTModel.loads(text)
        if feature_names:
            index = {n: i for i, n in enumerate(feature_names)}
            for t in model.trees:
                for nid in range(t.n_nodes()):
                    if t.is_leaf(nid):
                        continue
                    fid = index.get(t.feat_name[nid])
                    if fid is not None:
                        t.feat[nid] = fid
                    elif not t.feat_name[nid].isdigit():
                        raise ValueError(
                            f"continue_train: dumped split feature "
                            f"{t.feat_name[nid]!r} is not in this run's "
                            "feature set — resuming on different data?"
                        )
        log.info("continue_train: loaded %d trees", len(model.trees))
        return model, len(model.trees) // K

    def _shard_target(self, bins_np) -> Optional[int]:
        """Mesh>1: pad rows so the sample axis splits evenly across all mesh
        devices AND each device shard is Pallas-tileable (bm-divisible on
        TPU; a small multiple suffices for the dense CPU path). Multi-
        process: cross-process equalized target. Single device: None =
        pad_inputs' default bm rounding."""
        if self.mesh is not None and (
            jax.process_count() > 1 or self.mesh.devices.size > 1
        ):
            from ..parallel.mesh import equal_row_target

            mult = BM_DEFAULT if jax.default_backend() == "tpu" else 8
            return equal_row_target(bins_np.shape[0], self.mesh, multiple=mult)
        return None

    # -- entry ------------------------------------------------------------

    def train(
        self,
        train: Optional[GBDTData] = None,
        test: Optional[GBDTData] = None,
    ) -> GBDTResult:
        # preemption-safe: SIGTERM/SIGINT defer to the next round
        # boundary, where the loop dumps an emergency checkpoint through
        # the ordinary atomic dump path and raises Preempted — `--resume
        # auto` re-enters here via continue_train (docs/fault_tolerance.md)
        with trainer_guard(self):
            if self.engine == "device":
                return self._train_device(train, test)
            if jax.process_count() > 1:
                raise ValueError(
                    "multi-process GBDT training requires the device engine "
                    "(host-loop makers read per-row device state eagerly); "
                    f"got engine={self.engine!r}"
                )
            return self._train_host(train, test)

    # ======================================================================
    # DEVICE ENGINE
    # ======================================================================

    def _grow_spec(self, F: int, B: int, goss_scale: float = 1.0) -> GrowSpec:
        p = self.params
        caps = []
        if p.max_leaf_cnt > 0:
            caps.append(2 * p.max_leaf_cnt - 1)
        if p.max_depth > 0:
            caps.append(2 ** (p.max_depth + 1) - 1)
        if not caps:
            raise ValueError("gbdt needs optimization.max_depth or max_leaf_cnt")
        M = min(caps)
        if self.wave is not None:
            NW = self.wave
        else:
            # 64 measured fastest at Higgs scale (r5, 40-tree runs: 1.218
            # vs 1.160 trees/s at 32, quality inside the band): the hist
            # kernel is VPU-bound on the one-hot builds at narrow waves —
            # (4N+B)*bm VPU ops vs 3N*B*bm MACs per block — so wider waves
            # raise MXU utilization; 128 over-relaxes best-first and pays
            # for unused frontier slots (1.098 trees/s, worse AUC)
            NW = 64
        NW = max(1, min(NW, (M + 1) // 2))
        # dense einsum only where Mosaic can't compile (CPU tests / virtual
        # mesh); mesh>1 runs the SAME Pallas kernels per shard under
        # shard_map (r3 VERDICT #1: no more force_dense on multi-chip)
        force_dense = jax.default_backend() != "tpu"
        # leaf-partitioned histogram phases: DEFAULT-ON everywhere since r6
        # (the fused compact+gather+histogram kernel makes late-tree waves
        # O(wave rows) on TPU too — r5 shipped this opt-in because the XLA
        # row gather lost money there). YTK_PARTITION=0 or YTK_NO_PARTITION=1
        # turns it off, so an A/B "off" run can never silently run
        # partitioned; YTK_PARTITION=1 stays accepted (now a no-op).
        partition = (
            not knobs.get_bool("YTK_NO_PARTITION")
            and knobs.get_bool("YTK_PARTITION")
        )
        # budget ladder divisors: the TPU default routes only genuinely
        # late waves (<= n/64 rows) into partitioned passes, all through
        # the fused kernel — the XLA-gather rungs at n/8, n/32 measured as
        # net losers on TPU in r5 and stay off the default there. The CPU
        # dense path keeps the r5 ladder (gathers are cheap on CPU).
        # YTK_LADDER / YTK_FUSED / YTK_FUSED_MAX_ROWS override for tuning.
        ladder_env = knobs.get_str("YTK_LADDER")
        if ladder_env:
            ladder = tuple(int(x) for x in ladder_env.split(",") if x.strip())
        else:
            ladder = (8, 32) if force_dense else (64, 256)
        fused = knobs.get_bool("YTK_FUSED")
        fused_max_rows = knobs.get_int("YTK_FUSED_MAX_ROWS")
        return GrowSpec(
            F=F,
            B=B,
            max_nodes=M,
            wave=NW,
            policy=p.tree_grow_policy,
            max_depth=p.max_depth,
            max_leaves=p.max_leaf_cnt,
            lr=p.learning_rate,
            l1=p.l1,
            l2=p.l2,
            min_h=p.min_child_hessian_sum,
            max_abs=p.max_abs_leaf_val,
            min_split_loss=p.min_split_loss,
            min_split_samples=float(p.min_split_samples),
            use_bf16=self.use_bf16_hist,
            force_dense=force_dense,
            hist_mode="int8" if self.hist_precision == "int8" else "mxu",
            partition=partition,
            ladder=ladder,
            fused=fused,
            fused_max_rows=fused_max_rows,
            goss_a=self.goss[0],
            goss_b=self.goss[1],
            goss_scale=goss_scale,
        )

    def _prep_device_inputs(self, train: GBDTData, test: Optional[GBDTData]):
        """Binning + padding + device placement for the device engine.

        Returns a _DevInputs with the transposed/padded bin matrices (and
        test-set twins), label/weight/real-row arrays, and the padded
        feature count F_prog the growth program is shaped for."""
        p = self.params
        n_real, F = train.n_real, train.n_features
        self._missing_fill = train.missing_fill

        log.info("building bins (%d features)...", F)
        # single-device: bin on the TPU (sort + rank-pick + compare-count);
        # the host path costs ~4s/feature at 10M rows (reference load+
        # preprocess budget: 35s, docs/gbdt_experiments.md)
        use_dev_bin = (
            self.mesh is None or self.mesh.devices.size == 1
        ) and jax.process_count() == 1
        if use_dev_bin:
            X_t_dev = jnp.transpose(jax.device_put(train.X))  # (F, n) real rows
            bins = build_bins_maybe_device(
                train.X, X_t_dev, train.weight, p, train.feature_names
            )
        else:
            X_t_dev = None
            bins = build_bins_global(train.X, train.weight, p, train.feature_names)
        B_real = bins.max_bins
        B = max(8, 1 << (B_real - 1).bit_length())  # pad to pow2 for tiling
        # EFB: merge mutually-exclusive sparse columns into offset-binned
        # bundles BEFORE the matrix reaches HBM. Bundles are capped at the
        # padded bin width B, so the histogram shape never grows; the
        # engine's range tables + tree unbundling keep splits (and every
        # dumped model) in original feature space. Warm starts
        # (continue_train) stay bundled: the incumbent's score replay runs
        # on a transient PRE-bundle matrix (original feature space), so
        # re-bundling is exact — see _init_device_scores. Only the
        # multi-process case downgrades (the plan would need a cross-
        # process conflict merge), and it does so loudly: an operator who
        # asked for EFB must see the fallback in logs AND obs.
        plan = None
        if self.efb and jax.process_count() > 1:
            log.warning(
                "EFB disabled: multi-process runs would need a cross-"
                "process conflict merge; training unbundled"
            )
            obs_inc("gbdt.efb.downgrade")
            obs_event("gbdt.efb.downgrade", reason="multi_process")
        elif self.efb:
            budget = knobs.get_int("YTK_EFB_CONFLICT")
            with obs_span("gbdt.efb.plan", F=F):
                if use_dev_bin:
                    plan = build_bundle_plan(X_t_dev, bins, budget, B)
                else:
                    nnz, mins = column_stats(train.X)
                    plan = build_bundle_plan(
                        train.X.T, bins, budget, B, nnz=nnz, mins=mins
                    )
            if plan is not None:
                log.info("EFB: %s (conflict budget %d)", plan.summary(), budget)
                obs_inc("gbdt.efb.bundles", len(plan.bundles))
                obs_inc("gbdt.efb.features_bundled", plan.n_bundled_features)
                obs_gauge("gbdt.stat.efb_cols_saved", float(F - plan.n_cols))
        self._efb_plan = plan
        # serve-side binned scoring reads these back from the dumped
        # sidecar (`<data_path>.bins.json`); edges are per ORIGINAL
        # feature, pre-EFB, like the dumped trees
        self._bins_sidecar = (list(train.feature_names or []), bins)
        self._quality_features = self._build_quality_features(train)
        F_cols = plan.n_cols if plan is not None else F
        # mesh>1: the growth program runs under shard_map with each device
        # owning a contiguous feature slice of the histograms — pad the
        # feature axis so it divides evenly (padded features: all rows in
        # bin 0 + masked off, so they can never split)
        D = 1 if self.mesh is None else int(self.mesh.devices.size)
        F_prog = -(-F_cols // D) * D
        # warm-start + EFB: the incumbent's trees split on ORIGINAL feature
        # ids, so the score replay needs the pre-bundle matrix; keep it as
        # a transient (n_pad, F) row matrix that _init_device_scores frees
        # right after the replay
        keep_replay = plan is not None and p.model.continue_train
        self._replay_bins = None
        if use_dev_bin:
            n_rows = train.X.shape[0]
            n_pad = -(-n_rows // BM_DEFAULT) * BM_DEFAULT
            Xp = jnp.pad(X_t_dev, ((0, 0), (0, n_pad - n_rows)))
            bins_t_raw = bin_matrix_device(Xp, bins)
            bins_t = (
                bundle_bin_matrix_t(bins_t_raw, plan)
                if plan is not None
                else bins_t_raw
            )
            if B <= 256:
                bins_t = bins_t.astype(jnp.uint8)  # quarter the routing/DMA
            if keep_replay:
                self._replay_bins = [jnp.transpose(bins_t_raw)]
            del X_t_dev, Xp, bins_t_raw
        else:
            bins_np_raw = bin_matrix(train.X, bins)
            if plan is not None:
                bins_np = np.asarray(
                    bundle_bin_matrix_t(bins_np_raw.T, plan)
                ).T
            else:
                bins_np = bins_np_raw
            bins_t_np, n_pad = pad_inputs(
                bins_np, n_pad=self._shard_target(bins_np), F_pad=F_prog
            )
            bins_t = self._put_cols(bins_t_np)
            if keep_replay:
                self._replay_bins = [
                    self._put(
                        _pad0(bins_np_raw.astype(np.int32), n_pad)
                    )
                ]
            del bins_np_raw
        y = self._put(_pad0(train.y, n_pad))
        weight = self._put(_pad0(train.weight, n_pad))
        real_mask = self._put(np.arange(n_pad) < train.X.shape[0])
        # global row count (the score/tree program shapes); n_pad stays the
        # per-process shard length
        n_score = n_pad * jax.process_count()

        aux_bins = ()
        y_t = w_t = None
        nt_score = 0
        if test is not None:
            if use_dev_bin:
                nt = test.X.shape[0]
                nt_pad = -(-nt // BM_DEFAULT) * BM_DEFAULT
                Xt_t = jnp.pad(
                    jnp.transpose(jax.device_put(test.X)), ((0, 0), (0, nt_pad - nt))
                )
                bt_raw = bin_matrix_device(Xt_t, bins)
                bt_dev = (
                    bundle_bin_matrix_t(bt_raw, plan)
                    if plan is not None
                    else bt_raw
                )
                if B <= 256:
                    bt_dev = bt_dev.astype(jnp.uint8)
                aux_bins = (bt_dev,)
                if keep_replay:
                    self._replay_bins.append(jnp.transpose(bt_raw))
                del Xt_t, bt_dev, bt_raw
            else:
                bins_test_raw = bin_matrix(test.X, bins)
                if plan is not None:
                    bins_test_np = np.asarray(
                        bundle_bin_matrix_t(bins_test_raw.T, plan)
                    ).T
                else:
                    bins_test_np = bins_test_raw
                bt_np, nt_pad = pad_inputs(
                    bins_test_np, n_pad=self._shard_target(bins_test_np),
                    F_pad=F_prog,
                )
                aux_bins = (self._put_cols(bt_np),)
                if keep_replay:
                    self._replay_bins.append(
                        self._put(
                            _pad0(bins_test_raw.astype(np.int32), nt_pad)
                        )
                    )
                del bins_test_raw
            y_t = self._put(_pad0(test.y, nt_pad))
            w_t = self._put(_pad0(test.weight, nt_pad))
            nt_score = nt_pad * jax.process_count()
        log.info(
            "%d rows, %d features, %d bins (pad %d)", n_real, F, B_real, B
        )
        return _DevInputs(
            bins=bins, bins_t=bins_t, y=y, weight=weight, real_mask=real_mask,
            n_score=n_score, F=F_cols, F_prog=F_prog, B=B, D=D,
            aux_bins=aux_bins, y_t=y_t, w_t=w_t, nt_score=nt_score,
        )

    def _init_device_scores(self, model: GBDTModel, dd: "_DevInputs", base_np):
        """Base-score init + continue_train score replay through host trees."""
        K = self.K
        if K > 1:
            scores = jnp.full((dd.n_score, K), base_np, jnp.float32)
        else:
            scores = jnp.full((dd.n_score,), float(base_np), jnp.float32)
        scores_t = None
        if dd.y_t is not None:
            if K > 1:
                scores_t = jnp.full((dd.nt_score, K), base_np, jnp.float32)
            else:
                scores_t = jnp.full((dd.nt_score,), float(base_np), jnp.float32)
        if model.trees:
            # EFB warm start: the incumbent's trees split on original
            # feature ids, so replay walks the transient PRE-bundle matrix
            # (_prep_device_inputs keeps it only for this loop); bundled
            # training then proceeds on dd.bins_t as usual
            replay = getattr(self, "_replay_bins", None)
            if replay is not None:
                bins_dev = replay[0]
                bins_test_dev = replay[1] if len(replay) > 1 else None
            else:
                bins_dev = jnp.transpose(dd.bins_t)
                bins_test_dev = (
                    jnp.transpose(dd.aux_bins[0]) if dd.aux_bins else None
                )
            for i, t in enumerate(model.trees):
                add = self._tree_scores_from_raw(t, dd.bins, bins_dev)
                scores = scores.at[:, i % K].add(add) if K > 1 else scores + add
                if scores_t is not None:
                    add_t = self._tree_scores_from_raw(t, dd.bins, bins_test_dev)
                    scores_t = (
                        scores_t.at[:, i % K].add(add_t) if K > 1 else scores_t + add_t
                    )
            del bins_dev, bins_test_dev
        self._replay_bins = None  # free the pre-bundle replay matrices
        return scores, scores_t

    def _make_tree_bufs(self, M: int):
        """Whole-run tree buffers, written on device, fetched once."""
        p = self.params
        T = p.round_num * self.K
        bufs = {
            "feat": jnp.full((T, M), -1, jnp.int32),
            "slot": jnp.zeros((T, M), jnp.int32),
            "slot_r": jnp.zeros((T, M), jnp.int32),
            "left": jnp.full((T, M), -1, jnp.int32),
            "right": jnp.full((T, M), -1, jnp.int32),
            "leaf": jnp.zeros((T, M), jnp.float32),
            "gain": jnp.zeros((T, M), jnp.float32),
            "hess": jnp.zeros((T, M), jnp.float32),
            "cnt": jnp.zeros((T, M), jnp.float32),
            "n_nodes": jnp.zeros((T,), jnp.int32),
            # per-tree wave log from grow(): [rows_scanned, rows_needed,
            # splits, hist_width, rows_sampled] per histogram pass — the
            # roofline / O(wave rows) ablation record (~10 KB per tree)
            "wlog": jnp.zeros((T, wave_log_rows(M), 5), jnp.float32),
        }
        loss_buf = jnp.zeros((p.round_num,), jnp.float32)
        tloss_buf = jnp.zeros((p.round_num,), jnp.float32)
        return bufs, loss_buf, tloss_buf

    def _make_round_step(
        self, dd: "_DevInputs", grow, has_test: bool, spec: GrowSpec,
    ):
        """Build the jitted per-round program: grads -> K tree growths ->
        score/loss updates (reference: GBDTOptimizer.doBoost:482 +
        predictAndCalcLossGrad:513 as ONE device program per round)."""
        p = self.params
        K = self.K
        F, F_prog = dd.F, dd.F_prog
        # GOSS: grow() fits on the compacted sample and routes the full
        # train matrix as its first aux set — train leaf assignment comes
        # back in aux_pos[0], the caller-supplied aux sets shift by one
        goss_on = 0.0 < spec.goss_a < 1.0
        loss_fn = self.loss
        inst_rate = p.instance_sample_rate
        feat_rate = p.feature_sample_rate
        # LAD leaf refinement on device: the approximate quantile mode
        # (reference: TreeRefiner.java GK-sketch path, lad_refine_appr=true
        # default) as a rank-grid weighted median — exact when the grid
        # covers every row (n <= _LAD_Q)
        refine_lad = loss_fn.name == "l1" and K == 1
        if refine_lad and not p.lad_refine_appr:
            log.warning(
                "lad_refine_appr=false requests the precise sort-based "
                "refine, which only the host engine implements; the device "
                "engine uses the approximate rank-grid refine instead "
                "(pass engine='host' or leave engine='auto' for precise)"
            )

        def round_step(carry, rnd, key, data):
            bins_t, y, weight, real_mask = data[:4]
            aux_bins = (data[4],) if has_test else ()
            y_t, w_t = (data[5], data[6]) if has_test else (None, None)
            scores, scores_t, bufs, loss_buf, tloss_buf = carry
            preds = loss_fn.predict(scores)
            gs, hs = loss_fn.grad_hess(preds, y)
            kf, ki, kg = jax.random.split(key, 3)
            # weight-0 rows still count in the histogram count channel
            # (weight folds into g/h only), matching the host engine and the
            # reference's per-node sample counting
            include = real_mask
            if inst_rate < 1.0:
                include &= jax.random.uniform(ki, real_mask.shape) <= inst_rate
            if feat_rate < 1.0:
                fmask = jax.random.uniform(kf, (F,)) <= feat_rate
                fmask = fmask.at[0].set(fmask[0] | ~jnp.any(fmask))
            else:
                fmask = jnp.ones((F,), bool)
            if F_prog > F:  # padded features can never be sampled
                fmask = jnp.pad(fmask, (0, F_prog - F))

            for grp in range(K):
                g = (gs[:, grp] if K > 1 else gs) * weight
                h = (hs[:, grp] if K > 1 else hs) * weight
                tr, pos, aux_pos, wlog = grow(
                    bins_t, include, g, h, fmask, aux=aux_bins,
                    key=jax.random.fold_in(kg, grp),
                )
                if goss_on:
                    pos_train, aux_pos = aux_pos[0], aux_pos[1:]
                else:
                    pos_train = pos
                if refine_lad:
                    tr = _lad_refine_device(
                        tr, pos_train, y, scores, weight, real_mask,
                        p.learning_rate,
                    )
                add = tr.leaf[pos_train]
                if K > 1:
                    scores = scores.at[:, grp].add(add)
                else:
                    scores = scores + add
                if has_test:
                    add_t = tr.leaf[aux_pos[0]]
                    if K > 1:
                        scores_t = scores_t.at[:, grp].add(add_t)
                    else:
                        scores_t = scores_t + add_t
                t_idx = rnd * K + grp
                for name in (
                    "feat", "slot", "slot_r", "left", "right",
                    "leaf", "gain", "hess", "cnt",
                ):
                    arr = getattr(tr, name)
                    bufs[name] = bufs[name].at[t_idx].set(
                        arr.astype(bufs[name].dtype)
                    )
                bufs["n_nodes"] = bufs["n_nodes"].at[t_idx].set(tr.n_nodes)
                bufs["wlog"] = bufs["wlog"].at[t_idx].set(wlog)

            per = jnp.where(weight > 0, loss_fn.loss(scores, y), 0.0)
            loss_buf = loss_buf.at[rnd].set(
                jnp.sum(weight * per) / jnp.maximum(jnp.sum(weight), 1e-12)
            )
            if has_test:
                per_t = jnp.where(w_t > 0, loss_fn.loss(scores_t, y_t), 0.0)
                tloss_buf = tloss_buf.at[rnd].set(
                    jnp.sum(w_t * per_t) / jnp.maximum(jnp.sum(w_t), 1e-12)
                )
            return (scores, scores_t, bufs, loss_buf, tloss_buf)

        return jax.jit(round_step, donate_argnums=(0,))

    def _build_round_step(self, dd: "_DevInputs", spec: GrowSpec, has_test: bool):
        ranges = None
        if self._efb_plan is not None:
            ranges = self._efb_plan.range_tables(dd.B, F_pad=dd.F_prog)
        grow = make_grow_tree(
            spec, mesh=self.mesh if dd.D > 1 else None, ranges=ranges
        )
        return self._make_round_step(dd, grow, has_test, spec)

    def _probe_compile(
        self, jit_round, carry, data, dd, has_test: bool, spec: GrowSpec,
        start_round: int,
    ):
        """AOT-compile the round program with graceful degradation (TPU
        only): a Mosaic/XLA failure in the fused or partitioned program
        downgrades to the XLA-gather partitioned program, then to the
        full-scan program — a toolchain regression costs throughput, never
        the run. Returns (callable, effective_spec); the compiled object
        is reused for every round, so the probe is not a second compile.
        YTK_PARTITION_STRICT=1 keeps failures loud (equivalence runs)."""
        if (
            jax.default_backend() != "tpu"
            or knobs.get_bool("YTK_PARTITION_STRICT")
        ):
            return jit_round, spec
        import dataclasses

        args = (
            carry,
            jnp.asarray(start_round),
            jax.random.fold_in(jax.random.PRNGKey(20170425), start_round),
            data,
        )
        downgrades = []
        if spec.partition and spec.fused:
            downgrades.append(
                ({"fused": False}, "XLA-gather partitioned phases", "fused_to_xla")
            )
        if spec.partition:
            downgrades.append(
                ({"partition": False}, "full-scan histograms",
                 "partition_to_fullscan")
            )
        while True:
            try:
                return jit_round.lower(*args).compile(), spec
            except Exception as e:  # noqa: BLE001 — downgrade on any compile failure
                if not downgrades:
                    raise
                change, label, kind = downgrades.pop(0)
                log.warning(
                    "device round program failed to compile (%s: %.300s); "
                    "retrying with %s",
                    type(e).__name__, e, label,
                )
                # silent-Mosaic-fallback visibility: every AOT-probe
                # downgrade is a named counter + trace event, so bench JSON
                # (obs block) shows exactly which rungs were lost
                obs_inc("gbdt.downgrade.total")
                obs_inc(f"gbdt.downgrade.{kind}")
                obs_event(
                    "gbdt.downgrade", kind=kind,
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                spec = dataclasses.replace(spec, **change)
                jit_round = self._build_round_step(dd, spec, has_test)

    def _export_wave_stats(self, ts: dict, dd: "_DevInputs", spec: GrowSpec):
        """Analytic device-cost totals from the engine's wave log — the
        inputs to the bench's achieved-vs-peak MXU/HBM accounting and the
        O(wave rows) ablation record. The model counts the dominant device
        work only (histogram one-hot matmuls + routing traffic); split
        enumeration and score updates are O(nodes) / O(n) per ROUND and
        small beside them."""
        wl = self.wave_log  # (T, MW, 5)
        used = wl[..., 3] > 0
        F, B = dd.F_prog, dd.B
        bins_bytes = 1 if dd.B <= 256 else 4
        rows_scanned = float((wl[..., 0] * used).sum())
        trees_used = used.any(axis=-1)
        n_trees = float(trees_used.sum())
        goss_on = 0.0 < spec.goss_a < 1.0
        ts["hist_passes"] = float(used.sum())
        ts["hist_rows_scanned"] = rows_scanned
        ts["hist_rows_needed"] = float((wl[..., 1] * used).sum())
        # one-hot accumulation: rows x (3 * width) x B MACs per feature
        ts["hist_macs"] = float(
            (wl[..., 0] * 3.0 * wl[..., 3] * used).sum()
        ) * B * F
        # histogram pass traffic: bins row + pos/g/h per scanned row
        ts["hist_bytes"] = rows_scanned * (F * bins_bytes + 12)
        # routing: every wave re-reads each row's bins + pos, writes pos
        # (root pass routes nothing). Per-DEVICE rows, matching the wave
        # log's per-shard units and the single-chip peak comparison. The
        # fit-matrix width comes from each tree's root pass (== n per
        # shard unsampled, the compacted width under GOSS); with GOSS the
        # full train matrix ALSO routes every wave as an aux set for the
        # final leaf assignment.
        rows_per_device = dd.n_score / max(dd.D, 1)
        fit_rows = wl[:, 0, 0]  # (T,) per-shard fit width per tree
        route_waves_t = np.maximum(used.sum(axis=-1) - 1, 0)
        routed_rows = fit_rows + (rows_per_device if goss_on else 0.0)
        ts["route_bytes"] = float(
            (route_waves_t * routed_rows * trees_used).sum()
        ) * (F * bins_bytes + 8)
        ts["partition"] = bool(spec.partition)
        ts["fused"] = bool(
            spec.partition and spec.fused
            and (not spec.force_dense or spec.fused_interpret)
        )
        ts["goss"] = goss_on
        if goss_on:
            ts["goss_a"] = float(spec.goss_a)
            ts["goss_b"] = float(spec.goss_b)
            # per-shard GOSS-kept rows per tree (wave-log col 4, constant
            # within a tree) — the sampled-rows evidence next to
            # scanned/needed
            ts["goss_rows_per_tree"] = float(
                (wl[:, 0, 4] * trees_used).sum() / max(n_trees, 1.0)
            )
        self._publish_wave_obs(wl, used, goss_on)

    def _publish_wave_obs(self, wl, used, goss_on: bool = False) -> None:
        """Accumulate the wave log into obs counters ONCE PER TREE (the
        registry is the shared source bench and any report reads; the
        per-tree granularity keeps tree-level events available without a
        second device fetch — `wl` is the single end-of-run fetch)."""
        if not obs_enabled():
            return
        for t in range(wl.shape[0]):
            u = used[t]
            waves = float(u.sum())
            if not waves:
                continue
            scanned = float((wl[t, :, 0] * u).sum())
            needed = float((wl[t, :, 1] * u).sum())
            splits = float((wl[t, :, 2] * u).sum())
            sampled = float(wl[t, 0, 4])
            obs_inc("gbdt.trees")
            obs_inc("gbdt.waves", waves)
            obs_inc("gbdt.hist_rows_scanned", scanned)
            obs_inc("gbdt.hist_rows_needed", needed)
            obs_inc("gbdt.splits", splits)
            if goss_on:
                obs_inc("gbdt.goss.trees")
                obs_inc("gbdt.goss.rows_sampled", sampled)
            obs_event(
                "gbdt.tree", tree=t, waves=waves, rows_scanned=scanned,
                rows_needed=needed, splits=splits, rows_sampled=sampled,
            )

    def _run_rounds(
        self, jit_round, carry, data, dd, model, feature_names,
        start_round: int, has_test: bool, t0: float, ts: dict,
    ):
        """Enqueue the round programs with lagged sync + periodic dumps.

        Lagged sync: materializing a loss through this machine's device
        tunnel costs ~115 ms D2H, and fetching the CURRENT round's value
        stalls the enqueue pipeline for exactly that long every sync. At
        each sync point we enqueue a tiny on-device slice of the loss and
        materialize it one sync window LATER — by then it completed long
        ago, so the float() costs one RTT of host time with zero device
        idle (the queue stays ~2 windows deep; watch mode keeps the
        synchronous path since its metric evals fetch eagerly anyway)."""
        p = self.params
        K = self.K
        root_key = jax.random.PRNGKey(20170425)
        sync_every = max(1, (p.round_num - start_round) // 20)
        watch_eval = (
            EvalSet(p.eval_metric, K=max(K, 2))
            if p.eval_metric and (p.watch_train or p.watch_test)
            else None
        )
        self.sync_log: List[Tuple[int, float]] = []  # (round, wall s) at syncs
        # retrace alarm: the round program is AOT-compiled, so any XLA
        # compile counted after the FIRST sync (warmup: eval/predict jits)
        # is an unexpected recompilation — a retrace storm shows up here
        # instead of as silently-tripled round times
        self._retrace = health.RetraceSentinel("gbdt.rounds")
        # retrace culprit vocabulary: the sentinel arms/checks with the
        # CURRENT round-call signature (late-binding closure over `carry`)
        # so a fired health.retrace names the argument/dim that moved;
        # computed only at sync cadence, and only with ytkprof on
        self._retrace_sig = (
            (lambda: profiler.abstract_signature(carry, data))
            if profiler.enabled()
            else None
        )
        profile_dir = knobs.get_str("YTK_PROFILE_DIR")
        if profile_dir:
            jax.profiler.start_trace(profile_dir)
        t_train0 = time.time()
        pending: Optional[
            Tuple[int, jnp.ndarray, Optional[jnp.ndarray], float]
        ] = None
        for rnd in range(start_round, p.round_num):
            if self._guard is not None and self._guard.triggered:
                # round boundary = the safe preemption point: fetching the
                # tree buffers drains every enqueued round, so the dump
                # holds exactly the completed rounds and the resumed run
                # re-enters at `rnd` bit-identically (round-indexed RNG)
                self._preempt_checkpoint(
                    model, carry[2], dd.bins, feature_names, rnd
                )
            # enqueue-side span: the round program is async, so this
            # measures dispatch (device time shows up in the sync spans)
            with obs_span("gbdt.round", round=rnd), profiler.LEDGER.program(
                "gbdt.round",
                sig_fn=lambda: profiler.abstract_signature(carry, data),
            ):
                carry = jit_round(
                    carry, jnp.asarray(rnd), jax.random.fold_in(root_key, rnd), data
                )
            obs_inc("gbdt.rounds")
            if (rnd + 1) % sync_every == 0 or rnd == p.round_num - 1:
                if watch_eval is None:
                    nxt = (
                        rnd,
                        carry[3][rnd],
                        carry[4][rnd] if has_test else None,
                        time.time(),  # sync-point host time, not emission
                    )
                    if pending is not None:
                        self._emit_sync(pending, t0)
                    pending = nxt
                else:
                    self._sync_report(rnd, carry, dd, watch_eval, t0)
            if p.model.dump_freq > 0 and (rnd + 1) % p.model.dump_freq == 0:
                self._append_trees_from_bufs(
                    model, carry[2], dd.bins, feature_names,
                    len(model.trees), (rnd + 1) * K,
                )
                self._dump_model(model)
        if pending is not None:
            self._emit_sync(pending, t0)

        if profile_dir:
            jax.block_until_ready(carry[0])
            jax.profiler.stop_trace()
            log.info("jax profiler trace written to %s", profile_dir)
        ts["train"] = time.time() - t_train0
        if self.sync_log:
            # skip the first sync window: it absorbs the one-time XLA compile
            r0, s0 = self.sync_log[1] if len(self.sync_log) >= 3 else self.sync_log[0]
            r1, s1 = self.sync_log[-1]
            if r1 > r0:
                ts["trees_per_sec_steady"] = (r1 - r0) * K / max(s1 - s0, 1e-9)
        return carry

    def _train_device(
        self, train: Optional[GBDTData], test: Optional[GBDTData]
    ) -> GBDTResult:
        p = self.params
        t0 = time.time()
        ts = self.time_stats = {}  # TimeStats equivalent (data/gbdt/TimeStats.java)
        recorder.auto_install()
        recorder.set_config_fingerprint(p)
        health.install_trace_counters()
        if train is None:
            with profiler.phase("gbdt.load"):
                train, test = GBDTIngest(p, self.fs).load()
        ts["load"] = time.time() - t0
        health.record_memory("gbdt.load")
        K = self.K

        with profiler.phase("gbdt.preprocess", F=train.n_features):
            dd = self._prep_device_inputs(train, test)
        health.record_memory("gbdt.preprocess")
        bins = dd.bins
        y, weight, y_t, w_t = dd.y, dd.weight, dd.y_t, dd.w_t
        ts["preprocess"] = time.time() - t0 - ts["load"]
        log.info("load+preprocess %.1fs", time.time() - t0)

        # GOSS sizing discounts sample-axis padding (real-row fraction of
        # the per-process padded shard; top_k needs a static k, so the
        # engine can't count real rows itself)
        n_pad_local = dd.n_score // max(jax.process_count(), 1)
        goss_scale = min(1.0, train.n_real / max(n_pad_local, 1))
        spec = self._grow_spec(dd.F_prog, dd.B, goss_scale=goss_scale)

        base_np = self._base_score(train, K)
        model = GBDTModel(
            base_prediction=float(np.mean(base_np)),
            num_tree_in_group=K,
            obj_name=self.loss.name,
        )
        model, start_round = self._load_resume_model(
            model, K, feature_names=train.feature_names
        )
        scores, scores_t = self._init_device_scores(model, dd, base_np)
        bufs, loss_buf, tloss_buf = self._make_tree_bufs(spec.max_nodes)

        has_test = test is not None
        # big arrays ride as explicit args (closure capture would bake them
        # into the program as constants); test arrays fold into `data`
        data = (dd.bins_t, y, weight, dd.real_mask) + (
            (dd.aux_bins[0], y_t, w_t) if has_test else ()
        )
        jit_round = self._build_round_step(dd, spec, has_test)

        if p.just_evaluate:
            return self._finalize_device(
                model, bins, scores, y, weight, scores_t, y_t, w_t,
                bufs, loss_buf, tloss_buf, start_round, train.feature_names, t0,
                trained_rounds=start_round,
            )

        carry = (scores, scores_t, bufs, loss_buf, tloss_buf)
        # compile probe gets its own phase (it dominates short runs —
        # without it the ytkprof wall-time decomposition can't hit its
        # coverage bar) and a ledger label so every backend compile of
        # the round program lands named, with its argument signature
        with profiler.phase("gbdt.compile"), profiler.LEDGER.program(
            "gbdt.round",
            sig_fn=lambda: profiler.abstract_signature(carry, data),
        ):
            jit_round, spec = self._probe_compile(
                jit_round, carry, data, dd, has_test, spec, start_round
            )
        self.grow_spec = spec  # what actually ran (after any downgrade)
        with profiler.phase(
            "gbdt.train", capture=True, rounds=p.round_num - start_round
        ):
            carry = self._run_rounds(
                jit_round, carry, data, dd, model, train.feature_names,
                start_round, has_test, t0, ts,
            )
        health.record_memory("gbdt.train")
        scores, scores_t, bufs, loss_buf, tloss_buf = carry
        self.wave_log = np.asarray(jax.device_get(bufs["wlog"]))
        self._export_wave_stats(ts, dd, spec)
        t_fin = time.time()
        with profiler.phase("gbdt.finalize"):
            out = self._finalize_device(
                model, bins, scores, y, weight, scores_t, y_t, w_t,
                bufs, loss_buf, tloss_buf, start_round, train.feature_names, t0,
                trained_rounds=p.round_num,
            )
        ts["finalize"] = time.time() - t_fin
        health.record_memory("gbdt.finalize")
        log.info(
            "[time stats] load=%.1fs preprocess=%.1fs train=%.1fs "
            "finalize=%.1fs%s",
            ts["load"], ts["preprocess"], ts["train"], ts["finalize"],
            (
                f" steady={ts['trees_per_sec_steady']:.2f} trees/s"
                if "trees_per_sec_steady" in ts else ""
            ),
        )
        # mirror every scalar time_stat into the registry (gbdt.stat.*) —
        # the ONE snapshot bench roofline accounting reads, so benchmarks
        # and production runs report from the same source of truth
        for k, v in ts.items():
            if isinstance(v, (bool, int, float)):
                obs_gauge(f"gbdt.stat.{k}", float(v))
        return out

    def _health_sync(self, rnd: int, tl: float) -> None:
        """Sentinels at a pipeline sync: NaN/inf train loss (strict mode
        aborts the run with the flight-dump path) and the unexpected-retrace
        alarm — armed at the first sync, checked at every later one."""
        if not health.enabled():
            return
        health.check_loss("gbdt.sync", tl, round=rnd)
        sig_fn = getattr(self, "_retrace_sig", None)
        sig = sig_fn() if sig_fn is not None else None
        if self._retrace.baseline is None:
            self._retrace.arm(sig=sig)
        else:
            self._retrace.check(sig=sig, round=rnd)

    def _preempt_checkpoint(self, model, bufs, bins, names, rnd: int) -> None:
        """Emergency checkpoint at round boundary `rnd`, then Preempted."""
        self._append_trees_from_bufs(
            model, bufs, bins, names, len(model.trees), rnd * self.K
        )
        self._dump_model(model)
        if knobs.get_str("YTK_PROFILE_DIR"):
            # the Preempted raise skips the post-loop stop_trace: close the
            # profiler here or the very run being profiled loses its trace
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                log.warning("profiler stop at preemption failed: %s", e)
        self._guard.preempt(
            self.params.model.data_path, family="gbdt", rounds=rnd,
            trees=len(model.trees),
        )

    def _emit_sync(self, pending, t0) -> None:
        """Materialize a lagged sync record (round, loss slice[, test]).
        The logged time is the round's sync-point host timestamp carried in
        `pending` — emission happens one window later, which would skew
        absolute per-round times late (steady-state trees/s uses diffs and
        is insensitive either way)."""
        chaos_point("gbdt.sync")
        rnd, loss_dev, tloss_dev, t_sync = pending
        obs_inc("gbdt.syncs")
        with obs_span("gbdt.sync", round=rnd, lagged=True):
            tl = float(loss_dev)  # completed a window ago: one RTT, no stall
        self._health_sync(rnd, tl)
        elapsed = t_sync - t0
        self.sync_log.append((rnd, elapsed))
        msg = f"[round={rnd}] {elapsed:.1f}s train loss={tl:.6f}"
        if tloss_dev is not None:
            msg += f" test loss={float(tloss_dev):.6f}"
        log.info(msg)

    def _sync_report(self, rnd: int, carry, dd: "_DevInputs", watch_eval, t0):
        """Pipeline sync + progress line (+ watch-flag metrics at sync
        points — reference: EvalSet per round when watch_train/watch_test;
        here per sync so the enqueue pipeline stays deep between syncs).
        The final round skips the watch log: _finalize_device evaluates
        the same final scores anyway."""
        p = self.params
        chaos_point("gbdt.sync")
        obs_inc("gbdt.syncs")
        with obs_span("gbdt.sync", round=rnd, lagged=False):
            tl = float(carry[3][rnd])  # syncs the pipeline
        self._health_sync(rnd, tl)
        elapsed = time.time() - t0
        self.sync_log.append((rnd, elapsed))
        msg = f"[round={rnd}] {elapsed:.1f}s train loss={tl:.6f}"
        has_test = dd.y_t is not None
        if has_test:
            msg += f" test loss={float(carry[4][rnd]):.6f}"
        if watch_eval is not None and rnd != p.round_num - 1:
            if p.watch_train:
                m = watch_eval.evaluate(
                    self.loss.predict(carry[0]), dd.y, dd.weight
                )
                msg += " train " + " ".join(f"{k}={v:.6f}" for k, v in m.items())
            if p.watch_test and has_test:
                m = watch_eval.evaluate(
                    self.loss.predict(carry[1]), dd.y_t, dd.w_t
                )
                msg += " test " + " ".join(f"{k}={v:.6f}" for k, v in m.items())
        log.info(msg)

    def _base_score(self, train: GBDTData, K: int):
        p = self.params
        if p.sample_dependent_base_prediction:
            if jax.process_count() > 1:
                # global weighted label mean across process shards
                from ..parallel.collectives import host_allgather_objects

                w = train.weight[: train.n_real]
                y = np.asarray(train.y[: train.n_real])
                wy = (
                    (w[:, None] * y).sum(axis=0) if K > 1 else float(np.dot(w, y))
                )
                merged = host_allgather_objects((wy, float(np.sum(w))))
                tot_wy = np.sum([m[0] for m in merged], axis=0)
                tot_w = float(np.sum([m[1] for m in merged]))
                mean = tot_wy / max(tot_w, 1e-12)
                if K > 1:
                    return np.asarray(
                        self.loss.pred2score(jnp.asarray(mean)), np.float32
                    )
                return np.float32(self.loss.pred2score(float(mean)))
            if K > 1:
                mean = np.average(
                    np.asarray(train.y[: train.n_real]),
                    axis=0,
                    weights=np.asarray(train.weight[: train.n_real]),
                )
                return np.asarray(self.loss.pred2score(jnp.asarray(mean)), np.float32)
            mean = float(
                np.average(
                    train.y[: train.n_real], weights=train.weight[: train.n_real]
                )
            )
            return np.float32(self.loss.pred2score(mean))
        return np.float32(self.loss.pred2score(p.uniform_base_prediction))

    def _append_trees_from_bufs(
        self, model: GBDTModel, bufs, bins: FeatureBins, names, have: int, want: int
    ) -> None:
        """Convert device tree buffers [have, want) into host Trees."""
        if want <= have:
            return
        # slice on device first: dump_freq checkpoints fetch only the new
        # trees, not the whole (T, M) run buffers; one batched device_get
        # instead of 10 sequential fetches (D2H is ~115ms/transfer)
        host = jax.device_get({k: v[have:want] for k, v in bufs.items()})
        for i in range(want - have):
            tree = self._arrays_to_tree(
                {k: v[i] for k, v in host.items()}, bins, names
            )
            # tree sanity on the already-fetched host arrays: an empty tree
            # means boosting stopped learning; a NaN gain means the split
            # statistics went rotten on device
            health.check_tree("gbdt.tree", len(tree.gain), tree.gain, tree=have + i)
            model.trees.append(tree)

    def _arrays_to_tree(self, d: Dict[str, np.ndarray], bins, names) -> Tree:
        nn = int(d["n_nodes"])
        t = Tree()
        t.feat = [int(v) for v in d["feat"][:nn]]
        t.slot = [int(v) for v in d["slot"][:nn]]
        t.split = [float(v) for v in d["slot_r"][:nn]]  # slot-space pre-convert
        t.left = [int(v) for v in d["left"][:nn]]
        t.right = [int(v) for v in d["right"][:nn]]
        t.default_left = [True] * nn
        t.leaf_value = [float(v) for v in d["leaf"][:nn]]
        t.gain = [float(v) for v in d["gain"][:nn]]
        t.hess_sum = [float(v) for v in d["hess"][:nn]]
        t.sample_cnt = [int(round(float(v))) for v in d["cnt"][:nn]]
        if self._efb_plan is not None:
            # bundle-space (column, slot interval) -> original feature +
            # bin interval, BEFORE names and value conversion, so the
            # dumped model is indistinguishable from an unbundled run
            unbundle_tree(t, self._efb_plan)
        t.feat_name = [
            (names[f] if (names and 0 <= f < len(names)) else str(f)) if f >= 0 else ""
            for f in t.feat
        ]
        self._convert_tree(t, bins)
        return t

    def _finalize_device(
        self, model, bins, scores, y, weight, scores_t, y_t, w_t,
        bufs, loss_buf, tloss_buf, start_round, names, t0,
        trained_rounds: int,
    ) -> GBDTResult:
        p = self.params
        K = self.K
        self._append_trees_from_bufs(
            model, bufs, bins, names, len(model.trees), trained_rounds * K
        )
        if not p.just_evaluate:
            # held-out predictions (else train) feed the quality
            # sidecar's score block before the final dump lands
            if scores_t is not None:
                self._stash_quality_scores(scores_t, w_t)
            else:
                self._stash_quality_scores(scores, weight)
            self._dump_model(model)

        eval_set = EvalSet(p.eval_metric, K=max(K, 2)) if p.eval_metric else None
        res = GBDTResult(
            model=model,
            train_loss=float(_wavg_loss(self.loss, scores, y, weight)),
            test_loss=(
                float(_wavg_loss(self.loss, scores_t, y_t, w_t))
                if scores_t is not None
                else None
            ),
        )
        loss_np = np.asarray(loss_buf)
        tloss_np = np.asarray(tloss_buf)
        for rnd in range(start_round, trained_rounds):
            rec = {"round": rnd, "train_loss": float(loss_np[rnd])}
            if scores_t is not None:
                rec["test_loss"] = float(tloss_np[rnd])
            res.round_log.append(rec)
        if eval_set is not None:
            res.train_metrics = eval_set.evaluate(
                self.loss.predict(scores), y, weight
            )
            if scores_t is not None:
                res.test_metrics = eval_set.evaluate(
                    self.loss.predict(scores_t), y_t, w_t
                )
        log.info(
            "training done in %.1fs: %d trees, train loss %.6f%s",
            time.time() - t0,
            len(model.trees),
            res.train_loss,
            f", test loss {res.test_loss:.6f}" if res.test_loss is not None else "",
        )
        return res

    # ======================================================================
    # HOST ENGINE (original implementation; reference for tests + LAD)
    # ======================================================================

    def _decide_split(self, chg, cl, cr, hl, hr) -> bool:
        p = self.params
        return (
            np.isfinite(chg)
            and chg > p.min_split_loss
            and cl + cr >= p.min_split_samples
            and (hl + hr) >= p.min_child_hessian_sum * 2.0
        )

    def _finish_split(self, tree, bins_meta, nid, fid, slot_l, slot_r, stats):
        """Record a split on the host tree (slot-space; converted at dump)."""
        gl, hl, cl, gr, hr, cr = stats
        tree.feat[nid] = fid
        tree.feat_name[nid] = bins_meta[fid] if bins_meta else str(fid)
        tree.slot[nid] = slot_l
        tree.split[nid] = float(slot_l)  # slot until convert
        left, right = tree.add_children(nid)
        # f32 multiply, bit-identical to the device engine's leaf values
        lr = np.float32(self.params.learning_rate)
        tree.leaf_value[left] = float(np.float32(self.node_value_fn(gl, hl)) * lr)
        tree.leaf_value[right] = float(np.float32(self.node_value_fn(gr, hr)) * lr)
        tree.hess_sum[left], tree.sample_cnt[left] = float(hl), int(cl)
        tree.hess_sum[right], tree.sample_cnt[right] = float(hr), int(cr)
        return left, right

    def build_tree_level_wise(
        self, bins_dev, g, h, pos0, F: int, B: int, feat_mask, names
    ) -> Tree:
        """Level-synchronous growth: one histogram scan + one split search +
        one position update per level (reference level policy,
        DataParallelTreeMaker.make with TreeGrowPolicy.LEVEL)."""
        p = self.params
        tree = Tree()
        pos = pos0  # level-local node index per sample (-1 inactive)
        level_nids = [0]  # tree nid per level-local index
        # root stats
        root_hist = hist_kernel(bins_dev, pos, g, h, 1, F, B)
        ghc = np.asarray(jnp.sum(root_hist, axis=(1, 2)))[0] / F  # sums counted F times
        tree.hess_sum[0], tree.sample_cnt[0] = float(ghc[1]), int(round(ghc[2]))
        tree.leaf_value[0] = float(
            np.float32(self.node_value_fn(ghc[0], ghc[1]))
            * np.float32(p.learning_rate)
        )
        cfg = self._cfg()
        max_leaves = p.max_leaf_cnt if p.max_leaf_cnt > 0 else 1 << 30
        max_depth = p.max_depth if p.max_depth > 0 else 1 << 30

        for depth in range(max_depth):
            n_nodes = len(level_nids)
            if n_nodes == 0:
                break
            n_pad = 1 << (n_nodes - 1).bit_length()  # pad node count: few shapes
            hist = hist_kernel(bins_dev, pos, g, h, n_pad, F, B)
            out = split_kernel(hist, feat_mask, cfg)
            (chg, flat_idx, slot_l, GL, HL, CL, GR, HR, CR) = (
                np.asarray(o) for o in out
            )

            node_feat = np.full((n_pad,), -1, np.int32)
            node_slot = np.full((n_pad,), 0, np.int32)
            child_base = np.full((n_pad,), -1, np.int32)
            next_nids: List[int] = []
            leaves_after = tree.leaf_cnt()
            for k in range(n_nodes):
                nid = level_nids[k]
                can = (
                    depth < max_depth
                    and leaves_after + 1 < max_leaves + 1
                    and self._decide_split(chg[k], CL[k], CR[k], HL[k], HR[k])
                )
                if not can:
                    continue
                fid = int(flat_idx[k]) // B
                slot_right = int(flat_idx[k]) % B
                left, right = self._finish_split(
                    tree,
                    names,
                    nid,
                    fid,
                    int(slot_l[k]),
                    slot_right,
                    (GL[k], HL[k], CL[k], GR[k], HR[k], CR[k]),
                )
                tree.gain[nid] = float(chg[k])
                # store the interval's right end for split-value conversion
                tree.slot[nid] = int(slot_l[k])
                tree.split[nid] = float(slot_right)
                node_feat[k] = fid
                node_slot[k] = int(slot_l[k])
                child_base[k] = len(next_nids)
                next_nids.extend([left, right])
                leaves_after = tree.leaf_cnt()
            if not next_nids:
                break
            pos = pos_update_kernel(
                bins_dev,
                pos,
                jnp.asarray(node_feat),
                jnp.asarray(node_slot),
                jnp.asarray(child_base),
            )
            level_nids = next_nids

        return tree

    def build_tree_loss_wise(
        self, bins_dev, g, h, pos_active, F: int, B: int, feat_mask, names
    ) -> Tree:
        """Best-first growth with per-node histograms + sibling subtraction
        (reference TreeGrowPolicy.LOSS + HistogramPool)."""
        p = self.params
        tree = Tree()
        cfg = self._cfg()
        # tree_pos: tree nid per sample (-1 = excluded by instance sampling)
        tree_pos = jnp.where(pos_active >= 0, 0, -1)

        root_hist = node_hist_kernel(bins_dev, tree_pos >= 0, g, h, F, B)
        hists: Dict[int, jnp.ndarray] = {0: root_hist}
        s = np.asarray(jnp.sum(root_hist[..., :], axis=(0, 1)))  # counted once per f
        Gt, Ht, Ct = s[0] / F, s[1] / F, s[2] / F
        tree.hess_sum[0], tree.sample_cnt[0] = float(Ht), int(round(Ct))
        tree.leaf_value[0] = float(
            np.float32(self.node_value_fn(Gt, Ht)) * np.float32(p.learning_rate)
        )

        def best_of(nid):
            out = split_kernel(hists[nid][None], feat_mask, cfg)
            return tuple(np.asarray(o)[0] for o in out)

        frontier = {0: best_of(0)}
        max_leaves = p.max_leaf_cnt if p.max_leaf_cnt > 0 else 1 << 30
        depth_of = {0: 0}
        max_depth = p.max_depth if p.max_depth > 0 else 1 << 30

        while tree.leaf_cnt() < max_leaves:
            # pick the best expandable frontier node
            cand = [
                (v[0], nid)
                for nid, v in frontier.items()
                if depth_of[nid] < max_depth
                and self._decide_split(v[0], v[5], v[8], v[4], v[7])
            ]
            if not cand:
                break
            chg, nid = max(cand, key=lambda t: (t[0], -t[1]))
            (c, flat_idx, slot_l, GL, HL, CL, GR, HR, CR) = frontier.pop(nid)
            fid = int(flat_idx) // B
            slot_right = int(flat_idx) % B
            left, right = self._finish_split(
                tree, names, nid, fid, int(slot_l), slot_right, (GL, HL, CL, GR, HR, CR)
            )
            tree.gain[nid] = float(c)
            tree.slot[nid] = int(slot_l)
            tree.split[nid] = float(slot_right)
            depth_of[left] = depth_of[right] = depth_of[nid] + 1

            # route samples of nid to children
            b = jnp.take_along_axis(bins_dev, jnp.full((bins_dev.shape[0], 1), fid), 1)[:, 0]
            in_nid = tree_pos == nid
            tree_pos = jnp.where(
                in_nid, jnp.where(b > int(slot_l), right, left), tree_pos
            )

            # smaller child by scan; sibling by subtraction (HistogramPool)
            small, big = (left, right) if CL <= CR else (right, left)
            small_hist = node_hist_kernel(bins_dev, tree_pos == small, g, h, F, B)
            parent_hist = hists.pop(nid)
            hists[small] = small_hist
            hists[big] = parent_hist - small_hist
            frontier[small] = best_of(small)
            frontier[big] = best_of(big)

        return tree

    def _tree_scores_dev(self, tree: Tree, bins_dev) -> jnp.ndarray:
        """Slot-space tree traversal on device (bin <= slot goes left)."""
        feat = jnp.asarray(np.asarray(tree.feat, np.int32))
        slot = jnp.asarray(np.asarray(tree.slot, np.int32))
        left = jnp.asarray(np.asarray(tree.left, np.int32))
        right = jnp.asarray(np.asarray(tree.right, np.int32))
        leaf = jnp.asarray(np.asarray(tree.leaf_value, np.float32))
        depth = max(tree.max_depth(), 1)
        return _traverse_kernel(bins_dev, feat, slot, left, right, leaf, depth)

    # -- host boosting -----------------------------------------------------

    def _train_host(
        self,
        train: Optional[GBDTData] = None,
        test: Optional[GBDTData] = None,
    ) -> GBDTResult:
        p = self.params
        t0 = time.time()
        if train is None:
            train, test = GBDTIngest(p, self.fs).load()
        if self.mesh is not None:
            train = train.pad_rows(self.mesh.devices.size)
            test = test.pad_rows(self.mesh.devices.size) if test else None
        n, F = train.X.shape
        K = self.K

        self._missing_fill = train.missing_fill
        log.info("building bins (%d features)...", F)
        bins = build_bins_global(train.X, train.weight, p, train.feature_names)
        self._bins_sidecar = (list(train.feature_names or []), bins)
        self._quality_features = self._build_quality_features(train)
        B = bins.max_bins
        bins_np = bin_matrix(train.X, bins)
        bins_train = self._put(bins_np)

        feature_parallel = p.tree_maker == "feature" and self.mesh is not None
        if feature_parallel:
            # columns sharded over the mesh (FeatureParallelTreeMakerByLevel);
            # the maker is level-wise only, as in the reference
            from .feature_parallel import shard_features

            bins_t_fp, F_pad_fp = shard_features(self.mesh, bins_np)
            if p.tree_grow_policy != "level":
                log.info(
                    "tree_maker=feature grows level-wise (reference maker is "
                    "ByLevel); ignoring tree_grow_policy=%r", p.tree_grow_policy
                )
        del bins_np
        y = self._put(train.y)
        weight = self._put(train.weight)
        log.info(
            "load+preprocess %.1fs: %d rows, %d features, %d max bins",
            time.time() - t0,
            train.n_real,
            F,
            B,
        )

        base_np = self._base_score(train, K)
        model = GBDTModel(
            base_prediction=float(np.mean(base_np)),
            num_tree_in_group=K,
            obj_name=self.loss.name,
        )

        # continue_train: reload + replay scores
        model, start_round = self._load_resume_model(
            model, K, feature_names=train.feature_names
        )

        if K > 1:
            scores = jnp.full((n, K), base_np, jnp.float32)
        else:
            scores = jnp.full((n,), float(base_np), jnp.float32)
        for i, t in enumerate(model.trees):
            add = self._tree_scores_from_raw(t, bins, bins_train)
            if K > 1:
                scores = scores.at[:, i % K].add(add)
            else:
                scores = scores + add

        eval_set = EvalSet(p.eval_metric, K=max(K, 2)) if p.eval_metric else None
        rng = np.random.RandomState(20170425)
        feat_names = train.feature_names
        round_log: List[Dict] = []

        test_state = None
        if test is not None:
            bins_test = self._put(bin_matrix(test.X, bins))
            y_t = self._put(test.y)
            w_t = self._put(test.weight)
            if K > 1:
                scores_t = jnp.full((test.n, K), base_np, jnp.float32)
            else:
                scores_t = jnp.full((test.n,), float(base_np), jnp.float32)
            for i, t in enumerate(model.trees):
                add = self._tree_scores_from_raw(t, bins, bins_test)
                if K > 1:
                    scores_t = scores_t.at[:, i % K].add(add)
                else:
                    scores_t = scores_t + add
            test_state = (bins_test, y_t, w_t, scores_t)

        if p.just_evaluate:
            return self._finalize(
                model, scores, y, weight, test_state, eval_set, round_log, bins
            )

        for rnd in range(start_round, p.round_num):
            if self._guard is not None and self._guard.triggered:
                # host engine appends converted trees as it goes: the dump
                # is the checkpoint, resume re-enters at this round
                self._dump_model(model)
                self._guard.preempt(
                    p.model.data_path, family="gbdt_host", rounds=rnd,
                    trees=len(model.trees),
                )
            # fast-path grads from predictions (reference:
            # ILossFunction.getDerivativeFast, GBDTOptimizer:513)
            preds = self.loss.predict(scores)
            gs, hs = self.loss.grad_hess(preds, y)
            # instance sampling + weight fold-in
            inst = (rng.rand(n) <= p.instance_sample_rate).astype(np.float32)
            inst[train.n_real :] = 0.0
            pos0 = jnp.asarray(np.where(inst > 0, 0, -1).astype(np.int32))
            fmask = (rng.rand(F) <= p.feature_sample_rate).astype(bool)
            if not fmask.any():
                fmask[rng.randint(F)] = True
            fmask_dev = jnp.asarray(fmask)

            obs_inc("gbdt.rounds")
            for grp in range(K):
                g = (gs[:, grp] if K > 1 else gs) * weight
                h = (hs[:, grp] if K > 1 else hs) * weight
                if feature_parallel:
                    from .feature_parallel import build_tree_level_feature_parallel

                    tree = build_tree_level_feature_parallel(
                        self, self.mesh, bins_t_fp, F_pad_fp, g, h, pos0,
                        F, B, fmask_dev, feat_names,
                    )
                elif p.tree_grow_policy == "loss":
                    tree = self.build_tree_loss_wise(
                        bins_train, g, h, pos0, F, B, fmask_dev, feat_names
                    )
                else:
                    tree = self.build_tree_level_wise(
                        bins_train, g, h, pos0, F, B, fmask_dev, feat_names
                    )
                if self.loss.name == "l1" and K == 1:
                    self._refine_lad(tree, bins_train, y, scores, weight)
                add = self._tree_scores_dev(tree, bins_train)
                if K > 1:
                    scores = scores.at[:, grp].add(add)
                else:
                    scores = scores + add
                if test_state is not None:
                    add_t = self._tree_scores_dev(tree, test_state[0])
                    bins_test, y_t, w_t, scores_t = test_state
                    if K > 1:
                        scores_t = scores_t.at[:, grp].add(add_t)
                    else:
                        scores_t = scores_t + add_t
                    test_state = (bins_test, y_t, w_t, scores_t)
                self._convert_tree(tree, bins)
                model.trees.append(tree)

            rec = {"round": rnd, "elapsed": time.time() - t0}
            rec["train_loss"] = float(_wavg_loss(self.loss, scores, y, weight))
            if test_state is not None:
                rec["test_loss"] = float(
                    _wavg_loss(self.loss, test_state[3], test_state[1], test_state[2])
                )
            if eval_set is not None and (p.watch_train or p.watch_test or rnd == p.round_num - 1):
                if p.watch_train:
                    rec["train_metrics"] = eval_set.evaluate(
                        self.loss.predict(scores), y, weight
                    )
                if p.watch_test and test_state is not None:
                    rec["test_metrics"] = eval_set.evaluate(
                        self.loss.predict(test_state[3]), test_state[1], test_state[2]
                    )
            round_log.append(rec)
            log.info(
                "[round=%d] %.1fs train loss=%.6f%s",
                rnd,
                rec["elapsed"],
                rec["train_loss"],
                f" test loss={rec['test_loss']:.6f}" if "test_loss" in rec else "",
            )

            if p.model.dump_freq > 0 and (rnd + 1) % p.model.dump_freq == 0:
                self._dump_model(model)

        if test_state is not None:
            self._stash_quality_scores(test_state[3], test_state[2])
        else:
            self._stash_quality_scores(scores, weight)
        self._dump_model(model)
        return self._finalize(
            model, scores, y, weight, test_state, eval_set, round_log, bins
        )

    # -- helpers ----------------------------------------------------------

    def _convert_tree(self, tree: Tree, bins: FeatureBins) -> None:
        """Slot interval -> real split value + default direction
        (reference: GBDTOptimizer.convertModel:669 + addDefaultDirection)."""
        st = self.params.split_type
        for nid in range(tree.n_nodes()):
            if tree.is_leaf(nid):
                continue
            fid = tree.feat[nid]
            cond = bins.split_value(
                fid, tree.slot[nid], int(tree.split[nid]), split_type=st
            )
            tree.split[nid] = cond
            # missing-value default direction from the fill value
            fill = self._missing_fill
            if fill is not None:
                tree.default_left[nid] = bool(fill[fid] <= cond)

    _missing_fill: Optional[np.ndarray] = None
    _efb_plan = None  # BundlePlan when EFB merged columns this run
    _bins_sidecar = None  # (feature names, FeatureBins) for the serve sidecar
    _quality_features = None  # `<model>.sketch.json` feature block (obs/quality)
    _quality_scores = None  # held-out predictions for the sidecar score block
    _replay_bins = None  # transient pre-bundle matrices for warm-start replay
    _guard = None  # PreemptionGuard while train() runs (resilience/preempt.py)

    def _tree_scores_from_raw(self, tree: Tree, bins: FeatureBins, bins_dev):
        """Score a converted (value-space) tree against the bin matrix by
        re-deriving slot thresholds: bin b goes left iff its representative
        value <= cond."""
        feat = np.asarray(tree.feat, np.int32)
        slot = np.full(tree.n_nodes(), -1, np.int32)
        for nid in range(tree.n_nodes()):
            if tree.is_leaf(nid):
                continue
            fid = tree.feat[nid]
            cnt = int(bins.counts[fid])
            v = bins.values[fid, :cnt]
            slot[nid] = int(np.searchsorted(v, tree.split[nid], side="right")) - 1
        depth = max(tree.max_depth(), 1)
        return _traverse_kernel(
            bins_dev,
            jnp.asarray(feat),
            jnp.asarray(slot),
            jnp.asarray(np.asarray(tree.left, np.int32)),
            jnp.asarray(np.asarray(tree.right, np.int32)),
            jnp.asarray(np.asarray(tree.leaf_value, np.float32)),
            depth,
        )

    def _refine_lad(self, tree: Tree, bins_dev, y, scores, weight) -> None:
        """LAD leaf refinement: leaf value = lr * weighted median of
        (y - current score) over the leaf's samples (reference:
        optimizer/gbdt/TreeRefiner.java:72-123, precise mode)."""
        pos = np.asarray(self._tree_leaf_assignment(tree, bins_dev))
        resid = np.asarray(y) - np.asarray(scores)
        w = np.asarray(weight)
        lr = self.params.learning_rate
        for nid in range(tree.n_nodes()):
            if not tree.is_leaf(nid):
                continue
            m = (pos == nid) & (w > 0)
            if not m.any():
                continue
            r, ww = resid[m], w[m]
            order = np.argsort(r, kind="stable")
            cw = np.cumsum(ww[order])
            cut = 0.5 * cw[-1]
            tree.leaf_value[nid] = float(r[order][np.searchsorted(cw, cut)]) * lr

    def _tree_leaf_assignment(self, tree: Tree, bins_dev):
        feat = jnp.asarray(np.asarray(tree.feat, np.int32))
        slot = jnp.asarray(np.asarray(tree.slot, np.int32))
        left = jnp.asarray(np.asarray(tree.left, np.int32))
        right = jnp.asarray(np.asarray(tree.right, np.int32))
        depth = max(tree.max_depth(), 1)
        return _assign_kernel(bins_dev, feat, slot, left, right, depth)

    def _build_quality_features(self, train) -> Optional[dict]:
        """Feature block of the `<model>.sketch.json` quality sidecar
        (obs/quality.py): per-feature GK summaries + presence rates of
        the (real-row) training matrix, built once at binning time while
        the host matrix is still alive."""
        names = list(train.feature_names or [])
        if not names:
            return None
        from ..obs.quality import build_training_sketch

        n_real = getattr(train, "n_real", None) or train.X.shape[0]
        with obs_span("gbdt.quality_sketch", features=len(names)):
            return build_training_sketch(
                np.asarray(train.X[:n_real]), names,
                weight=np.asarray(train.weight[:n_real]),
            )

    def _stash_quality_scores(self, scores, weight) -> None:
        """Score distribution for the quality sidecar: predictions of the
        trained ensemble over the held-out set when one exists (else the
        training rows), padded/zero-weight rows excluded."""
        try:
            preds = np.asarray(self.loss.predict(scores))
            w = np.asarray(weight)[: preds.shape[0]]
            self._quality_scores = preds[w > 0]
        except Exception as e:  # noqa: BLE001 — sidecar evidence, never the run
            log.warning("quality score stash failed (%s: %s); the sketch "
                        "sidecar will carry no score block",
                        type(e).__name__, e)

    def _dump_model(self, model: GBDTModel) -> None:
        if jax.process_index() != 0:
            return  # rank0-only dump (reference: GBDTOptimizer.java:434-437)
        p = self.params
        model_text = model.dumps(with_stats=True)
        from .binning import model_text_digest

        digest = model_text_digest(model_text)
        if self._bins_sidecar is not None:
            # bin-edge sidecar for serve-side binned scoring — written
            # BEFORE the model so a fingerprint-watch reload (triggered by
            # the model file) always finds edges at least as fresh; the
            # embedded digest of the model text about to land lets serving
            # reject the new-edges/old-model pairing a crash between the
            # two writes would leave behind
            from .binning import bin_edges_path, dump_bin_edges

            names, bins = self._bins_sidecar
            if len(names) == len(bins.counts):
                dump_bin_edges(
                    self.fs, bin_edges_path(p.model.data_path), names, bins,
                    split_type=p.split_type,
                    model_digest=digest,
                )
        if self._quality_features is not None:
            # model-quality sidecar (`<model>.sketch.json`, obs/quality.py):
            # per-feature training sketches + (once training finished) the
            # held-out score distribution — written BEFORE the model like
            # `.bins.json`, so a fingerprint-watch reload never pairs a
            # fresh ensemble with a stale drift baseline
            from ..obs.quality import (
                build_score_block,
                dump_quality_sidecar,
                quality_sidecar_path,
            )

            payload = dict(self._quality_features)
            if self._quality_scores is not None:
                payload["score"] = build_score_block(self._quality_scores)
            dump_quality_sidecar(
                self.fs, quality_sidecar_path(p.model.data_path), payload,
                model_digest=digest,
            )
        # atomic write-then-replace: the serving registry hot-reloads this
        # file on a fingerprint watch, so a reader must never see a
        # half-written ensemble
        with self.fs.atomic_open(p.model.data_path) as f:
            f.write(model_text)
        if p.model.feature_importance_path:
            # reference format: header + name\tsum_split_count\tsum_gain
            # (dataflow/GBDTDataFlow.dumpFeatureImportance:397-415)
            imp = model.feature_importance()
            with self.fs.atomic_open(p.model.feature_importance_path) as f:
                f.write("feature_name\tsum_split_count\tsum_gain\n")
                for name, (cnt, gain) in imp.items():
                    f.write(f"{name}\t{cnt}\t{gain}\n")

    def _finalize(
        self, model, scores, y, weight, test_state, eval_set, round_log, bins
    ) -> GBDTResult:
        res = GBDTResult(
            model=model,
            train_loss=float(_wavg_loss(self.loss, scores, y, weight)),
            test_loss=None,
            round_log=round_log,
        )
        if eval_set is not None:
            res.train_metrics = eval_set.evaluate(self.loss.predict(scores), y, weight)
        if test_state is not None:
            _, y_t, w_t, scores_t = test_state
            res.test_loss = float(_wavg_loss(self.loss, scores_t, y_t, w_t))
            if eval_set is not None:
                res.test_metrics = eval_set.evaluate(
                    self.loss.predict(scores_t), y_t, w_t
                )
        return res


_LAD_Q = 4096  # rank-grid resolution for device LAD refine


def _lad_refine_device(tr, pos, y, scores, weight, real_mask, lr):
    """Approximate LAD leaf refinement inside the device round: leaf value =
    lr * weighted median of (y - score) over the leaf's rows, medians taken
    on a global rank grid of _LAD_Q sorted residuals (reference:
    optimizer/gbdt/TreeRefiner.java approximate GK mode; grid quantization
    replaces the sketch — exact when n <= _LAD_Q). One sort + one
    scatter-add per tree, no host round-trip."""
    M = tr.leaf.shape[0]
    Q = _LAD_Q
    r = y - scores
    valid = real_mask & (weight > 0)
    big = jnp.float32(3.4e38)
    rs = jnp.sort(jnp.where(valid, r, big))
    nv = jnp.sum(valid.astype(jnp.int32))
    # ranks = i*(nv-1)//(Q-1) in pure i32: i*base + i*rem//(Q-1) avoids the
    # i*(nv-1) product overflowing at n > ~500k
    i = jnp.arange(Q, dtype=jnp.int32)
    span = jnp.maximum(nv - 1, 0)
    base, rem = span // (Q - 1), span % (Q - 1)
    ranks = i * base + (i * rem) // (Q - 1)
    grid = rs[ranks]
    qi = jnp.clip(jnp.searchsorted(grid, r, side="right") - 1, 0, Q - 1)
    flat = pos * Q + qi
    w = jnp.where(valid, weight, 0.0)
    hist = jnp.zeros((M * Q,), jnp.float32).at[flat].add(w, mode="drop")
    cw = jnp.cumsum(hist.reshape(M, Q), axis=1)
    tot = cw[:, -1]
    med = grid[jnp.argmax(cw >= 0.5 * tot[:, None], axis=1)]
    is_leaf = (tr.feat == -1) & (jnp.arange(M) < tr.n_nodes)
    return tr._replace(
        leaf=jnp.where(is_leaf & (tot > 0), med * lr, tr.leaf)
    )


def _wavg_loss(loss, scores, y, weight):
    per = jnp.where(weight > 0, loss.loss(scores, y), 0.0)
    return jnp.sum(weight * per) / jnp.maximum(jnp.sum(weight), 1e-12)


def _pad0(arr: np.ndarray, n_pad: int) -> np.ndarray:
    n = arr.shape[0]
    if n == n_pad:
        return arr
    return np.pad(arr, ((0, n_pad - n),) + ((0, 0),) * (arr.ndim - 1))


@partial(jax.jit, static_argnames=("depth",))
def _traverse_kernel(bins, feat, slot, left, right, leaf, depth: int):
    """Fixed-depth slot-space traversal: leaves self-loop via feat<0."""
    n = bins.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        f = feat[node]
        is_leaf = f < 0
        b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(b <= slot[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    node = jax.lax.fori_loop(0, depth, step, node)
    return leaf[node]


@partial(jax.jit, static_argnames=("depth",))
def _assign_kernel(bins, feat, slot, left, right, depth: int):
    n = bins.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        f = feat[node]
        is_leaf = f < 0
        b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(b <= slot[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    return jax.lax.fori_loop(0, depth, step, node)
