"""Sample-position routing kernel — the SamplePositionData equivalent.

The XLA formulation (engine._route_wave) runs NW sequential full-array
passes per wave: each slot re-reads one bins row (42 MB at 10.5M rows)
AND rewrites the whole pos array — ~1.3 GB of HBM traffic per 16-slot
wave. This kernel does the whole wave in ONE pass: per sample block it
loads the block's bin rows once, resolves every slot's compare/select in
VMEM, and writes pos once (~0.3 GB per wave with uint8 bins).

Reference: SamplePositionData.resetPosition:115 (partition samples of a
split node between its children).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("bm",))
def _route_pallas(bins4, pos, valid, nid, feat, slot, lo, hi, lch, rch, bm: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, nblk = bins4.shape[0], bins4.shape[1]
    n = nblk * bm
    NW = nid.shape[0]
    pos3 = pos.reshape(nblk, 1, bm)
    # pack the per-slot scalars into one (8, NW) i32 table (SMEM-resident);
    # rows 6/7 carry the split's EFB member range [lo, hi] — a row goes
    # right only when its bin is inside the range AND above the slot
    # (plain columns pass lo=0/hi=B-1, reducing to the bin > slot compare)
    tab = jnp.stack(
        [
            valid.astype(jnp.int32),
            nid,
            feat,
            slot,
            lch,
            rch,
            lo,
            hi,
        ]
    )

    def kernel(tab_ref, bins_ref, pos_ref, out_ref):
        p = pos_ref[0, 0, :][None, :]  # (1, bm)
        newp = p
        for i in range(NW):
            f = tab_ref[2, i]
            row = bins_ref[pl.ds(f, 1), 0, 0, :]  # (1, bm), dynamic sublane
            ri = row.astype(jnp.int32)
            m = (p == tab_ref[1, i]) & (tab_ref[0, i] != 0)
            go_right = (
                (ri > tab_ref[3, i]) & (ri >= tab_ref[6, i]) & (ri <= tab_ref[7, i])
            )
            child = jnp.where(go_right, tab_ref[5, i], tab_ref[4, i])
            newp = jnp.where(m, child, newp)
        out_ref[0, 0, :] = newp[0]

    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((F, 1, 1, bm), lambda k: (0, k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda k: (k, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bm), lambda k: (k, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nblk, 1, bm), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
    )(tab, bins4, pos3).reshape(n)


def route_wave(
    bins_t, pos, valid, nid, feat, slot, lch, rch, bm: int = 8192,
    lo=None, hi=None,
):
    """One-pass wave routing; XLA fallback off-TPU (see engine._route_wave).

    bins_t: (F, n) or pre-tiled (F, nblk, 1, bm). lo/hi: optional per-slot
    EFB member-range bounds (default: unbounded, the plain bin > slot
    compare)."""
    F = bins_t.shape[0]
    NW = nid.shape[0]
    if lo is None:
        lo = jnp.zeros((NW,), jnp.int32)
    if hi is None:
        hi = jnp.full((NW,), 2**30, jnp.int32)
    if jax.default_backend() == "tpu":
        bins4 = (
            bins_t
            if bins_t.ndim == 4
            else bins_t.reshape(F, bins_t.shape[1] // bm, 1, bm)
        )
        return _route_pallas(
            bins4, pos, valid, nid,
            jnp.maximum(feat, 0), slot, lo, hi, lch, rch, bm,
        )
    from .engine import _route_wave

    bins2 = bins_t if bins_t.ndim == 2 else bins_t.reshape(F, -1)
    return _route_wave(
        bins2, pos, valid, nid, jnp.maximum(feat, 0), slot, lo, hi, lch, rch,
        NW,
    )
