"""Histogram accumulation kernels — the GBDT hot op, TPU-first.

The reference's hottest loop (HistogramBuilder.java:72-90) scatter-adds
(g, h, 1) into per-(node, feature, bin) slots. XLA scatter serializes on
TPU (measured ~1.7 s per 1M-row pass), so the TPU path instead computes
the histogram as a blocked one-hot matmul on the MXU:

    for each (feature-group, sample-block) grid step:
        P  (N, bm)  = node one-hot                  # VPU, once per block
        PV (3N, bm) = [P*g | P*h | P]               # VPU, once per block
        for f in group:                             # unrolled F_g times
            OH (B, bm)   = bin one-hot              # VPU compare vs iota
            out[f] (3N,B) += PV @ OH.T              # MXU NT-dot, f32 accum

Layouts are lane-major throughout (P (N, bm), OH (B, bm), samples always
on lanes) so no in-kernel transposes occur and no (x, 1) blocks blow up
VMEM with lane padding. Grouping features inside one grid step amortizes
the node one-hot (a 28x saving at wide waves) and the pos/g/h DMAs.
Samples whose pos is not in `node_ids` (including pos = -1 dead rows)
match no one-hot row and vanish.

bf16 operands halve MXU time; histogram sums accumulate in f32 either
way (counts stay exact — 0/1 one-hots are exact in bf16). use_bf16=False
forces true-f32 MXU passes (Precision.HIGHEST — TPU silently runs f32
dots at bf16 input precision otherwise).

A dense-einsum fallback provides the same math on CPU (tests run on the
virtual mesh with JAX_PLATFORMS=cpu where Mosaic kernels can't compile).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


# sample-block width: the Pallas grid's lane-major tile. 16384 measured
# ~13% faster than 8192 at the Higgs shape (fewer grid steps amortize the
# per-step P/PV build and DMA; scripts/tune_hist_kernel.py)
BM_DEFAULT = 16384


def _pad_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@partial(jax.jit, static_argnames=("B", "bm", "fg", "use_bf16"))
def _hist_pallas(
    bins4, pos, g, h, node_ids, B: int, bm: int, fg: int, use_bf16: bool
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, nblk = bins4.shape[0], bins4.shape[1]
    n = nblk * bm
    N = node_ids.shape[0]
    assert F % fg == 0, (F, fg)
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32
    prec = None if use_bf16 else jax.lax.Precision.HIGHEST
    nt = (((1,), (1,)), ((), ()))  # A @ B.T

    pos3 = pos.reshape(nblk, 1, bm)
    g3 = g.reshape(nblk, 1, bm)
    h3 = h.reshape(nblk, 1, bm)
    ids2 = node_ids.reshape(N, 1)

    def kernel(bins_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref):
        blk = pl.program_id(1)
        p = pos_ref[0, 0, :][None, :]  # (1, bm) lanes
        P = (ids_ref[:, 0:1] == p).astype(cdt)  # (N, bm)
        gv = g_ref[0, 0, :][None, :].astype(cdt)
        hv = h_ref[0, 0, :][None, :].astype(cdt)
        PV = jnp.concatenate([P * gv, P * hv, P], axis=0)  # (3N, bm)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
        for fi in range(fg):
            b = bins_ref[fi, 0, 0, :][None, :].astype(jnp.int32)  # (1, bm)
            OH = (iota_b == b).astype(cdt)  # (B, bm)
            acc = jax.lax.dot_general(
                PV, OH, nt, precision=prec, preferred_element_type=jnp.float32
            )  # (3N, B)

            @pl.when(blk == 0)
            def _():
                out_ref[fi, :, :] = acc

            @pl.when(blk > 0)
            def _():
                out_ref[fi, :, :] = out_ref[fi, :, :] + acc

    out = pl.pallas_call(
        kernel,
        grid=(F // fg, nblk),
        in_specs=[
            pl.BlockSpec((fg, 1, 1, bm), lambda fo, k: (fo, k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((N, 1), lambda fo, k: (0, 0)),
        ],
        out_specs=pl.BlockSpec((fg, 3 * N, B), lambda fo, k: (fo, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * N, B), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(bins4, pos3, g3, h3, ids2)
    return out  # (F, 3N, B), rows [g*N | h*N | c*N]


@partial(jax.jit, static_argnames=("B", "bm", "fg"))
def _hist_pallas_q(bins4, pos, gq, hq, node_ids, B: int, bm: int, fg: int):
    """int8 variant: gq/hq are pre-quantized grads as f32 integers in
    [-127, 127] (caller owns the scales); one-hots are exact, dots run at
    2x MXU rate with i32 accumulation (|sum| <= bm*127 per tile, far from
    overflow). Counts stay exact. Output (F, 3N, B) int32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, nblk = bins4.shape[0], bins4.shape[1]
    N = node_ids.shape[0]
    assert F % fg == 0, (F, fg)
    nt = (((1,), (1,)), ((), ()))  # A @ B.T

    pos3 = pos.reshape(nblk, 1, bm)
    g3 = gq.reshape(nblk, 1, bm)
    h3 = hq.reshape(nblk, 1, bm)
    ids2 = node_ids.reshape(N, 1)

    def kernel(bins_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref):
        blk = pl.program_id(1)
        p = pos_ref[0, 0, :][None, :]
        Pb = ids_ref[:, 0:1] == p  # (N, bm) bool
        # Mosaic legalizes neither int8 multiplies nor int8/i1 selects, so
        # the masking runs in f32 (inputs are pre-rounded to [-127, 127])
        # and the assembled block casts to int8 for the 2x-rate dot
        P = Pb.astype(jnp.float32)
        gv = P * g_ref[0, 0, :][None, :]
        hv = P * h_ref[0, 0, :][None, :]
        PV = jnp.concatenate([gv, hv, P], axis=0).astype(jnp.int8)  # (3N, bm)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
        for fi in range(fg):
            b = bins_ref[fi, 0, 0, :][None, :].astype(jnp.int32)
            OH = (iota_b == b).astype(jnp.int8)  # (B, bm)
            acc = jax.lax.dot_general(
                PV, OH, nt, preferred_element_type=jnp.int32
            )  # (3N, B) i32

            @pl.when(blk == 0)
            def _():
                out_ref[fi, :, :] = acc

            @pl.when(blk > 0)
            def _():
                out_ref[fi, :, :] = out_ref[fi, :, :] + acc

    return pl.pallas_call(
        kernel,
        grid=(F // fg, nblk),
        in_specs=[
            pl.BlockSpec((fg, 1, 1, bm), lambda fo, k: (fo, k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((N, 1), lambda fo, k: (0, 0)),
        ],
        out_specs=pl.BlockSpec((fg, 3 * N, B), lambda fo, k: (fo, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * N, B), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(bins4, pos3, g3, h3, ids2)


@partial(jax.jit, static_argnames=("B",))
def _hist_dense_q(bins_t, pos, gq, hq, node_ids, B: int):
    """int8 math via int32 einsum (CPU / fallback path for the q kernel);
    gq/hq are f32 integers in [-127, 127]."""
    P = (node_ids[:, None] == pos[None, :]).astype(jnp.int32)
    OH = (
        bins_t.astype(jnp.int32)[:, None, :] == jnp.arange(B)[None, :, None]
    ).astype(jnp.int32)
    gi = gq.astype(jnp.int32)
    hi = hq.astype(jnp.int32)
    hg = jnp.einsum("xn,fbn->fxb", P * gi[None, :], OH)
    hh = jnp.einsum("xn,fbn->fxb", P * hi[None, :], OH)
    hc = jnp.einsum("xn,fbn->fxb", P, OH)
    return jnp.concatenate([hg, hh, hc], axis=1)  # (F, 3N, B) i32


def hist_wave_q(
    bins_t, pos, gq, hq, node_ids, B: int, bm: int = BM_DEFAULT,
    force_dense: bool = False,
):
    """(N, F, B, 3) int32 histograms from int8-quantized grads."""
    F = bins_t.shape[0]
    N = node_ids.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not force_dense:
        bins4 = (
            bins_t
            if bins_t.ndim == 4
            else bins_t.reshape(F, bins_t.shape[1] // bm, 1, bm)
        )
        out = _hist_pallas_q(bins4, pos, gq, hq, node_ids, B, bm, _pick_fg(F))
    else:
        bins2 = bins_t if bins_t.ndim == 2 else bins_t.reshape(F, -1)
        out = _hist_dense_q(bins2, pos, gq, hq, node_ids, B)
    out = out.reshape(F, 3, N, B)
    return jnp.transpose(out, (2, 0, 3, 1))


@partial(jax.jit, static_argnames=("B", "use_bf16"))
def _hist_dense(bins_t, pos, g, h, node_ids, B: int, use_bf16: bool):
    """Same math as the Pallas kernel via einsum (CPU / fallback path)."""
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32
    P = (node_ids[:, None] == pos[None, :]).astype(cdt)  # (N, n)
    OH = (
        bins_t.astype(jnp.int32)[:, None, :] == jnp.arange(B)[None, :, None]
    ).astype(cdt)  # (F, B, n)
    gv = g.astype(cdt)
    hv = h.astype(cdt)
    hg = jnp.einsum("xn,fbn->fxb", P * gv[None, :], OH, preferred_element_type=jnp.float32)
    hh = jnp.einsum("xn,fbn->fxb", P * hv[None, :], OH, preferred_element_type=jnp.float32)
    hc = jnp.einsum("xn,fbn->fxb", P, OH, preferred_element_type=jnp.float32)
    return jnp.concatenate([hg, hh, hc], axis=1)  # (F, 3N, B)


def _pick_fg(F: int) -> int:
    # wider groups amortize the per-step P/PV build further: fg=14 measured
    # ~12% faster than fg=7 at the Higgs shape (r5, device-loop timing)
    for fg in (14, 7, 8, 4, 5, 6, 3, 2):
        if F % fg == 0:
            return fg
    return 1


def hist_wave(
    bins_t,
    pos,
    g,
    h,
    node_ids,
    B: int,
    bm: int = BM_DEFAULT,
    use_bf16: bool = True,
    force_dense: bool = False,
):
    """(N, F, B, 3) histograms for the nodes listed in `node_ids`.

    bins_t   (F, n) int32 — transposed bin matrix (n padded to bm)
    pos      (n,) int32   — tree-node id per sample (-1 or absent = skip)
    g, h     (n,) f32     — weighted grad / hess per sample
    node_ids (N,) int32   — node ids to histogram (-2 pads: match nothing)
    """
    F = bins_t.shape[0]
    N = node_ids.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not force_dense:
        bins4 = (
            bins_t
            if bins_t.ndim == 4
            else bins_t.reshape(F, bins_t.shape[1] // bm, 1, bm)
        )
        out = _hist_pallas(
            bins4, pos, g, h, node_ids, B, bm, _pick_fg(F), use_bf16
        )
    else:
        bins2 = bins_t if bins_t.ndim == 2 else bins_t.reshape(F, -1)
        out = _hist_dense(bins2, pos, g, h, node_ids, B, use_bf16)
    # (F, 3N, B) -> (N, F, B, 3)
    out = out.reshape(F, 3, N, B)
    return jnp.transpose(out, (2, 0, 3, 1))


# ---------------------------------------------------------------------------
# Fused compact+gather+histogram kernel (leaf-partitioned waves)
# ---------------------------------------------------------------------------
#
# Late-tree waves touch a few thousand rows out of millions. The XLA
# formulation (gather (R, F) rows + transpose + full kernel) loses on TPU
# because real-index gathers run far off the strided path. This kernel
# fuses the row gather INTO the histogram pass: the wave's compacted
# row-index list arrives in SMEM tiles, each grid step issues one small
# DMA per selected row (HBM row-major bins -> VMEM scratch, all in
# flight before the first wait), and the gathered tile feeds the same
# one-hot MXU accumulation as the dense kernels — no (R, F) gather, no
# transpose, no extra HBM round trip. Wave cost becomes O(R) DMA issues
# + O(R*N*B) MACs instead of O(n*N*B).
#
# Layout: the gathered tile is ROW-major (rows on sublanes), so the bin
# one-hot is built per feature from a lane-column slice and the MXU pass
# is a plain NN dot PV (3N, bm_g) @ OH (bm_g, bins B) — pos/g/h tiles stay
# lane-major exactly like the full-scan kernels.

BMG_DEFAULT = 1024  # gathered-tile rows (sublane dim of the NN dot)


def _tpu_compiler_params(**kw):
    """jax renamed TPUCompilerParams -> CompilerParams; the fused kernel
    traces on CPU too (interpret-mode tests), so resolve at call time."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _gather_grid_call(
    rows, idx, pos_g, g_t, h_t, ids2, out_dtype, kernel, B, bm_g, interpret
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R = idx.shape[0]
    F = rows.shape[1]
    N = ids2.shape[0]
    assert R % bm_g == 0, (R, bm_g)
    return pl.pallas_call(
        kernel,
        grid=(R // bm_g,),
        in_specs=[
            pl.BlockSpec((bm_g,), lambda t: (t,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),  # rows stay in HBM
            pl.BlockSpec((1, 1, bm_g), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, 1, bm_g), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, 1, bm_g), lambda t: (t, 0, 0)),
            pl.BlockSpec((N, 1), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((F, 3 * N, B), lambda t: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * N, B), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((bm_g, F), rows.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(idx, rows, pos_g, g_t, h_t, ids2)


def _gather_rows_dma(idx_ref, rows_ref, scratch, sem, bm_g: int):
    """Issue one DMA per selected row (all in flight), then drain. The
    issue loop is the kernel's dominant cost at large R — which is why
    the budget ladder only routes small waves here."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def issue(i, c):
        iv = idx_ref[i]
        pltpu.make_async_copy(
            rows_ref.at[pl.ds(iv, 1), :], scratch.at[pl.ds(i, 1), :], sem
        ).start()
        return c

    jax.lax.fori_loop(0, bm_g, issue, 0)

    def drain(i, c):
        pltpu.make_async_copy(
            rows_ref.at[pl.ds(0, 1), :], scratch.at[pl.ds(0, 1), :], sem
        ).wait()
        return c

    jax.lax.fori_loop(0, bm_g, drain, 0)


@partial(
    jax.jit, static_argnames=("B", "bm_g", "use_bf16", "interpret")
)
def _hist_gather_pallas(
    rows, idx, pos_g, g, h, node_ids, B: int, bm_g: int, use_bf16: bool,
    interpret: bool,
):
    """Fused gather+histogram, f32/bf16 MXU variant.

    rows     (n, F) u8|i32 — ROW-major bin matrix (HBM resident)
    idx      (R,) i32      — compacted row indices (R % bm_g == 0; slots
                             past the wave's row count point at row 0 and
                             are masked by pos_g = -1)
    pos_g    (R,) i32      — node id per gathered row (-1 = dead slot)
    g, h     (R,) f32      — gathered weighted grad / hess
    node_ids (N,) i32      — wave node ids (-2 pads match nothing)
    Returns (F, 3N, B) f32 partial histograms, rows [g*N | h*N | c*N].
    """
    from jax import lax

    R = idx.shape[0]
    F = rows.shape[1]
    N = node_ids.shape[0]
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32
    prec = None if use_bf16 else jax.lax.Precision.HIGHEST
    nn = (((1,), (0,)), ((), ()))  # A @ B

    pos3 = pos_g.reshape(R // bm_g, 1, bm_g)
    g3 = g.reshape(R // bm_g, 1, bm_g)
    h3 = h.reshape(R // bm_g, 1, bm_g)
    ids2 = node_ids.reshape(N, 1)

    def kernel(idx_ref, rows_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref,
               scratch, sem):
        from jax.experimental import pallas as pl

        t = pl.program_id(0)
        _gather_rows_dma(idx_ref, rows_ref, scratch, sem, bm_g)
        p = pos_ref[0, 0, :][None, :]  # (1, bm_g) lanes
        P = (ids_ref[:, 0:1] == p).astype(cdt)  # (N, bm_g)
        gv = g_ref[0, 0, :][None, :].astype(cdt)
        hv = h_ref[0, 0, :][None, :].astype(cdt)
        PV = jnp.concatenate([P * gv, P * hv, P], axis=0)  # (3N, bm_g)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
        for f in range(F):
            col = scratch[:, f : f + 1].astype(jnp.int32)  # (bm_g, 1)
            OH = (col == iota_b).astype(cdt)  # (bm_g, B) row-major
            acc = lax.dot_general(
                PV, OH, nn, precision=prec,
                preferred_element_type=jnp.float32,
            )  # (3N, B)

            @pl.when(t == 0)
            def _():
                out_ref[f, :, :] = acc

            @pl.when(t > 0)
            def _():
                out_ref[f, :, :] = out_ref[f, :, :] + acc

    return _gather_grid_call(
        rows, idx, pos3, g3, h3, ids2, jnp.float32, kernel, B, bm_g, interpret
    )


@partial(jax.jit, static_argnames=("B", "bm_g", "interpret"))
def _hist_gather_pallas_q(
    rows, idx, pos_g, gq, hq, node_ids, B: int, bm_g: int, interpret: bool
):
    """Fused gather+histogram, int8 variant (gq/hq are f32 integers in
    [-127, 127], caller owns the scales; i32 accumulation is exact and
    order-independent, so fused-budget trees equal full-scan trees
    bit-for-bit). Returns (F, 3N, B) int32."""
    from jax import lax

    R = idx.shape[0]
    F = rows.shape[1]
    N = node_ids.shape[0]
    nn = (((1,), (0,)), ((), ()))

    pos3 = pos_g.reshape(R // bm_g, 1, bm_g)
    g3 = gq.reshape(R // bm_g, 1, bm_g)
    h3 = hq.reshape(R // bm_g, 1, bm_g)
    ids2 = node_ids.reshape(N, 1)

    def kernel(idx_ref, rows_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref,
               scratch, sem):
        from jax.experimental import pallas as pl

        t = pl.program_id(0)
        _gather_rows_dma(idx_ref, rows_ref, scratch, sem, bm_g)
        p = pos_ref[0, 0, :][None, :]
        Pb = ids_ref[:, 0:1] == p  # (N, bm_g) bool
        # int8 multiplies / selects don't legalize in Mosaic — mask in f32
        # and cast the assembled block (same trick as _hist_pallas_q)
        P = Pb.astype(jnp.float32)
        gv = P * g_ref[0, 0, :][None, :]
        hv = P * h_ref[0, 0, :][None, :]
        PV = jnp.concatenate([gv, hv, P], axis=0).astype(jnp.int8)  # (3N, bm_g)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
        for f in range(F):
            col = scratch[:, f : f + 1].astype(jnp.int32)
            OH = (col == iota_b).astype(jnp.int8)  # (bm_g, B)
            acc = lax.dot_general(
                PV, OH, nn, preferred_element_type=jnp.int32
            )  # (3N, B) i32

            @pl.when(t == 0)
            def _():
                out_ref[f, :, :] = acc

            @pl.when(t > 0)
            def _():
                out_ref[f, :, :] = out_ref[f, :, :] + acc

    return _gather_grid_call(
        rows, idx, pos3, g3, h3, ids2, jnp.int32, kernel, B, bm_g, interpret
    )


def hist_wave_gather(
    rows,
    idx,
    pos_g,
    g,
    h,
    node_ids,
    B: int,
    mode: str = "mxu",
    use_bf16: bool = True,
    bm_g: int = BMG_DEFAULT,
    force_dense: bool = False,
    interpret: bool = False,
):
    """(N, F, B, 3) partial histograms over a compacted row subset.

    The TPU path runs the fused gather+hist kernel; off-TPU (unless
    `interpret` forces the Pallas interpreter, for tests) the same math
    runs as an explicit (R, F) gather + dense einsum — bit-identical in
    int8 mode. Output dtype matches hist_wave (f32) / hist_wave_q (i32).
    """
    F = rows.shape[1]
    N = node_ids.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    if (on_tpu and not force_dense) or interpret:
        if mode == "int8":
            out = _hist_gather_pallas_q(
                rows, idx, pos_g, g, h, node_ids, B, bm_g, interpret
            )
        else:
            out = _hist_gather_pallas(
                rows, idx, pos_g, g, h, node_ids, B, bm_g, use_bf16, interpret
            )
    else:
        bt = jnp.transpose(jnp.take(rows, idx, axis=0)).astype(jnp.int32)
        if mode == "int8":
            out = _hist_dense_q(bt, pos_g, g, h, node_ids, B)
        else:
            out = _hist_dense(bt, pos_g, g, h, node_ids, B, use_bf16)
    out = out.reshape(F, 3, N, B)
    return jnp.transpose(out, (2, 0, 3, 1))


def compact_indices(mask, R: int):
    """Order-preserving compaction of a boolean row mask into a static
    (R,) index buffer: `idx[:cnt]` are the positions of the True entries
    in ascending order, slots at/past `cnt` point at row 0 (callers mask
    them out — the fused gather kernel via pos_g = -1, the GOSS fit set
    via an `arange(R) < cnt` validity mask). Shared by the engine's
    leaf-partitioned budget gathers and the per-tree GOSS row selection,
    so both hot paths compact rows with the same scatter idiom.

    Returns (idx (R,) int32, cnt () int32). Requires R >= true-count
    (overflow entries are dropped by the scatter's drop mode — callers
    size R from static knowledge)."""
    n = mask.shape[0]
    csum = jnp.cumsum(mask.astype(jnp.int32))
    cnt = csum[-1]
    dest = jnp.where(mask, csum - 1, R)
    idx = jnp.zeros((R,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop"
    )
    return idx, cnt


def pad_inputs(
    bins: np.ndarray, bm: int = BM_DEFAULT, n_pad: int = None, F_pad: int = None
):
    """Host-side one-time prep: transpose + pad the bin matrix for hist_wave.

    Returns (bins_t (F_pad, n_pad) int32, n_pad). Padding rows get bin 0
    but are excluded by pos = -1; padded FEATURES (mesh feature-slice
    alignment) are all-bin-0 and masked by the caller. Pass `n_pad` to pad
    to an explicit target (multi-process shard equalization) instead of
    the next bm multiple."""
    n, F = bins.shape
    if n_pad is None:
        n_pad = _pad_to(n, bm)
    if F_pad is None:
        F_pad = F
    bins_t = np.zeros((F_pad, n_pad), np.int32)
    bins_t[:F, :n] = bins.T
    return bins_t, n_pad
