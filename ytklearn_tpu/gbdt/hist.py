"""Histogram accumulation kernels — the GBDT hot op, TPU-first.

The reference's hottest loop (HistogramBuilder.java:72-90) scatter-adds
(g, h, 1) into per-(node, feature, bin) slots. XLA scatter serializes on
TPU (measured ~1.7 s per 1M-row pass), so the TPU path instead computes
the histogram as a blocked one-hot matmul on the MXU:

    for each (feature, sample-block) grid step:
        P  (N, bm) = node one-hot       # VPU compare: ids col vs pos row
        OH (B, bm) = bin one-hot        # VPU compare: bin iota vs bins row
        hist_g (N, B) += (P * g) @ OH.T # MXU NT-dot, f32 accumulation
        hist_h (N, B) += (P * h) @ OH.T
        hist_c (N, B) += P @ OH.T

All per-sample arrays ride as (nblk, bm) row-major chunks so every VMEM
block is a full-lane (1, bm) vector — no (x, 1) lane-padding blowups, no
in-kernel transposes. Samples whose pos is not in `node_ids` (including
pos = -1 dead rows) match no one-hot row and vanish.

A dense-einsum fallback provides the same math on CPU (tests run on the
virtual mesh with JAX_PLATFORMS=cpu where Mosaic kernels can't compile).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _pad_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@partial(jax.jit, static_argnames=("B", "bm", "use_bf16"))
def _hist_pallas(bins_t, pos, g, h, node_ids, B: int, bm: int, use_bf16: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, n = bins_t.shape
    N = node_ids.shape[0]
    nblk = n // bm
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32

    bins3 = bins_t.reshape(F, nblk, 1, bm)
    pos2 = pos.reshape(nblk, 1, bm)
    g2 = g.reshape(nblk, 1, bm)
    h2 = h.reshape(nblk, 1, bm)
    ids2 = node_ids.reshape(N, 1)

    def kernel(bins_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref):
        blk = pl.program_id(1)
        b = bins_ref[0, 0, 0, :][None, :]  # (1, bm) lanes
        p = pos_ref[0, 0, :][None, :]  # (1, bm)
        P = (ids_ref[:, 0:1] == p).astype(cdt)  # (N, bm)
        OH = (
            jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0) == b
        ).astype(cdt)  # (B, bm)
        gv = g_ref[0, 0, :][None, :].astype(cdt)  # (1, bm)
        hv = h_ref[0, 0, :][None, :].astype(cdt)

        nt = (((1,), (1,)), ((), ()))  # A @ B.T
        hg = jax.lax.dot_general(P * gv, OH, nt, preferred_element_type=jnp.float32)
        hh = jax.lax.dot_general(P * hv, OH, nt, preferred_element_type=jnp.float32)
        hc = jax.lax.dot_general(P, OH, nt, preferred_element_type=jnp.float32)
        acc = jnp.concatenate([hg, hh, hc], axis=0)  # (3N, B)

        @pl.when(blk == 0)
        def _():
            out_ref[0, :, :] = acc

        @pl.when(blk > 0)
        def _():
            out_ref[0, :, :] = out_ref[0, :, :] + acc

    out = pl.pallas_call(
        kernel,
        grid=(F, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, 1, bm), lambda f, k: (f, k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda f, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda f, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda f, k: (k, 0, 0)),
            pl.BlockSpec((N, 1), lambda f, k: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 3 * N, B), lambda f, k: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * N, B), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(bins3, pos2, g2, h2, ids2)
    return out  # (F, 3N, B), rows [g*N | h*N | c*N]


@partial(jax.jit, static_argnames=("B", "use_bf16"))
def _hist_dense(bins_t, pos, g, h, node_ids, B: int, use_bf16: bool):
    """Same math as the Pallas kernel via einsum (CPU / fallback path)."""
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32
    P = (node_ids[:, None] == pos[None, :]).astype(cdt)  # (N, n)
    OH = (
        bins_t[:, None, :] == jnp.arange(B)[None, :, None]
    ).astype(cdt)  # (F, B, n)
    gv = g.astype(cdt)
    hv = h.astype(cdt)
    hg = jnp.einsum("xn,fbn->fxb", P * gv[None, :], OH, preferred_element_type=jnp.float32)
    hh = jnp.einsum("xn,fbn->fxb", P * hv[None, :], OH, preferred_element_type=jnp.float32)
    hc = jnp.einsum("xn,fbn->fxb", P, OH, preferred_element_type=jnp.float32)
    return jnp.concatenate([hg, hh, hc], axis=1)  # (F, 3N, B)


def hist_wave(
    bins_t,
    pos,
    g,
    h,
    node_ids,
    B: int,
    bm: int = 8192,
    use_bf16: bool = True,
    force_dense: bool = False,
):
    """(N, F, B, 3) histograms for the nodes listed in `node_ids`.

    bins_t   (F, n) int32 — transposed bin matrix (n padded to bm)
    pos      (n,) int32   — tree-node id per sample (-1 or absent = skip)
    g, h     (n,) f32     — weighted grad / hess per sample
    node_ids (N,) int32   — node ids to histogram (-2 pads: match nothing)
    """
    F, n = bins_t.shape
    N = node_ids.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not force_dense:
        out = _hist_pallas(bins_t, pos, g, h, node_ids, B, bm, use_bf16)
    else:
        out = _hist_dense(bins_t, pos, g, h, node_ids, B, use_bf16)
    # (F, 3N, B) -> (N, F, B, 3)
    out = out.reshape(F, 3, N, B)
    return jnp.transpose(out, (2, 0, 3, 1))


def pad_inputs(bins: np.ndarray, bm: int = 8192):
    """Host-side one-time prep: transpose + pad the bin matrix for hist_wave.

    Returns (bins_t (F, n_pad) int32, n_pad). Padding rows get bin 0 but
    are excluded by pos = -1."""
    n, F = bins.shape
    n_pad = _pad_to(n, bm)
    bins_t = np.zeros((F, n_pad), np.int32)
    bins_t[:, :n] = bins.T
    return bins_t, n_pad
