"""Histogram accumulation kernels — the GBDT hot op, TPU-first.

The reference's hottest loop (HistogramBuilder.java:72-90) scatter-adds
(g, h, 1) into per-(node, feature, bin) slots. XLA scatter serializes on
TPU (measured ~1.7 s per 1M-row pass), so the TPU path instead computes
the histogram as a blocked one-hot matmul on the MXU:

    for each (feature-group, sample-block) grid step:
        P  (N, bm)  = node one-hot                  # VPU, once per block
        PV (3N, bm) = [P*g | P*h | P]               # VPU, once per block
        for f in group:                             # unrolled F_g times
            OH (B, bm)   = bin one-hot              # VPU compare vs iota
            out[f] (3N,B) += PV @ OH.T              # MXU NT-dot, f32 accum

Layouts are lane-major throughout (P (N, bm), OH (B, bm), samples always
on lanes) so no in-kernel transposes occur and no (x, 1) blocks blow up
VMEM with lane padding. Grouping features inside one grid step amortizes
the node one-hot (a 28x saving at wide waves) and the pos/g/h DMAs.
Samples whose pos is not in `node_ids` (including pos = -1 dead rows)
match no one-hot row and vanish.

bf16 operands halve MXU time; histogram sums accumulate in f32 either
way (counts stay exact — 0/1 one-hots are exact in bf16). use_bf16=False
forces true-f32 MXU passes (Precision.HIGHEST — TPU silently runs f32
dots at bf16 input precision otherwise).

A dense-einsum fallback provides the same math on CPU (tests run on the
virtual mesh with JAX_PLATFORMS=cpu where Mosaic kernels can't compile).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


# sample-block width: the Pallas grid's lane-major tile. 16384 measured
# ~13% faster than 8192 at the Higgs shape (fewer grid steps amortize the
# per-step P/PV build and DMA; scripts/tune_hist_kernel.py)
BM_DEFAULT = 16384


def _pad_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@partial(jax.jit, static_argnames=("B", "bm", "fg", "use_bf16"))
def _hist_pallas(
    bins4, pos, g, h, node_ids, B: int, bm: int, fg: int, use_bf16: bool
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, nblk = bins4.shape[0], bins4.shape[1]
    n = nblk * bm
    N = node_ids.shape[0]
    assert F % fg == 0, (F, fg)
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32
    prec = None if use_bf16 else jax.lax.Precision.HIGHEST
    nt = (((1,), (1,)), ((), ()))  # A @ B.T

    pos3 = pos.reshape(nblk, 1, bm)
    g3 = g.reshape(nblk, 1, bm)
    h3 = h.reshape(nblk, 1, bm)
    ids2 = node_ids.reshape(N, 1)

    def kernel(bins_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref):
        blk = pl.program_id(1)
        p = pos_ref[0, 0, :][None, :]  # (1, bm) lanes
        P = (ids_ref[:, 0:1] == p).astype(cdt)  # (N, bm)
        gv = g_ref[0, 0, :][None, :].astype(cdt)
        hv = h_ref[0, 0, :][None, :].astype(cdt)
        PV = jnp.concatenate([P * gv, P * hv, P], axis=0)  # (3N, bm)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
        for fi in range(fg):
            b = bins_ref[fi, 0, 0, :][None, :].astype(jnp.int32)  # (1, bm)
            OH = (iota_b == b).astype(cdt)  # (B, bm)
            acc = jax.lax.dot_general(
                PV, OH, nt, precision=prec, preferred_element_type=jnp.float32
            )  # (3N, B)

            @pl.when(blk == 0)
            def _():
                out_ref[fi, :, :] = acc

            @pl.when(blk > 0)
            def _():
                out_ref[fi, :, :] = out_ref[fi, :, :] + acc

    out = pl.pallas_call(
        kernel,
        grid=(F // fg, nblk),
        in_specs=[
            pl.BlockSpec((fg, 1, 1, bm), lambda fo, k: (fo, k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((N, 1), lambda fo, k: (0, 0)),
        ],
        out_specs=pl.BlockSpec((fg, 3 * N, B), lambda fo, k: (fo, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * N, B), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(bins4, pos3, g3, h3, ids2)
    return out  # (F, 3N, B), rows [g*N | h*N | c*N]


@partial(jax.jit, static_argnames=("B", "bm", "fg"))
def _hist_pallas_q(bins4, pos, gq, hq, node_ids, B: int, bm: int, fg: int):
    """int8 variant: gq/hq are pre-quantized grads as f32 integers in
    [-127, 127] (caller owns the scales); one-hots are exact, dots run at
    2x MXU rate with i32 accumulation (|sum| <= bm*127 per tile, far from
    overflow). Counts stay exact. Output (F, 3N, B) int32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, nblk = bins4.shape[0], bins4.shape[1]
    N = node_ids.shape[0]
    assert F % fg == 0, (F, fg)
    nt = (((1,), (1,)), ((), ()))  # A @ B.T

    pos3 = pos.reshape(nblk, 1, bm)
    g3 = gq.reshape(nblk, 1, bm)
    h3 = hq.reshape(nblk, 1, bm)
    ids2 = node_ids.reshape(N, 1)

    def kernel(bins_ref, pos_ref, g_ref, h_ref, ids_ref, out_ref):
        blk = pl.program_id(1)
        p = pos_ref[0, 0, :][None, :]
        Pb = ids_ref[:, 0:1] == p  # (N, bm) bool
        # Mosaic legalizes neither int8 multiplies nor int8/i1 selects, so
        # the masking runs in f32 (inputs are pre-rounded to [-127, 127])
        # and the assembled block casts to int8 for the 2x-rate dot
        P = Pb.astype(jnp.float32)
        gv = P * g_ref[0, 0, :][None, :]
        hv = P * h_ref[0, 0, :][None, :]
        PV = jnp.concatenate([gv, hv, P], axis=0).astype(jnp.int8)  # (3N, bm)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
        for fi in range(fg):
            b = bins_ref[fi, 0, 0, :][None, :].astype(jnp.int32)
            OH = (iota_b == b).astype(jnp.int8)  # (B, bm)
            acc = jax.lax.dot_general(
                PV, OH, nt, preferred_element_type=jnp.int32
            )  # (3N, B) i32

            @pl.when(blk == 0)
            def _():
                out_ref[fi, :, :] = acc

            @pl.when(blk > 0)
            def _():
                out_ref[fi, :, :] = out_ref[fi, :, :] + acc

    return pl.pallas_call(
        kernel,
        grid=(F // fg, nblk),
        in_specs=[
            pl.BlockSpec((fg, 1, 1, bm), lambda fo, k: (fo, k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((1, 1, bm), lambda fo, k: (k, 0, 0)),
            pl.BlockSpec((N, 1), lambda fo, k: (0, 0)),
        ],
        out_specs=pl.BlockSpec((fg, 3 * N, B), lambda fo, k: (fo, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * N, B), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(bins4, pos3, g3, h3, ids2)


@partial(jax.jit, static_argnames=("B",))
def _hist_dense_q(bins_t, pos, gq, hq, node_ids, B: int):
    """int8 math via int32 einsum (CPU / fallback path for the q kernel);
    gq/hq are f32 integers in [-127, 127]."""
    P = (node_ids[:, None] == pos[None, :]).astype(jnp.int32)
    OH = (
        bins_t.astype(jnp.int32)[:, None, :] == jnp.arange(B)[None, :, None]
    ).astype(jnp.int32)
    gi = gq.astype(jnp.int32)
    hi = hq.astype(jnp.int32)
    hg = jnp.einsum("xn,fbn->fxb", P * gi[None, :], OH)
    hh = jnp.einsum("xn,fbn->fxb", P * hi[None, :], OH)
    hc = jnp.einsum("xn,fbn->fxb", P, OH)
    return jnp.concatenate([hg, hh, hc], axis=1)  # (F, 3N, B) i32


def hist_wave_q(
    bins_t, pos, gq, hq, node_ids, B: int, bm: int = BM_DEFAULT,
    force_dense: bool = False,
):
    """(N, F, B, 3) int32 histograms from int8-quantized grads."""
    F = bins_t.shape[0]
    N = node_ids.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not force_dense:
        bins4 = (
            bins_t
            if bins_t.ndim == 4
            else bins_t.reshape(F, bins_t.shape[1] // bm, 1, bm)
        )
        out = _hist_pallas_q(bins4, pos, gq, hq, node_ids, B, bm, _pick_fg(F))
    else:
        bins2 = bins_t if bins_t.ndim == 2 else bins_t.reshape(F, -1)
        out = _hist_dense_q(bins2, pos, gq, hq, node_ids, B)
    out = out.reshape(F, 3, N, B)
    return jnp.transpose(out, (2, 0, 3, 1))


@partial(jax.jit, static_argnames=("B", "use_bf16"))
def _hist_dense(bins_t, pos, g, h, node_ids, B: int, use_bf16: bool):
    """Same math as the Pallas kernel via einsum (CPU / fallback path)."""
    cdt = jnp.bfloat16 if use_bf16 else jnp.float32
    P = (node_ids[:, None] == pos[None, :]).astype(cdt)  # (N, n)
    OH = (
        bins_t.astype(jnp.int32)[:, None, :] == jnp.arange(B)[None, :, None]
    ).astype(cdt)  # (F, B, n)
    gv = g.astype(cdt)
    hv = h.astype(cdt)
    hg = jnp.einsum("xn,fbn->fxb", P * gv[None, :], OH, preferred_element_type=jnp.float32)
    hh = jnp.einsum("xn,fbn->fxb", P * hv[None, :], OH, preferred_element_type=jnp.float32)
    hc = jnp.einsum("xn,fbn->fxb", P, OH, preferred_element_type=jnp.float32)
    return jnp.concatenate([hg, hh, hc], axis=1)  # (F, 3N, B)


def _pick_fg(F: int) -> int:
    # wider groups amortize the per-step P/PV build further: fg=14 measured
    # ~12% faster than fg=7 at the Higgs shape (r5, device-loop timing)
    for fg in (14, 7, 8, 4, 5, 6, 3, 2):
        if F % fg == 0:
            return fg
    return 1


def hist_wave(
    bins_t,
    pos,
    g,
    h,
    node_ids,
    B: int,
    bm: int = BM_DEFAULT,
    use_bf16: bool = True,
    force_dense: bool = False,
):
    """(N, F, B, 3) histograms for the nodes listed in `node_ids`.

    bins_t   (F, n) int32 — transposed bin matrix (n padded to bm)
    pos      (n,) int32   — tree-node id per sample (-1 or absent = skip)
    g, h     (n,) f32     — weighted grad / hess per sample
    node_ids (N,) int32   — node ids to histogram (-2 pads: match nothing)
    """
    F = bins_t.shape[0]
    N = node_ids.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not force_dense:
        bins4 = (
            bins_t
            if bins_t.ndim == 4
            else bins_t.reshape(F, bins_t.shape[1] // bm, 1, bm)
        )
        out = _hist_pallas(
            bins4, pos, g, h, node_ids, B, bm, _pick_fg(F), use_bf16
        )
    else:
        bins2 = bins_t if bins_t.ndim == 2 else bins_t.reshape(F, -1)
        out = _hist_dense(bins2, pos, g, h, node_ids, B, use_bf16)
    # (F, 3N, B) -> (N, F, B, 3)
    out = out.reshape(F, 3, N, B)
    return jnp.transpose(out, (2, 0, 3, 1))


def pad_inputs(
    bins: np.ndarray, bm: int = BM_DEFAULT, n_pad: int = None, F_pad: int = None
):
    """Host-side one-time prep: transpose + pad the bin matrix for hist_wave.

    Returns (bins_t (F_pad, n_pad) int32, n_pad). Padding rows get bin 0
    but are excluded by pos = -1; padded FEATURES (mesh feature-slice
    alignment) are all-bin-0 and masked by the caller. Pass `n_pad` to pad
    to an explicit target (multi-process shard equalization) instead of
    the next bm multiple."""
    n, F = bins.shape
    if n_pad is None:
        n_pad = _pad_to(n, bm)
    if F_pad is None:
        F_pad = F
    bins_t = np.zeros((F_pad, n_pad), np.int32)
    bins_t[:F, :n] = bins.T
    return bins_t, n_pad
