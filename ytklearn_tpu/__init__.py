"""ytklearn_tpu — a TPU-native distributed classical-ML training framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of ytk-learn
(linear / multiclass linear / FM / FFM / GBDT / gradient-boosted soft trees,
distributed training, text model formats, online prediction), designed
TPU-first: SPMD over `jax.sharding.Mesh`, jit-compiled update steps, XLA
collectives over ICI instead of the reference's ytk-mp4j TCP collectives.
"""

__version__ = "0.1.0"
