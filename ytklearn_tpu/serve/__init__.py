"""ytklearn_tpu.serve — the online serving layer (docs/serving.md).

The reference ships a thread-safe `predictor/OnlinePredictor.java` API and
stops there; this layer is the rest of the serving story the ROADMAP north
star asks for ("serve heavy traffic from millions of users"):

  CompiledScorer   lowers a loaded OnlinePredictor into dense arrays and
                   jit-compiles a padded batch-shape ladder (1/8/64/512 by
                   default, knob YTK_SERVE_LADDER) with warmup-on-load, so
                   varying request sizes never retrace in steady state
  MicroBatcher     Clipper-style dynamic micro-batching queue (max batch /
                   max wait knobs) with a bounded depth that sheds load
                   when full, per-request deadlines, and graceful drain
  ModelRegistry    multi-model registry with fingerprint-watch hot reload:
                   the replacement scorer is warmed BEFORE an atomic swap
  ServeApp         stdlib ThreadingHTTPServer exposing /predict, /healthz,
                   /readyz, and /metrics (obs registry snapshot + latency
                   percentiles); SIGTERM drains in-flight work; optional
                   AIMD batch-size controller + LRU prediction cache
  fleet/           multi-process serving fleet: FleetFront spawns N
                   replica workers (one full stack each), balances on
                   least-queued-rows, heals crashes, fans out admin, and
                   aggregates fleet metrics (ring-union p99)

CLI: `python -m ytklearn_tpu.cli serve <conf> <model_name> [--replicas N]`
/ `ytklearn-tpu-serve` (cli.py).
"""

from __future__ import annotations

from .batcher import (  # noqa: F401
    BatchPolicy,
    DeadlineExceeded,
    MicroBatcher,
    OverloadError,
    ServeClosed,
)
from .registry import ModelRegistry, model_fingerprint  # noqa: F401
from .scorer import DEFAULT_LADDER, CompiledScorer, parse_ladder  # noqa: F401
from .server import ServeApp  # noqa: F401
from .fleet import (  # noqa: F401
    AIMDController,
    AutoscalePolicy,
    FleetAutoscaler,
    FleetFront,
    PredictionCache,
    default_replica_count,
    serve_worker_argv,
)

__all__ = [
    "AIMDController",
    "AutoscalePolicy",
    "BatchPolicy",
    "CompiledScorer",
    "DEFAULT_LADDER",
    "DeadlineExceeded",
    "FleetAutoscaler",
    "FleetFront",
    "MicroBatcher",
    "ModelRegistry",
    "OverloadError",
    "PredictionCache",
    "ServeApp",
    "ServeClosed",
    "default_replica_count",
    "model_fingerprint",
    "parse_ladder",
    "serve_worker_argv",
]
