"""Stdlib HTTP serving front end: /predict, /healthz, /readyz, /metrics.

A ThreadingHTTPServer (one thread per connection) in front of per-model
MicroBatchers: handler threads block on their request's pending handle
while the batcher worker coalesces rows across connections into one
compiled-scorer call. The model entry is resolved ONCE per batch, so a
hot reload lands between batches, never inside one.

Endpoints (JSON in/out):

  POST /predict    {"features": {...}} one row, or {"rows": [{...}, ...]};
                   optional "model" (default: the first loaded model) and
                   "deadline_ms". 200 -> {"scores", "predictions",
                   "model", "version"}; 429 overloaded (queue shed),
                   504 deadline expired, 503 draining, 404 unknown model
  GET /healthz     process liveness + health.* sentinel counter summary
  GET /readyz      200 only when models are loaded+warm and not draining
  GET /metrics     obs registry snapshot + request latency p50/p99/p999,
                   queue depth, per-model versions; `?raw=1` adds the
                   (ts, ms) latency-ring samples (fleet union input),
                   `?history=1` adds the per-metric time-series rings,
                   `?models=1` adds the mesh-obs per-model accounting
                   table (scoped counters, latency, burn-sentinel state,
                   cache occupancy, prof attribution)
  GET /admin/traces  the request-trace exemplar ring: head-sampled +
                   tail-retained (shed/504/SLO-violating) per-hop traces
                   (obs/trace.py, YTK_TRACE_SAMPLE)
  POST /admin/rollback {"model": name}  swap back to the previously served
                   version and pin (undo a bad continual promotion)
  POST /admin/pin  {"model": name}  freeze the served version (watcher
                   skips it); /admin/unpin re-enables hot reload

SIGTERM (install_signal_handlers) flips /readyz to 503, stops intake,
drains queued requests to completion, then stops the listener — the
load-balancer-friendly shutdown order.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..obs import enabled as obs_enabled, inc as obs_inc, snapshot as obs_snapshot, span as obs_span
from ..obs import health as obs_health
from ..obs import model_metrics as obs_models
from ..obs import quality as obs_quality
from ..obs import trace as obs_trace
from ..obs.core import REGISTRY as OBS_REGISTRY
from ..obs.heartbeat import start_history_sampler
from ..obs.recorder import thread_guard
from ..resilience import chaos_point
from .batcher import (
    BatchPolicy,
    DeadlineExceeded,
    MicroBatcher,
    OverloadError,
    ScoredRateWindow,
    ServeClosed,
    retry_after_s,
)
from .fleet.aimd import maybe_controller
from .fleet.cache import maybe_cache
from .registry import ModelRegistry, NoPreviousVersion

log = logging.getLogger("ytklearn_tpu.serve")


class _LatencyWindow:
    """Bounded ring of recent request latencies -> percentiles.

    Samples are (wall_ts, ms) PAIRS: the export (`/metrics?raw=1`) must
    carry timestamps so the fleet front can WINDOW the ring union — an
    idle replica's ring otherwise holds stale samples forever and dilutes
    the fleet p99 with minutes-old latencies (r17 satellite fix)."""

    def __init__(self, maxlen: int = 4096):
        self._ring = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        with self._lock:
            self._ring.append((time.time(), ms))

    def raw(self) -> list:
        """[(wall_ts, ms)] pairs — the fleet front unions replica rings
        (windowed on ts) so fleet p99 is computed over every replica's
        RECENT samples, not replica-0's and not stale ones."""
        with self._lock:
            return [[round(t, 3), round(v, 3)] for t, v in self._ring]

    def percentiles(self) -> Dict[str, float]:
        # one percentile implementation serves both the per-process ring
        # and the fleet ring union — the payloads must never diverge
        from .fleet.front import latency_percentiles

        with self._lock:
            vals = [v for _, v in self._ring]
        return latency_percentiles(vals)


class ServeApp:
    """Registry + batchers + HTTP listener; start()/stop() lifecycle."""

    def __init__(
        self,
        registry: ModelRegistry,
        policy: Optional[BatchPolicy] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        slo_ms: Optional[float] = None,
        cache_rows: Optional[int] = None,
        replica_id: Optional[int] = None,
    ):
        self.registry = registry
        self.policy = policy or BatchPolicy()
        self.host = host
        self.port = port
        # slo_ms > 0 arms the AIMD batch-size controller per batcher
        # (serve/fleet/aimd.py); None/0 keeps the fixed policy knobs
        self.slo_ms = slo_ms
        # cache_rows > 0 arms the LRU prediction cache (serve/fleet/cache.py)
        self.cache = maybe_cache(cache_rows if cache_rows is not None else 0)
        # fleet identity: stamped into /metrics so the front (and a
        # postmortem) can name this replica; None = solo process
        self.replica_id = replica_id
        # SLO burn-rate sentinel (health.slo_burn): every request feeds
        # it; a windowed violation rate over budget fires the alarm. The
        # same SLO arms the trace plane's tail rule (SLO-violating
        # requests are always kept as exemplars)
        self.slo_burn = (
            obs_health.SLOBurnSentinel("serve.predict", slo_ms)
            if slo_ms and slo_ms > 0 else None
        )
        if slo_ms and slo_ms > 0:
            obs_trace.configure_tracing(slo_ms=slo_ms)
        self.latency = _LatencyWindow()
        # mesh-obs per-model accounting plane (obs/model_metrics.py):
        # bounded scoped families — counters, latency rings, and burn
        # sentinels keyed by model name, fed at the SAME sites as their
        # global twins (exact conservation). Published as the process
        # default so flight dumps carry the per-model block.
        self.models = obs_models.ModelMetrics(slo_ms=slo_ms)
        for _n in registry.names():
            self.models.register(_n)
        obs_models.set_default(self.models)
        # model-quality monitor (obs/quality.py): the predict path feeds
        # sampled rows + predictions into per-model drift sketches; the
        # evaluator thread (armed in start()) judges them against each
        # model's training sidecar. YTK_QUALITY_SAMPLE=0 disables.
        self.quality = obs_quality.default_monitor()
        # recent scored-rows/s (success path) -> the 429 Retry-After
        # queue-drain estimate (same arithmetic as the fleet front);
        # per-model windows back the model-aware Retry-After hint
        self._scored = ScoredRateWindow()
        self._scored_by_model: Dict[str, ScoredRateWindow] = {}
        self.draining = False
        self._batchers: Dict[str, MicroBatcher] = {}
        self._batchers_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._started_at = time.time()

    # -- batching ---------------------------------------------------------

    def batcher_for(self, name: str) -> MicroBatcher:
        """One batcher per model name, created lazily. The score_fn
        resolves the registry entry per BATCH, so every batch is scored by
        exactly one model version (hot-reload atomicity)."""
        with self._batchers_lock:
            b = self._batchers.get(name)
            if b is None:
                def score_fn(rows, _name=name):
                    entry = self.registry.get(_name)
                    scores, preds = entry.scorer.score_and_predict(rows)
                    return scores, preds, entry  # entry = version of record

                controller = None
                if self.slo_ms and self.slo_ms > 0:
                    # AIMD searches over THIS model's compiled ladder, so
                    # every size it picks is already warm (no retrace)
                    controller = maybe_controller(
                        self.registry.get(name).scorer.ladder, self.slo_ms
                    )
                b = MicroBatcher(
                    score_fn, self.policy, controller=controller,
                    # shed/expiry counters mirrored per model at the
                    # batcher's own sites (mesh-obs conservation)
                    model_scope=self.models.register(name),
                )
                self._batchers[name] = b
            return b

    def _rate_for(self, name: str) -> ScoredRateWindow:
        """Per-model scored-rows/s window (model-aware Retry-After)."""
        r = self._scored_by_model.get(name)
        if r is None:
            with self._batchers_lock:
                r = self._scored_by_model.get(name)
                if r is None:
                    r = self._scored_by_model[name] = ScoredRateWindow()
        return r

    def _request_done(self, ms: float) -> None:
        """Per-request bookkeeping shared by every completion path."""
        self.latency.record(ms)
        if self.slo_burn is not None:
            self.slo_burn.observe(ms)

    def retry_after_s(self, model: Optional[str] = None) -> int:
        """429 Retry-After hint: queued rows ÷ recent scored-rows/s
        (clamped to a small bound) — how long the queue actually needs
        to drain before a retry has a chance. When the request named a
        model the estimate uses THAT model's own queue depth and drain
        rate: queues drain per batcher, so a cold model's queue behind a
        hot model would otherwise borrow the hot model's rate and be
        wrong by the traffic ratio. Global aggregate is the fallback."""
        with self._batchers_lock:
            batchers = dict(self._batchers)
            rates = dict(self._scored_by_model)
        if model and model in batchers:
            # the model's own window; empty (no drain evidence yet) ->
            # the clamp bound, the honest worst case
            rate = rates.get(model)
            if rate is None:
                rate = ScoredRateWindow()
            return retry_after_s(batchers[model].queued_rows, rate)
        backlog = sum(b.queued_rows for b in batchers.values())
        return retry_after_s(backlog, self._scored)

    def _request_errored(self, status: int) -> None:
        """429/504 burned SLO budget without ever being scored; a 503
        drain is the server going away, not a burn."""
        if self.slo_burn is not None and status in (429, 504):
            self.slo_burn.observe(violated=True)

    def _observe_quality(self, entry, rows, preds) -> None:
        """Feed the model-quality plane (drift sketches). Failures are
        counted and logged — monitoring must never 500 a request."""
        if not self.quality.enabled():
            return
        try:
            self.quality.observe(entry, rows, preds)
        except Exception as e:  # noqa: BLE001 — monitoring, never the request
            obs_inc("quality.errors")
            log.warning("quality observe failed: %s: %s",
                        type(e).__name__, e)

    def predict(self, rows, model: Optional[str] = None,
                deadline_ms: Optional[float] = None, timeout: float = 30.0,
                trace=None):
        """The serving hot path (HTTP handler and tests both land here).

        `trace` is an obs.trace ctx the HTTP handler began (it owns the
        finish — the response write is part of the trace); direct callers
        leave it None and this method begins/finishes its own, so a bench
        or embedded caller gets the same exemplars the HTTP path does."""
        if self.draining:
            raise ServeClosed("server is draining")
        names = self.registry.names()
        if not names:
            raise KeyError("no models loaded")
        name = model or names[0]
        try:
            entry = self.registry.get(name)  # 404 before enqueue for bad names
        except KeyError:
            # unknown-name accounting lands in the bounded __overflow__
            # family (only registry-loaded names get their own) — a 404
            # name-flood moves one counter, never the family map
            self.models.record_not_found(name)
            raise
        scope = self.models.register(name)
        # fleet restart drill: kind=kill here takes this replica down
        # mid-request, exactly like a hardware loss under load
        chaos_point("serve.worker")
        own = trace is None
        ctx = obs_trace.begin() if own else trace
        t0 = time.perf_counter()
        try:
            cache = self.cache
            if cache is not None:
                hit = cache.lookup(cache.model_key(entry), rows, scope=scope)
                ctx.hop_at("serve.cache", t0, time.perf_counter(),
                           hit=hit is not None, rows=len(rows))
                if hit is not None:
                    # every row of this request was scored before by the
                    # CURRENT entry: bypass the queue entirely (no batcher,
                    # no scorer) — the stored values ARE the scored path's
                    # outputs, so the response is bit-identical to a cold one
                    ms = (time.perf_counter() - t0) * 1e3
                    self._request_done(ms)
                    obs_inc("serve.requests")
                    obs_inc("serve.request_rows", len(rows))
                    self.models.record_request(name, len(rows), ms)
                    preds_hit = np.asarray([h[1] for h in hit])
                    # cache hits are served traffic: the drift sketches
                    # must see the distribution clients actually send
                    self._observe_quality(entry, rows, preds_hit)
                    if own:
                        obs_trace.finish(ctx, status=200, latency_ms=ms,
                                         rows=len(rows), cached=True)
                    return {
                        "model": name,
                        "version": entry.version,
                        "cached": True,
                        "scores": np.asarray([h[0] for h in hit]).tolist(),
                        "predictions": preds_hit.tolist(),
                    }
            pending = self.batcher_for(name).submit(
                rows, deadline_ms=deadline_ms, trace=ctx
            )
            scores, preds = pending.get(timeout)
            if ctx.ids and pending.t_done is not None:
                # completion -> this thread resumed: GIL/scheduler wake
                # latency, a real stage of the request under load
                ctx.hop_at("serve.wake", pending.t_done, time.perf_counter())
        except OverloadError:
            self._request_errored(429)
            self.models.record_violation(name, 429)
            if own:
                obs_trace.finish(ctx, status=429, rows=len(rows),
                                 latency_ms=(time.perf_counter() - t0) * 1e3)
            raise
        except DeadlineExceeded:
            self._request_errored(504)
            self.models.record_violation(name, 504)
            if own:
                obs_trace.finish(ctx, status=504, rows=len(rows),
                                 latency_ms=(time.perf_counter() - t0) * 1e3)
            raise
        except ServeClosed:
            if own:  # a drain is not an SLO burn, but the trace closes
                obs_trace.finish(ctx, status=503, rows=len(rows),
                                 latency_ms=(time.perf_counter() - t0) * 1e3)
            raise
        except Exception:
            # batch error, timeout, anything else: an owned head-sampled
            # trace must still land in the ring (status 500) instead of
            # leaking with its hops unrecorded
            if own:
                obs_trace.finish(ctx, status=500, rows=len(rows),
                                 latency_ms=(time.perf_counter() - t0) * 1e3)
            raise
        ms = (time.perf_counter() - t0) * 1e3
        self._request_done(ms)
        # scored-path completions only (a cache hit never drained the
        # queue): the Retry-After estimate wants the queue's drain rate
        self._scored.record(len(rows))
        self._rate_for(name).record(len(rows))
        obs_inc("serve.requests")
        obs_inc("serve.request_rows", len(rows))
        self.models.record_request(name, len(rows), ms)
        # version from the batch's own entry resolution — the response
        # must name the model that actually scored it, not whatever was
        # current at enqueue time (hot-reload race)
        entry = pending.meta or self.registry.get(name)
        # quality plane: keyed by the entry that ACTUALLY scored the
        # batch, like the cache below — a swap between submit and score
        # must not attribute rows to the wrong version's sketches
        self._observe_quality(entry, rows, preds)
        if cache is not None:
            # keyed by the entry that ACTUALLY scored the batch: a swap
            # landing between submit and score must not mislabel rows
            cache.store(cache.model_key(entry), rows, scores, preds,
                        scope=scope)
        if own:
            obs_trace.finish(ctx, status=200, latency_ms=ms, rows=len(rows))
        return {
            "model": name,
            "version": entry.version,
            "scores": np.asarray(scores).tolist(),
            "predictions": np.asarray(preds).tolist(),
        }

    # -- status -----------------------------------------------------------

    def ready(self) -> bool:
        with self._batchers_lock:  # batcher_for inserts concurrently
            batchers = list(self._batchers.values())
        return (
            not self.draining
            and len(self.registry) > 0
            and all(not b.closed for b in batchers)
        )

    def _entry_snapshot(self) -> dict:
        """{name: entry} resolved ONCE per model for a whole payload: a
        scrape racing a hot-reload swap must read each model's fields
        from one entry, never blend pre-swap `version` with post-swap
        `rung` (the registry swaps atomically per name; repeated
        `get(n)` calls inside one payload would not)."""
        out = {}
        for n in self.registry.names():
            try:
                out[n] = self.registry.get(n)
            except KeyError:
                continue  # unloaded between names() and get() — skip
        return out

    def health_payload(self) -> dict:
        counters = obs_snapshot()["counters"]
        return {
            "status": "draining" if self.draining else "ok",
            "uptime_s": round(time.time() - self._started_at, 1),
            "models": {
                n: {"version": entry.version}
                for n, entry in self._entry_snapshot().items()
            },
            "health_events": {
                k: v for k, v in sorted(counters.items())
                if k.startswith("health.") and k.count(".") == 1
            },
        }

    def metrics_payload(self, raw: bool = False, history: bool = False,
                        quality: bool = False, prof: bool = False,
                        models: bool = False) -> dict:
        snap = obs_snapshot()
        with self._batchers_lock:  # batcher_for inserts concurrently
            batchers = dict(self._batchers)
        # one entry per model for the WHOLE payload (models block, prof
        # block, per-model plane): no intra-scrape hot-reload blending
        entries = self._entry_snapshot()
        latency = self.latency.percentiles()
        if raw:
            # the fleet front merges replica rings (union windowed on the
            # sample timestamps, then one percentile pass) — fleet p99
            # must be a fleet number computed over RECENT samples
            latency["raw_ms"] = self.latency.raw()
        out = {
            # identity rides every metrics scrape so the front's fleet
            # table (and a postmortem diffing scrapes) names the replica
            "replica": {"replica_id": self.replica_id, "pid": os.getpid()},
            "latency": latency,
            "queue_depth": {n: b.queue_depth for n, b in batchers.items()},
            "batching": {
                n: (
                    b.controller.snapshot()
                    if b.controller is not None
                    else {"max_batch": self.policy.max_batch,
                          "max_wait_ms": self.policy.max_wait_ms}
                )
                for n, b in batchers.items()
            },
            "models": {
                n: {
                    "version": entry.version,
                    "ladder": list(entry.scorer.ladder),
                    "pinned": self.registry.pinned(n),
                    # effective scoring rung + backend (fused/binned
                    # lowering evidence — serve_bench fleet records it)
                    "rung": entry.scorer.rung_info(),
                }
                for n, entry in entries.items()
            },
            "counters": {k: round(v, 3) for k, v in sorted(snap["counters"].items())},
            "gauges": {k: round(v, 4) for k, v in sorted(snap["gauges"].items())},
        }
        if self.cache is not None:
            out["cache"] = {"rows": len(self.cache),
                            "max_rows": self.cache.max_rows}
        if models:
            # mesh-obs per-model table (`/metrics?models=1`): scoped
            # counters + latency percentiles (+ raw rings under &raw=1 —
            # the fleet front's per-model union input) + sentinel state,
            # joined with per-model cache occupancy and the r20 prof
            # plane's per-model execute-time attribution
            for n in entries:
                self.models.register(n)  # loaded-but-quiet models show up
            block = self.models.snapshot(raw=raw,
                                         counters=snap["counters"])
            if self.cache is not None:
                occupancy = self.cache.scope_rows()
                for s, mb in block["models"].items():
                    mb["cache_rows"] = occupancy.get(s, 0)
            from ..obs import profiler as obs_profiler

            if obs_profiler.enabled():
                for n, entry in entries.items():
                    mb = block["models"].get(self.models.scope_name(n))
                    if mb is not None:
                        mb["prof"] = entry.scorer.prof_snapshot()
            out["model_metrics"] = block
        if history:
            # metrics history plane: bounded per-metric (ts, value) rings
            # sampled by the obs heartbeat thread (YTK_OBS_HISTORY_N) —
            # {} when the plane is off (obs disabled or N=0)
            out["history"] = OBS_REGISTRY.history_snapshot() or {}
        if quality:
            # model-quality plane: per-model drift/calibration metrics +
            # the serialized serve-side GK sketches the fleet front
            # merges (obs/quality.py; {} when YTK_QUALITY_SAMPLE=0)
            out["quality"] = (
                self.quality.snapshot(include_sketches=True)
                if self.quality.enabled() else {}
            )
        if prof:
            # ytkprof plane (obs/profiler.py): per-model per-rung settled
            # execute-time attribution + the process compile ledger —
            # enabled:false with empty blocks when YTK_PROF is off
            from ..obs import profiler as obs_profiler

            out["prof"] = {
                "enabled": obs_profiler.enabled(),
                "models": {
                    n: entry.scorer.prof_snapshot()
                    for n, entry in entries.items()
                },
                "compile": obs_profiler.LEDGER.snapshot(limit=16),
                "phases": obs_profiler.phases_snapshot(),
            }
        return out

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "ServeApp":
        app = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # stderr spam -> logging
                log.debug("http: " + fmt, *args)

            def _json(self, code: int, payload: dict,
                      headers: Optional[Dict[str, str]] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _admin(self, action: str) -> None:
                """Registry version control: rollback / pin / unpin by
                model name (default: the first loaded model)."""
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError(
                            "request body must be a JSON object"
                        )
                    names = app.registry.names()
                    if not names:
                        raise KeyError("no models loaded")
                    name = req.get("model") or names[0]
                    if action == "rollback":
                        entry = app.registry.rollback(name)
                        self._json(200, {"model": name, "action": action,
                                         "version": entry.version,
                                         "pinned": True})
                    else:
                        getattr(app.registry, action)(name)
                        self._json(200, {"model": name, "action": action,
                                         "pinned": app.registry.pinned(name)})
                except NoPreviousVersion as e:
                    # the model exists; there is just nothing to roll back
                    # to — not an unknown-name 404
                    self._json(409, {"error": str(e.args[0]),
                                     "type": "no_previous_version"})
                except KeyError as e:
                    self._json(404, {"error": str(e.args[0]),
                                     "type": "unknown_model"})
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": str(e), "type": "bad_request"})

            def do_GET(self):  # noqa: N802 — stdlib handler API
                split = urllib.parse.urlsplit(self.path)
                path = split.path
                query = urllib.parse.parse_qs(split.query)
                if path == "/healthz":
                    self._json(200, app.health_payload())
                elif path == "/readyz":
                    ok = app.ready()
                    self._json(200 if ok else 503,
                               {"ready": ok,
                                "status": "draining" if app.draining else
                                ("ok" if ok else "no models")})
                elif path == "/metrics":
                    raw = query.get("raw", ["0"])[0] not in ("0", "")
                    hist = query.get("history", ["0"])[0] not in ("0", "")
                    qual = query.get("quality", ["0"])[0] not in ("0", "")
                    prof = query.get("prof", ["0"])[0] not in ("0", "")
                    mdl = query.get("models", ["0"])[0] not in ("0", "")
                    self._json(200, app.metrics_payload(
                        raw=raw, history=hist, quality=qual, prof=prof,
                        models=mdl))
                elif path == "/admin/traces":
                    # the per-process exemplar ring: head-sampled + tail-
                    # retained request traces (obs/trace.py); obs_report
                    # merges these cross-process into one waterfall
                    self._json(200, obs_trace.exemplars_payload())
                else:
                    self._json(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):  # noqa: N802
                if self.path in ("/admin/rollback", "/admin/pin",
                                 "/admin/unpin"):
                    self._admin(self.path.rsplit("/", 1)[1])
                    return
                if self.path != "/predict":
                    self._json(404, {"error": f"unknown path {self.path}"})
                    return
                t_parse = time.perf_counter()
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    rows = req.get("rows")
                    if rows is None:
                        feats = req.get("features")
                        if feats is None:
                            raise ValueError(
                                'request needs "features" or "rows"')
                        rows = [feats]
                    if not isinstance(rows, list) or not all(
                        isinstance(r, dict) for r in rows
                    ):
                        raise ValueError('"rows" must be a list of objects')
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": str(e), "type": "bad_request"})
                    return
                # request trace: adopt the front's propagated ids (the
                # X-Ytk-Trace header a forwarded batch carries), else let
                # the head sampler decide; the handler owns begin+finish
                # so parse and response write are part of the trace
                ctx = obs_trace.begin(
                    self.headers.get(obs_trace.TRACE_HEADER)
                )
                ctx.hop_at("serve.parse", t_parse, time.perf_counter(),
                           rows=len(rows))

                def _reply(status: int, payload: dict,
                           headers: Optional[Dict[str, str]] = None) -> None:
                    with ctx.hop("serve.write", status=status):
                        self._json(status, payload, headers=headers)
                    obs_trace.finish(
                        ctx, status=status, rows=len(rows),
                        latency_ms=(time.perf_counter() - t_parse) * 1e3,
                    )

                with obs_span("serve.request", rows=len(rows)):
                    try:
                        out = app.predict(
                            rows,
                            model=req.get("model"),
                            deadline_ms=req.get("deadline_ms"),
                            trace=ctx,
                        )
                    except OverloadError as e:
                        # Retry-After: queue-drain estimate so a shed
                        # client backs off intelligently (clamped);
                        # model-aware when the request named one — the
                        # named model's own queue and drain rate
                        _reply(429, {"error": str(e), "type": "overload"},
                               headers={"Retry-After":
                                        str(app.retry_after_s(
                                            req.get("model")))})
                        return
                    except DeadlineExceeded as e:
                        _reply(504, {"error": str(e), "type": "deadline"})
                        return
                    except ServeClosed as e:
                        _reply(503, {"error": str(e), "type": "draining"})
                        return
                    except KeyError as e:
                        _reply(404, {"error": str(e.args[0]),
                                     "type": "unknown_model"})
                        return
                    except Exception as e:  # noqa: BLE001 — typed 500
                        obs_inc("serve.request_errors")
                        log.exception("predict failed")
                        _reply(500, {"error": f"{type(e).__name__}: {e}",
                                     "type": "internal"})
                        return
                _reply(200, out)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="ytk-serve-http",
            kwargs={"poll_interval": 0.1}, daemon=True,
        )
        self._serve_thread.start()
        if obs_enabled():
            # metrics history plane: per-metric rings sampled by the obs
            # heartbeat thread; /metrics?history=1 exports them (no-op
            # when YTK_OBS_HISTORY_N=0)
            start_history_sampler()
        # quality evaluator: periodic drift/calibration judgement against
        # each model's training sidecar (no-op when YTK_QUALITY_SAMPLE=0)
        obs_quality.start_quality_evaluator()
        log.info("serve: listening on %s:%d (%d model(s))",
                 self.host, self.port, len(self.registry))
        return self

    @thread_guard
    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful by default: refuse new work, finish queued requests,
        then stop the listener and the reload watcher."""
        self.draining = True  # readyz flips immediately
        with self._batchers_lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.close(drain=drain, timeout=timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.registry.close()
        log.info("serve: stopped (drained=%s)", drain)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful drain (in a thread; the handler must
        return so in-flight handler frames can finish their writes)."""

        def _drain(signum, frame):
            log.info("serve: signal %d, draining", signum)
            threading.Thread(
                target=self.stop, kwargs={"drain": True}, daemon=True
            ).start()

        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
