"""Dynamic micro-batching queue with backpressure (Clipper, NSDI'17).

Requests (one or more feature-dict rows each) enqueue into a bounded queue;
one worker thread coalesces them into batches of up to `max_batch` rows,
waiting at most `max_wait_ms` for stragglers after the first request
arrives. The scorer's shape ladder then pads the coalesced batch to a
compiled rung, so the adaptive batch size never costs a retrace.

Backpressure is load *shedding*, not buffering: when the queue holds
`max_queue` pending requests, submit() raises OverloadError immediately —
the caller (server.py) turns that into a typed 429 so the client can back
off, instead of every request slowly timing out (Clipper's
"reject early under overload" rule). Per-request deadlines are checked at
dequeue time: a request that already waited past its deadline is failed
with DeadlineExceeded without wasting scorer time on it.

Shutdown is graceful by default: close(drain=True) stops intake, lets the
worker finish everything already queued, and joins it — the SIGTERM path
(server.py) rides this so in-flight requests complete.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import event as obs_event, gauge as obs_gauge, inc as obs_inc, span as obs_span
from ..obs import trace as obs_trace
from ..obs.recorder import thread_guard


class OverloadError(RuntimeError):
    """Bounded queue full — the request was shed, not enqueued."""


#: Retry-After hints are clamped to this bound: a drain estimate past it
#: means "overloaded, come back soon-ish" — a huge honest number would
#: just push clients into one synchronized retry storm later
RETRY_AFTER_MAX_S = 8


class ScoredRateWindow:
    """Recent scored-rows/s estimate feeding the 429 Retry-After hint.

    Both shed paths (replica/solo server and fleet front) derive the
    header from the same arithmetic: backlog rows ÷ this window's rate,
    clamped to [1, RETRY_AFTER_MAX_S] seconds — so a client backs off
    roughly as long as the queue actually needs to drain instead of
    hammering an overloaded process. record() is called once per
    completed request on the success path; reads tolerate an empty
    window (no drain evidence -> the clamp bound, the honest worst case).
    """

    def __init__(self, window_s: float = 10.0, maxlen: int = 1024):
        self.window_s = float(window_s)
        self._ring: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, rows: int) -> None:
        with self._lock:
            self._ring.append((time.time(), int(rows)))

    def rows_per_s(self) -> float:
        now = time.time()
        with self._lock:
            pts = [(t, r) for t, r in self._ring if now - t <= self.window_s]
        if not pts:
            return 0.0
        total = sum(r for _t, r in pts)
        # divide by the span the retained samples ACTUALLY cover: under
        # load the bounded ring holds far less than window_s of history
        # (1024 entries at 50k req/s is ~20ms) and dividing by the full
        # window would underestimate throughput ~500x, degenerating every
        # Retry-After to the clamp bound exactly when the estimate
        # matters most
        span = now - pts[0][0]
        return total / max(span, 0.05)


def retry_after_s(backlog_rows: float, rate: ScoredRateWindow) -> int:
    """Queue-drain estimate in whole seconds for a Retry-After header."""
    rows_per_s = rate.rows_per_s()
    if rows_per_s <= 0.0:
        return RETRY_AFTER_MAX_S
    est = math.ceil(backlog_rows / rows_per_s)
    return max(1, min(RETRY_AFTER_MAX_S, int(est)))


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired before it reached the scorer."""


class ServeClosed(RuntimeError):
    """The batcher is draining or closed; no new work is accepted."""


@dataclass
class BatchPolicy:
    """Micro-batching knobs (CLI flags / YTK_SERVE_* env, docs/serving.md)."""

    max_batch: int = 512  # rows per scorer call (ladder top is the ceiling)
    max_wait_ms: float = 2.0  # straggler wait after the first queued request
    max_queue: int = 2048  # pending requests before shedding
    default_deadline_ms: float = 0.0  # 0 = no deadline


class _Pending:
    """One submitted request: rows in, result (or typed error) out.

    The worker stores the whole batch result + this request's offset; the
    slice happens in get() on the caller's thread. Completion signaling
    is LAZY: the common high-throughput pattern (a deep in-flight window
    where results land before get() is called) pays one flag write under
    a shared lock per request, and a threading.Event is allocated and set
    only when a caller actually has to block — at 30k req/s the per-
    request Event create + set was a measurable slice of the front's GIL
    budget (scripts/serve_bench.py --fleet found it)."""

    __slots__ = ("rows", "result", "meta", "_off", "error", "t_enq",
                 "t_done", "deadline", "trace", "_done", "_event", "_sig")

    def __init__(self, rows, deadline: Optional[float], sig: threading.Lock,
                 trace=None):
        self.rows = rows
        self.result = None  # (batch_scores, batch_preds) shared by the batch
        self.meta = None  # score_fn's optional 3rd return (e.g. model entry)
        self._off = 0
        self.error: Optional[BaseException] = None
        self.t_enq = time.perf_counter()
        self.t_done = None  # set by the worker at completion: the caller
        # measures its wake-up gap (completion -> get() return) from it
        self.deadline = deadline  # perf_counter timestamp or None
        # sampled request-trace ctx (obs/trace.py) or None: the worker
        # records the queue-wait hop and copies the batch's sub-hops
        # (scorer assemble/execute, front forward) onto it
        self.trace = trace
        self._done = False
        self._event: Optional[threading.Event] = None
        self._sig = sig  # shared per-batcher signal lock (lost-wake guard)

    def finish(self) -> None:
        """Worker side: result/meta/error fields are set — publish. The
        flag flip and the waiter's event creation are serialized by the
        shared lock, so a wake can never be lost."""
        with self._sig:
            self._done = True
            ev = self._event
        if ev is not None:
            ev.set()

    def get(self, timeout: Optional[float] = None):
        if not self._done:
            with self._sig:
                if not self._done and self._event is None:
                    self._event = threading.Event()
                ev = self._event if not self._done else None
            if ev is not None and not ev.wait(timeout):
                raise TimeoutError("serve request did not complete in time")
        if self.error is not None:
            raise self.error
        scores, preds = self.result
        n = len(self.rows)
        return (
            np.asarray(scores[self._off : self._off + n]),
            np.asarray(preds[self._off : self._off + n]),
        )


class MicroBatcher:
    """Coalesce submitted rows into scorer batches on a worker thread.

    `score_fn(rows) -> (scores, preds)` is called with at most
    `policy.max_batch` rows; results are split back per request. Thread-safe
    for any number of producers.
    """

    def __init__(
        self,
        score_fn: Callable,
        policy: Optional[BatchPolicy] = None,
        controller=None,
        trace_site: str = "serve",
        model_scope: Optional[str] = None,
    ):
        self.score_fn = score_fn
        self.policy = policy or BatchPolicy()
        # hop-name prefix for request traces through this batcher:
        # "serve" inside a replica/solo server, "front" for the fleet
        # front's per-replica forwarders (queue hop = f"{site}.queue")
        self.trace_site = trace_site
        # mesh-obs family scope (obs/model_metrics.py): when set, the shed
        # and deadline-expiry counters are mirrored per model at the SAME
        # sites as their global twins — the exact-conservation identity
        # (sum over `serve.model.*.shed` == `serve.shed`) holds because no
        # other code path increments either
        self.model_scope = model_scope
        # optional AIMD batch-size controller (serve/fleet/aimd.py): when
        # set, it supplies max_batch/max_wait_ms live (snapped to the
        # compiled ladder) and is fed per-request latencies by the worker;
        # None keeps the fixed BatchPolicy knobs
        self.controller = controller
        self._queue: collections.deque = collections.deque()
        self._queued_rows = 0  # maintained with _queue; O(1) linger checks
        self._lock = threading.Lock()
        self._sig = threading.Lock()  # _Pending completion signaling
        self._not_empty = threading.Condition(self._lock)
        self._closing = False
        self._closed = False
        self._worker = threading.Thread(
            target=self._loop, name="ytk-serve-batcher", daemon=True
        )
        self._worker.start()

    # -- producer side ----------------------------------------------------

    def submit(
        self,
        rows: Sequence[Dict[str, float]],
        deadline_ms: Optional[float] = None,
        trace=None,
    ) -> _Pending:
        """Enqueue rows; returns a pending handle (.get(timeout) blocks).
        Raises OverloadError (queue full) or ServeClosed synchronously.
        `trace` is an optional obs.trace ctx; the NOOP ctx is normalized
        to None here so the worker's per-request check stays one `is not
        None` on the unsampled path."""
        if deadline_ms is None:
            deadline_ms = self.policy.default_deadline_ms
        deadline = (
            time.perf_counter() + deadline_ms / 1e3 if deadline_ms and deadline_ms > 0
            else None
        )
        if trace is not None and not trace.ids:
            trace = None
        req = _Pending(list(rows), deadline, self._sig, trace=trace)
        with self._not_empty:
            if self._closing:
                raise ServeClosed("serve batcher is draining")
            if len(self._queue) >= self.policy.max_queue:
                obs_inc("serve.shed")
                if self.model_scope is not None:
                    obs_inc(f"serve.model.{self.model_scope}.shed")
                raise OverloadError(
                    f"serve queue full ({self.policy.max_queue} pending)"
                )
            was_empty = not self._queue
            self._queue.append(req)
            self._queued_rows += len(req.rows)
            # queue_depth gauge is maintained by the worker (once per batch);
            # a per-submit gauge write is measurable at 30k req/s
            # wake the worker only on the transitions it acts on (first
            # request, or a full batch ready); notifying every submit makes
            # the linger window a notify/wake ping-pong that caps throughput
            if was_empty or self._queued_rows >= self._max_batch():
                self._not_empty.notify()
        return req

    def score(self, rows, deadline_ms=None, timeout: Optional[float] = 30.0):
        """submit() + get(): (scores, preds) numpy arrays for `rows`."""
        return self.submit(rows, deadline_ms).get(timeout)

    # -- worker side ------------------------------------------------------

    def _max_batch(self) -> int:
        c = self.controller
        return c.max_batch if c is not None else self.policy.max_batch

    def _max_wait_ms(self) -> float:
        c = self.controller
        return c.max_wait_ms if c is not None else self.policy.max_wait_ms

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block for the first request, linger max_wait_ms for more, then
        take up to max_batch rows' worth. None = closed and drained."""
        wait_s = self._max_wait_ms() / 1e3
        max_batch = self._max_batch()
        with self._not_empty:
            while not self._queue:
                if self._closing:
                    return None
                self._not_empty.wait(timeout=0.05)
            if wait_s > 0 and not self._closing:
                deadline = time.perf_counter() + wait_s
                while self._queued_rows < max_batch:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._not_empty.wait(timeout=remaining)
            batch: List[_Pending] = []
            n_rows = 0
            while self._queue:
                nxt = len(self._queue[0].rows)
                if batch and n_rows + nxt > max_batch:
                    break
                req = self._queue.popleft()
                batch.append(req)
                n_rows += nxt
            self._queued_rows -= n_rows
            obs_gauge("serve.queue_depth", len(self._queue))
            return batch

    @thread_guard
    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                break
            now = time.perf_counter()
            live: List[_Pending] = []
            traced = None
            for req in batch:
                if req.trace is not None:
                    # queue-wait hop: enqueue -> dequeue, recorded for the
                    # expired requests too (the 504's trace must SHOW the
                    # queue is where its deadline went)
                    req.trace.hop_at(
                        self.trace_site + ".queue", req.t_enq, now,
                        rows=len(req.rows),
                    )
                if req.deadline is not None and now > req.deadline:
                    obs_inc("serve.deadline_expired")
                    if self.model_scope is not None:
                        obs_inc(
                            f"serve.model.{self.model_scope}"
                            ".deadline_expired"
                        )
                    req.error = DeadlineExceeded(
                        f"deadline expired after "
                        f"{(now - req.t_enq) * 1e3:.1f} ms in queue"
                    )
                    req.finish()
                else:
                    live.append(req)
                    if req.trace is not None:
                        if traced is None:
                            traced = []
                        traced.append(req.trace)
            if not live:
                continue
            rows: List[dict] = []
            for req in live:
                rows.extend(req.rows)
            if traced:
                # batch-scoped sub-hops (scorer assemble/execute, front
                # forward) recorded during score_fn land on every traced
                # request of this batch; the untraced path never touches
                # the trace module
                obs_trace.set_current_batch(traced)
            try:
                with obs_span("serve.batch", rows=len(rows), requests=len(live)):
                    out = self.score_fn(rows)
                if traced:
                    # copy the staged hops BEFORE finish(): the handler
                    # thread closes the trace the moment its pending
                    # handle completes
                    obs_trace.end_current_batch()
                    traced = None
                # score_fn returns (scores, preds) or (scores, preds, meta);
                # meta rides along per batch — the server uses it to report
                # WHICH model version actually scored these rows (resolving
                # it before enqueue would race a hot reload)
                scores, preds = out[0], out[1]
                meta = out[2] if len(out) > 2 else None
                obs_inc("serve.batches")
                obs_inc("serve.batch_rows", len(rows))
                result = (scores, preds)
                off = 0
                t_done = time.perf_counter()
                for req in live:
                    req.result = result
                    req.meta = meta
                    req._off = off
                    off += len(req.rows)
                    req.t_done = t_done
                    req.finish()
                    if self.controller is not None:
                        # client-visible latency (enqueue -> scored): the
                        # number the SLO is written against
                        self.controller.observe((t_done - req.t_enq) * 1e3)
                if self.controller is not None:
                    self.controller.note_batch()
            except Exception as e:  # noqa: BLE001 — fail the requests, not the worker
                if traced:
                    obs_trace.end_current_batch()  # partial hops still land
                obs_inc("serve.batch_errors")
                obs_event("serve.batch_error", error=type(e).__name__)
                for req in live:
                    req.error = e
                    req.finish()
        self._closed = True

    # -- shutdown ---------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake; drain=True processes everything already queued
        before the worker exits, drain=False fails queued requests."""
        with self._not_empty:
            self._closing = True
            if not drain:
                for req in self._queue:
                    req.error = ServeClosed("serve batcher closed")
                    req.finish()
                self._queue.clear()
                self._queued_rows = 0
                obs_gauge("serve.queue_depth", 0)
            self._not_empty.notify_all()
        self._worker.join(timeout=timeout)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def queued_rows(self) -> int:
        """Rows currently queued (one racy int read — the Retry-After
        estimate and the front's balancer both want a cheap snapshot,
        not a fenced count)."""
        return self._queued_rows

    @property
    def closed(self) -> bool:
        return self._closed
