"""Multi-model registry with fingerprint-watch hot reload.

The reference's serving story assumes a restart per model push; here a
trainer can dump a new model text over the served path and the registry
picks it up without dropping traffic:

  1. a watcher thread polls the model files' fingerprint (size+mtime of
     every file under model.data_path and its sidecars) every
     YTK_SERVE_WATCH_S seconds (default 5; 0 disables),
  2. on change it builds a NEW predictor + CompiledScorer and warms the
     whole shape ladder off to the side — traffic keeps hitting the old
     scorer through every compile,
  3. then swaps the entry reference atomically (one dict assignment under
     the registry lock) and records a `serve.reload` obs event.

A request therefore always sees exactly one model version: whichever entry
reference its batch resolved. Trainer dumps are atomic (write tmp +
os.replace, io/fs.py atomic_open) so the watcher can never observe a
half-written file; in-flight `*.tmp-*` names are excluded from the
fingerprint, and a multi-file dump caught mid-promotion is caught at the
set level too — the fingerprint is re-taken after the warm load and a
mismatch defers the swap (`serve.reload_deferred`) until the file set
settles. A dump that fails to parse keeps the old entry serving and
fires `serve.reload_failed`.

Continuous-training handshake (docs/continual.md): the `ytklearn-tpu
retrain` driver promotes a validated candidate over the served path and
the watcher picks it up like any other dump. `pin(name)` freezes a model
at its current in-memory version (the watcher skips it);
`rollback(name)` swaps back to the previously served entry and pins, so
a bad promotion is undone in one call without touching disk.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Dict, Optional

from ..config import knobs
from ..io.fs import is_tmp_path
from ..obs import event as obs_event, gauge as obs_gauge, inc as obs_inc
from ..obs.recorder import thread_guard
from ..predict import create_predictor
from ..resilience import chaos_point, retry_call
from .scorer import CompiledScorer

log = logging.getLogger("ytklearn_tpu.serve")


class NoPreviousVersion(KeyError):
    """rollback() on a loaded model that has never been reloaded: the
    model exists but there is no previous entry to return to — a state
    error (HTTP 409), not an unknown name (404)."""


def _sidecar_paths(predictor) -> list:
    """Every file the loaded model was parsed from (data_path tree +
    transform-stat / field-dict / tree-info sidecars where configured),
    plus the continual driver's version sidecar so a re-promotion with
    identical weights still fingerprints as a change."""
    p = predictor.params
    paths = [
        p.model.data_path,
        p.model.data_path + ".version.json",
        # bin-edge sidecar for serve-side binned scoring: an edges-only
        # change must re-lower the scorer too (gbdt/binning.py)
        p.model.data_path + ".bins.json",
        # model-quality sketch sidecar (obs/quality.py): a fresh drift
        # baseline must reload with the model it was trained with
        p.model.data_path + ".sketch.json",
    ]
    feature = getattr(p, "feature", None)
    if feature is not None and feature.transform.switch_on:
        paths.append(p.model.data_path + "_feature_transform_stat")
    field_dict = getattr(p.model, "field_dict_path", "")
    if field_dict:
        paths.append(field_dict)
    return paths


def model_fingerprint(predictor) -> str:
    """Stable digest of (path, size, mtime_ns) for every model file; ""
    when nothing exists (then any appearance is a change)."""
    h = hashlib.sha1()
    found = False
    for root in _sidecar_paths(predictor):
        try:
            files = predictor.fs.recur_get_paths([root])
        except FileNotFoundError:
            continue
        for f in sorted(files):
            if is_tmp_path(f):
                continue  # in-flight atomic write; settles by next poll
            try:
                st = os.stat(f)
                h.update(f"{f}:{st.st_size}:{st.st_mtime_ns};".encode())
            except OSError:
                # remote fs: fall back to the path list itself
                h.update(f"{f};".encode())
            found = True
    return h.hexdigest() if found else ""


class _Entry:
    __slots__ = ("name", "model_name", "config", "predictor", "scorer",
                 "fingerprint", "version", "loaded_at")

    def __init__(self, name, model_name, config, predictor, scorer,
                 fingerprint, version):
        self.name = name
        self.model_name = model_name
        self.config = config
        self.predictor = predictor
        self.scorer = scorer
        self.fingerprint = fingerprint
        self.version = version
        self.loaded_at = time.time()


class ModelRegistry:
    """name -> warmed (predictor, scorer) entries; atomic hot swap."""

    def __init__(self, ladder=None, watch_interval_s: Optional[float] = None):
        self.ladder = ladder
        if watch_interval_s is None:
            watch_interval_s = knobs.get_float("YTK_SERVE_WATCH_S")
        self.watch_interval_s = watch_interval_s
        self._entries: Dict[str, _Entry] = {}
        self._prev: Dict[str, _Entry] = {}  # last swapped-out entry per name
        self._pinned: set = set()  # names the watcher must not reload
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    # -- loading ----------------------------------------------------------

    def load(self, name: str, model_name: str, config) -> _Entry:
        """Load + warm a model under `name`; replaces any existing entry
        (warm-before-swap, same as a reload)."""
        entry = self._build(name, model_name, config, version=1)
        with self._lock:
            prev = self._entries.get(name)
            if prev is not None:
                entry.version = prev.version + 1
                self._prev[name] = prev  # rollback target
            self._entries[name] = entry
        obs_gauge("serve.models", len(self._entries))
        log.info(
            "serve: loaded model %r (%s) v%d, ladder=%s",
            name, model_name, entry.version, entry.scorer.ladder,
        )
        return entry

    def _build(self, name, model_name, config, version) -> _Entry:
        # `serve.load` retry/chaos site: a transient read fault off the
        # model store used to strand the reload until the next poll tick
        # (or fail the initial load outright) — now it costs a backoff.
        # Fatal faults (parse errors, missing files) still propagate to
        # maybe_reload's keep-serving handler on the first throw.
        def _once():
            chaos_point("serve.load")
            predictor = create_predictor(model_name, config)
            scorer = CompiledScorer(predictor, ladder=self.ladder, warmup=True)
            return predictor, scorer

        predictor, scorer = retry_call(_once, site="serve.load")
        return _Entry(
            name, model_name, config, predictor, scorer,
            model_fingerprint(predictor), version,
        )

    def get(self, name: str) -> _Entry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"no model named {name!r} is loaded")
        return entry

    def names(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- version pinning / rollback ---------------------------------------

    def pinned(self, name: str) -> bool:
        with self._lock:
            return name in self._pinned

    def pin(self, name: str) -> None:
        """Freeze `name` at its current in-memory version: the watcher (and
        explicit maybe_reload calls) skip it until unpin()."""
        self.get(name)  # KeyError for unknown names
        with self._lock:
            self._pinned.add(name)
        obs_event("serve.pin", model=name)
        log.info("serve: pinned %r (hot reload disabled)", name)

    def unpin(self, name: str) -> None:
        self.get(name)  # KeyError for unknown names (a typo must not 200)
        with self._lock:
            self._pinned.discard(name)
        obs_event("serve.unpin", model=name)
        log.info("serve: unpinned %r (hot reload re-enabled)", name)

    def rollback(self, name: str) -> _Entry:
        """Swap `name` back to the previously served entry (the one the
        last load/reload replaced) and PIN it, so the watcher doesn't
        immediately re-promote the bad on-disk model. The undo button for
        a bad continual promotion; raises KeyError for an unknown name
        and NoPreviousVersion for a known model with nothing to return
        to (the server maps them to 404 vs 409)."""
        with self._lock:
            entry = self._entries.get(name)
            prev = self._prev.get(name)
            if entry is None:
                raise KeyError(f"no model named {name!r} is loaded")
            if prev is None:
                raise NoPreviousVersion(
                    f"model {name!r} has no previous version to roll back to"
                )
            self._entries[name] = prev
            self._prev[name] = entry  # rollback is itself undoable
            self._pinned.add(name)
        obs_inc("serve.rollback")
        obs_event(
            "serve.rollback", model=name,
            from_version=entry.version, to_version=prev.version,
        )
        log.warning(
            "serve: rolled back %r v%d -> v%d and pinned (unpin to resume "
            "hot reload)", name, entry.version, prev.version,
        )
        return prev

    # -- hot reload -------------------------------------------------------

    def maybe_reload(self, name: str) -> bool:
        """Reload `name` if its files changed. Warm first, swap after —
        traffic never sees a cold or half-swapped scorer. True = swapped.
        Pinned names never reload (version-pinning hook)."""
        entry = self.get(name)
        if self.pinned(name):
            return False
        fp = model_fingerprint(entry.predictor)
        if fp == entry.fingerprint:
            return False
        t0 = time.perf_counter()
        try:
            fresh = self._build(
                name, entry.model_name, entry.config, entry.version + 1
            )
            # stamp the PRE-read fingerprint, not a post-read one: if the
            # dump was still being written while _build parsed it, the
            # settled files fingerprint differently than `fp` and the next
            # poll reloads again — a post-read stamp would freeze a torn
            # model in place forever
            fresh.fingerprint = fp
        except Exception as e:  # noqa: BLE001 — keep serving the old model
            obs_inc("serve.reload_failed")
            obs_event("serve.reload_failed", model=name, error=type(e).__name__)
            log.warning("serve: reload of %r failed, keeping v%d: %s",
                        name, entry.version, e)
            return False
        if model_fingerprint(fresh.predictor) != fp:
            # the file SET changed while _build was parsing it (a multi-file
            # promotion caught mid-move): individual files are whole (atomic
            # replaces) but the loaded predictor may blend versions — don't
            # serve it; the next poll reloads once the set settles
            obs_inc("serve.reload_deferred")
            log.info(
                "serve: reload of %r deferred — model files changed during "
                "the warm load; keeping v%d until the set settles",
                name, entry.version,
            )
            return False
        with self._lock:
            if name in self._pinned:
                # pinned (or rolled back, which pins) DURING the warm load:
                # the operator's freeze wins over the in-flight build
                obs_inc("serve.reload_deferred")
                log.info(
                    "serve: reload of %r discarded — pinned during the "
                    "warm load; keeping v%d",
                    name, self._entries[name].version,
                )
                return False
            self._prev[name] = self._entries[name]  # rollback target
            self._entries[name] = fresh  # the atomic swap
        obs_inc("serve.reload")
        obs_event(
            "serve.reload",
            model=name,
            version=fresh.version,
            warm_ms=round((time.perf_counter() - t0) * 1e3, 1),
        )
        log.info("serve: hot-reloaded %r -> v%d (warmed in %.0f ms)",
                 name, fresh.version, (time.perf_counter() - t0) * 1e3)
        return True

    def start_watching(self) -> None:
        """Poll fingerprints every watch_interval_s (0/negative disables)."""
        if self.watch_interval_s <= 0 or self._watcher is not None:
            return
        self._watcher = threading.Thread(
            target=self._watch_loop, name="ytk-serve-watch", daemon=True
        )
        self._watcher.start()

    @thread_guard
    def _watch_loop(self) -> None:
        while not self._stop.wait(self.watch_interval_s):
            for name in self.names():
                try:
                    self.maybe_reload(name)
                except Exception:  # noqa: BLE001 — the watcher must survive
                    log.exception("serve: watch reload of %r crashed", name)

    def close(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5.0)
            self._watcher = None
