"""Multi-model registry with fingerprint-watch hot reload.

The reference's serving story assumes a restart per model push; here a
trainer can dump a new model text over the served path and the registry
picks it up without dropping traffic:

  1. a watcher thread polls the model files' fingerprint (size+mtime of
     every file under model.data_path and its sidecars) every
     YTK_SERVE_WATCH_S seconds (default 5; 0 disables),
  2. on change it builds a NEW predictor + CompiledScorer and warms the
     whole shape ladder off to the side — traffic keeps hitting the old
     scorer through every compile,
  3. then swaps the entry reference atomically (one dict assignment under
     the registry lock) and records a `serve.reload` obs event.

A request therefore always sees exactly one model version: whichever entry
reference its batch resolved. A half-written dump just fingerprints
differently again on the next poll and reloads once it settles; a dump
that fails to parse keeps the old entry serving and fires
`serve.reload_failed`.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Dict, Optional

from ..config import knobs
from ..obs import event as obs_event, gauge as obs_gauge, inc as obs_inc
from ..predict import create_predictor
from .scorer import CompiledScorer

log = logging.getLogger("ytklearn_tpu.serve")


def _sidecar_paths(predictor) -> list:
    """Every file the loaded model was parsed from (data_path tree +
    transform-stat / field-dict / tree-info sidecars where configured)."""
    p = predictor.params
    paths = [p.model.data_path]
    feature = getattr(p, "feature", None)
    if feature is not None and feature.transform.switch_on:
        paths.append(p.model.data_path + "_feature_transform_stat")
    field_dict = getattr(p.model, "field_dict_path", "")
    if field_dict:
        paths.append(field_dict)
    return paths


def model_fingerprint(predictor) -> str:
    """Stable digest of (path, size, mtime_ns) for every model file; ""
    when nothing exists (then any appearance is a change)."""
    h = hashlib.sha1()
    found = False
    for root in _sidecar_paths(predictor):
        try:
            files = predictor.fs.recur_get_paths([root])
        except FileNotFoundError:
            continue
        for f in sorted(files):
            try:
                st = os.stat(f)
                h.update(f"{f}:{st.st_size}:{st.st_mtime_ns};".encode())
            except OSError:
                # remote fs: fall back to the path list itself
                h.update(f"{f};".encode())
            found = True
    return h.hexdigest() if found else ""


class _Entry:
    __slots__ = ("name", "model_name", "config", "predictor", "scorer",
                 "fingerprint", "version", "loaded_at")

    def __init__(self, name, model_name, config, predictor, scorer,
                 fingerprint, version):
        self.name = name
        self.model_name = model_name
        self.config = config
        self.predictor = predictor
        self.scorer = scorer
        self.fingerprint = fingerprint
        self.version = version
        self.loaded_at = time.time()


class ModelRegistry:
    """name -> warmed (predictor, scorer) entries; atomic hot swap."""

    def __init__(self, ladder=None, watch_interval_s: Optional[float] = None):
        self.ladder = ladder
        if watch_interval_s is None:
            watch_interval_s = knobs.get_float("YTK_SERVE_WATCH_S")
        self.watch_interval_s = watch_interval_s
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    # -- loading ----------------------------------------------------------

    def load(self, name: str, model_name: str, config) -> _Entry:
        """Load + warm a model under `name`; replaces any existing entry
        (warm-before-swap, same as a reload)."""
        entry = self._build(name, model_name, config, version=1)
        with self._lock:
            prev = self._entries.get(name)
            if prev is not None:
                entry.version = prev.version + 1
            self._entries[name] = entry
        obs_gauge("serve.models", len(self._entries))
        log.info(
            "serve: loaded model %r (%s) v%d, ladder=%s",
            name, model_name, entry.version, entry.scorer.ladder,
        )
        return entry

    def _build(self, name, model_name, config, version) -> _Entry:
        predictor = create_predictor(model_name, config)
        scorer = CompiledScorer(predictor, ladder=self.ladder, warmup=True)
        return _Entry(
            name, model_name, config, predictor, scorer,
            model_fingerprint(predictor), version,
        )

    def get(self, name: str) -> _Entry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"no model named {name!r} is loaded")
        return entry

    def names(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- hot reload -------------------------------------------------------

    def maybe_reload(self, name: str) -> bool:
        """Reload `name` if its files changed. Warm first, swap after —
        traffic never sees a cold or half-swapped scorer. True = swapped."""
        entry = self.get(name)
        fp = model_fingerprint(entry.predictor)
        if fp == entry.fingerprint:
            return False
        t0 = time.perf_counter()
        try:
            fresh = self._build(
                name, entry.model_name, entry.config, entry.version + 1
            )
            # stamp the PRE-read fingerprint, not a post-read one: if the
            # dump was still being written while _build parsed it, the
            # settled files fingerprint differently than `fp` and the next
            # poll reloads again — a post-read stamp would freeze a torn
            # model in place forever
            fresh.fingerprint = fp
        except Exception as e:  # noqa: BLE001 — keep serving the old model
            obs_inc("serve.reload_failed")
            obs_event("serve.reload_failed", model=name, error=type(e).__name__)
            log.warning("serve: reload of %r failed, keeping v%d: %s",
                        name, entry.version, e)
            return False
        with self._lock:
            self._entries[name] = fresh  # the atomic swap
        obs_inc("serve.reload")
        obs_event(
            "serve.reload",
            model=name,
            version=fresh.version,
            warm_ms=round((time.perf_counter() - t0) * 1e3, 1),
        )
        log.info("serve: hot-reloaded %r -> v%d (warmed in %.0f ms)",
                 name, fresh.version, (time.perf_counter() - t0) * 1e3)
        return True

    def start_watching(self) -> None:
        """Poll fingerprints every watch_interval_s (0/negative disables)."""
        if self.watch_interval_s <= 0 or self._watcher is not None:
            return
        self._watcher = threading.Thread(
            target=self._watch_loop, name="ytk-serve-watch", daemon=True
        )
        self._watcher.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.watch_interval_s):
            for name in self.names():
                try:
                    self.maybe_reload(name)
                except Exception:  # noqa: BLE001 — the watcher must survive
                    log.exception("serve: watch reload of %r crashed", name)

    def close(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5.0)
            self._watcher = None
