"""Replica worker process management for the serving fleet.

A *replica* is one ordinary single-process server (`cli serve` — the
whole r9 batcher+CompiledScorer+registry stack) spawned as a subprocess
with `--replica-id N --port 0`. The contract between front and worker is
deliberately thin — shared-nothing, one pipe line and one port:

  banner     the worker prints ONE JSON line on stdout
             (`{"serving": ..., "port": <bound port>, ...}`); the front
             reads the ephemeral port from it
  readiness  the worker's own `/readyz` (models loaded + warmed, not
             draining) — the front polls it before routing traffic, at
             startup and after every restart
  identity   `--replica-id` stamps obs identity (replica_id, pid) into
             the worker's events, flight dumps, and `/metrics.replica`

`spawn_replica` is also what the front's crash-restart path calls: the
spawn itself rides `resilience.retry` (site `serve.worker`), so a
transiently failing exec/bind costs a backoff instead of a dead slot.
Tests inject a stub `argv` (tests/fleet_stub_worker.py) to drill the
spawn/kill/restart machinery without paying a jax import per replica.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from ...resilience import retry_call
from ...obs.recorder import thread_guard

log = logging.getLogger("ytklearn_tpu.serve.fleet")


class WorkerStartupError(RuntimeError):
    """The worker exited or failed to report a port/readiness in time."""


class ReplicaHandle:
    """One live (or restarting) replica slot owned by the front."""

    __slots__ = ("replica_id", "proc", "port", "state", "restarts",
                 "started_at", "log_path", "wall_t0")

    def __init__(self, replica_id: int):
        self.replica_id = replica_id
        self.proc: Optional[subprocess.Popen] = None
        self.port: int = 0
        #: starting | ready | dead | draining.  "draining" is the
        #: scale-down fence too (front.scale_down): the balancer skips it
        #: and the monitor ignores it (only ready/dead slots are acted
        #: on), so a slot mid-reap can neither receive traffic nor be
        #: "healed" back to life
        self.state = "starting"
        self.restarts = 0
        self.started_at = 0.0
        self.log_path: Optional[str] = None
        #: the replica's obs clock origin on the wall clock (banner
        #: handshake, stamped at every spawn): trace-hop offsets from this
        #: replica align to the front's timeline as `wall_t0 + ts`
        self.wall_t0: Optional[float] = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def http_json(
    method: str,
    port: int,
    path: str,
    payload=None,
    timeout: float = 10.0,
    headers: Optional[Dict[str, str]] = None,
):
    """One HTTP round-trip to a local replica -> (status, parsed body).
    `payload` may be a dict (JSON-encoded here) or pre-built str/bytes
    (the front's raw-splice forward path skips a re-encode). `headers`
    merge over the defaults (the trace-context propagation header rides
    here). Connection-level failures raise (OSError shapes — the
    retry/reroute classification in front.py keys off that)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        if payload is None:
            body = None
        elif isinstance(payload, bytes):
            body = payload
        elif isinstance(payload, str):
            body = payload.encode()
        else:
            body = json.dumps(payload).encode()
        hdrs = {"Content-Type": "application/json"} if body else {}
        if headers:
            hdrs.update(headers)
        try:
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            raw = resp.read()
        except http.client.HTTPException as e:
            # a peer dying MID-exchange surfaces as IncompleteRead /
            # BadStatusLine — HTTPException, not OSError. Normalize to the
            # OSError family so the reroute classification (is_transient)
            # treats a mid-response crash like any other connection loss
            raise ConnectionResetError(
                f"HTTP exchange broke mid-response: {type(e).__name__}: {e}"
            ) from e
        try:
            data = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            data = {"error": raw[:200].decode("utf-8", "replace")}
        return resp.status, data
    finally:
        conn.close()


def _read_banner(proc: subprocess.Popen, timeout_s: float) -> dict:
    """First stdout line as JSON, read on a helper thread so a silent or
    wedged worker can't hang the front."""
    out: List[str] = []

    @thread_guard
    def _read():
        try:
            out.append(proc.stdout.readline())
        except (OSError, ValueError):
            pass

    t = threading.Thread(target=_read, name="ytk-fleet-banner", daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if not out or not out[0]:
        raise WorkerStartupError(
            f"worker pid={proc.pid} printed no banner within {timeout_s:.0f}s"
            + (f" (exited rc={proc.returncode})" if proc.poll() is not None
               else "")
        )
    try:
        banner = json.loads(out[0])
    except json.JSONDecodeError as e:
        raise WorkerStartupError(
            f"worker pid={proc.pid} banner is not JSON: {out[0][:200]!r}"
        ) from e
    if not isinstance(banner, dict) or "port" not in banner:
        raise WorkerStartupError(
            f"worker pid={proc.pid} banner has no port: {banner!r}"
        )
    return banner


def wait_ready(port: int, timeout_s: float, proc=None,
               abort: Optional[Callable[[], bool]] = None) -> None:
    """Poll the worker's /readyz until 200 (models loaded AND warm).
    `abort` (e.g. "the fleet is closing") ends the wait early."""
    deadline = time.monotonic() + timeout_s
    last = "no response yet"
    while time.monotonic() < deadline:
        if abort is not None and abort():
            raise WorkerStartupError("worker startup aborted (fleet closing)")
        if proc is not None and proc.poll() is not None:
            raise WorkerStartupError(
                f"worker exited rc={proc.returncode} before becoming ready"
            )
        try:
            status, body = http_json("GET", port, "/readyz", timeout=2.0)
            if status == 200:
                return
            last = f"readyz {status}: {body.get('status')}"
        except OSError as e:
            last = f"{type(e).__name__}: {e}"
        time.sleep(0.05)
    raise WorkerStartupError(
        f"worker on port {port} not ready within {timeout_s:.0f}s ({last})"
    )


def spawn_replica(
    argv: List[str],
    replica_id: int,
    handle: Optional[ReplicaHandle] = None,
    env: Optional[Dict[str, str]] = None,
    log_dir: Optional[str] = None,
    ready_timeout_s: float = 120.0,
    abort: Optional[Callable[[], bool]] = None,
) -> ReplicaHandle:
    """Spawn `argv + [--replica-id N]`, read the port banner, wait for
    /readyz. Reuses `handle` on restart (slot identity, restart count).
    The spawn itself is retried under the `serve.worker` site. `abort`
    ends the ready wait early (fleet shutdown mid-respawn). The child is
    published on `h.proc` IMMEDIATELY after Popen — before it is ready —
    so a stop() racing a respawn can always terminate it (no orphan)."""
    h = handle or ReplicaHandle(replica_id)

    def _once() -> None:
        h.state = "starting"
        stderr = subprocess.DEVNULL
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            h.log_path = os.path.join(log_dir, f"replica_{replica_id}.log")
            # ytklint: allow(unseamed-io) reason=replica stderr sink handed to Popen; must be a real fd, and _once runs under retry_call(site="serve.worker") below
            stderr = open(h.log_path, "ab")
        try:
            # ytklint: allow(unseamed-io) reason=this IS the process-spawn seam; _once runs under retry_call(site="serve.worker") below
            proc = subprocess.Popen(
                list(argv) + ["--replica-id", str(replica_id)],
                stdout=subprocess.PIPE,
                stderr=stderr,
                env=dict(os.environ, **(env or {})),
                text=True,
            )
        finally:
            if stderr is not subprocess.DEVNULL:
                stderr.close()  # the child holds its own fd now
        h.proc = proc  # visible to stop_replica from the first instant
        try:
            banner = _read_banner(proc, ready_timeout_s)
            port = int(banner["port"])
            wait_ready(port, ready_timeout_s, proc=proc, abort=abort)
        except Exception:
            # never leak a half-started worker process into the fleet
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10.0)
            raise
        h.port = port
        h.state = "ready"
        h.started_at = time.time()
        # monotonic-offset handshake: the worker banner carries its obs
        # clock origin (wall_t0); the front keeps it per slot so a trace
        # merge can align replica hop offsets without re-asking a process
        # that may be dead by postmortem time
        h.wall_t0 = banner.get("wall_t0")
        log.info(
            "fleet: replica %d ready (pid=%d port=%d)",
            replica_id, proc.pid, port,
        )

    retry_call(_once, site="serve.worker")
    return h


@thread_guard
def stop_replica(h: ReplicaHandle, timeout_s: float = 30.0,
                 reason: str = "shutdown") -> None:
    """SIGTERM (the worker drains in-flight work), escalate to kill.
    Fleet shutdown and autoscaler scale-down both end here: by the time
    scale_down() calls this the slot is already fenced and its forwarder
    drained, so the worker's own SIGTERM drain finds at most the batch
    it is currently scoring — zero requests are lost to a reap."""
    h.state = "draining"
    proc = h.proc
    if proc is None or proc.poll() is not None:
        h.state = "dead"
        return
    proc.terminate()
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log.warning(
            "fleet: replica %d did not drain in %.0fs (%s); killing",
            h.replica_id, timeout_s, reason,
        )
        proc.kill()
        proc.wait(timeout=10.0)
    h.state = "dead"


def default_replica_count() -> int:
    """`--replicas -1` / auto: one replica per accelerator device, or per
    CPU core divided by two on the host backend (each CPU replica runs a
    featurize thread + an XLA thread pool; 1:1 per core oversubscribes)."""
    try:
        import jax

        if jax.default_backend() != "cpu":
            return max(1, jax.local_device_count())
    except Exception as e:  # noqa: BLE001 — sizing must work without a backend
        log.warning("fleet: backend probe failed (%s); sizing by cpu count", e)
    return max(1, (os.cpu_count() or 2) // 2)


def serve_worker_argv(
    config_path: str,
    model_name: str,
    extra_flags: Optional[List[str]] = None,
) -> List[str]:
    """The real worker command: `python -m ytklearn_tpu.cli serve` bound
    to an ephemeral localhost port, single-process (`--replicas 0`)."""
    return [
        sys.executable, "-m", "ytklearn_tpu.cli", "serve",
        config_path, model_name,
        "--host", "127.0.0.1", "--port", "0", "--replicas", "0",
    ] + list(extra_flags or [])
