"""ytklearn_tpu.serve.fleet — multi-process serving fleet (docs/serving.md).

The r9 server is one process: one GIL, one device, one latency ring. This
package is the layer that turns it into a fleet — the two Clipper layers
r9 deferred (AIMD adaptive batching, bounded prediction cache) plus the
multi-replica fan-out itself:

  FleetFront        shared-nothing front process: spawns N replica
                    workers (each the full r9 stack in its own process),
                    balances on least-queued-rows, coalesces client
                    requests into per-replica batched forwards, reroutes
                    around and restarts crashed/wedged replicas, fans
                    /admin/* out fleet-wide, and aggregates /metrics with
                    a replica latency-ring union (fleet p99 is real)
  AIMDController    searches the largest batch size meeting the p99 SLO
                    (additive increase / multiplicative backoff), always
                    snapped to the compiled shape ladder so adaptation
                    never retraces
  PredictionCache   bounded LRU keyed on (model fingerprint, feature
                    row); hits bypass the batcher queue and are
                    bit-identical to the scored path; hot reload
                    invalidates by key, for free
  AutoscalePolicy / FleetAutoscaler
                    load-driven replica-count elasticity: a control
                    thread watches windowed load signals (backlog, shed
                    rate, p99 vs SLO, slo-burn) and grows or reaps slots
                    within `--replicas-min/--replicas-max` with
                    hysteresis + per-direction cooldowns; scale-down is
                    drain-based (fence, complete/reroute, SIGTERM)

CLI: `ytklearn-tpu-serve <conf> <model> --replicas N
      [--replicas-min A --replicas-max B]` (cli.py).
"""

from __future__ import annotations

from .aimd import AIMDController, maybe_controller  # noqa: F401
from .autoscaler import (  # noqa: F401
    AutoscalePolicy,
    FleetAutoscaler,
    ScaleSignals,
    maybe_autoscaler,
)
from .cache import PredictionCache, maybe_cache, row_key  # noqa: F401
from .front import FleetFront, latency_percentiles  # noqa: F401
from .worker import (  # noqa: F401
    ReplicaHandle,
    WorkerStartupError,
    default_replica_count,
    http_json,
    serve_worker_argv,
    spawn_replica,
    stop_replica,
)

__all__ = [
    "AIMDController",
    "AutoscalePolicy",
    "FleetAutoscaler",
    "FleetFront",
    "PredictionCache",
    "ReplicaHandle",
    "ScaleSignals",
    "WorkerStartupError",
    "default_replica_count",
    "http_json",
    "latency_percentiles",
    "maybe_autoscaler",
    "maybe_cache",
    "maybe_controller",
    "row_key",
    "serve_worker_argv",
    "spawn_replica",
    "stop_replica",
]
