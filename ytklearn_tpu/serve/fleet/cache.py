"""Bounded LRU prediction cache — Clipper's other deferred layer.

Online traffic is heavy-tailed: a small set of hot feature rows (the
popular item, the returning user) accounts for a large share of requests.
Clipper (NSDI'17 §4.1) puts a prediction cache in front of the batching
queue so those rows cost a dict lookup instead of a scorer pass. Rules:

  key        (model fingerprint + version, exact feature-row tuple) — the
             row itself is the key, not a hash of it, so a collision can
             never serve another row's prediction
  values     the (score, prediction) the SCORED path produced, stored
             per row — a hit is bit-identical to a cold request by
             construction (test-pinned)
  bound      `YTK_SERVE_CACHE_ROWS` rows, LRU eviction
             (`serve.cache.evict` counts)
  invalidation  free: the fingerprint/version in the key changes when the
             registry hot-swaps an entry, so every stale row simply stops
             matching and ages out of the LRU — no flush, no lock sweep,
             no coordination with the reload path
  writes     only from scored batches, keyed by the entry that ACTUALLY
             scored them (the batch meta), never by the entry that was
             current at submit time — a hot reload between submit and
             score must not poison the cache with mislabeled rows

Counters: `serve.cache.hit` / `serve.cache.miss` / `serve.cache.evict`
(+ `serve.cache.rows` gauge) land in `/metrics`.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ...config import knobs
from ...obs import gauge as obs_gauge, inc as obs_inc


def row_key(row: Dict[str, float]) -> tuple:
    """A feature-dict row as a canonical hashable key (sorted items —
    insertion order must not split identical rows into distinct keys)."""
    return tuple(sorted(row.items()))


class PredictionCache:
    """LRU of (model key, row key) -> (score, prediction) scalars/rows."""

    def __init__(self, max_rows: Optional[int] = None):
        if max_rows is None:
            max_rows = knobs.get_int("YTK_SERVE_CACHE_ROWS")
        self.max_rows = max(0, int(max_rows))
        self._lru: OrderedDict = OrderedDict()
        # mesh-obs per-model occupancy: which family scope stored each
        # key (maintained with _lru under the same lock), and the live
        # row count per scope — `/metrics?models=1` reports who actually
        # owns the shared cache budget
        self._key_scope: dict = {}
        self._scope_rows: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.max_rows > 0

    @staticmethod
    def model_key(entry) -> tuple:
        """The invalidation half of the cache key: fingerprint + version
        of a registry entry. A hot reload (new fingerprint, bumped
        version) or a rollback (older version) changes it, so stale rows
        never match again."""
        return (entry.fingerprint, entry.version)

    def lookup(
        self, model_key: tuple, rows: Sequence[Dict[str, float]],
        scope: Optional[str] = None,
    ) -> Optional[list]:
        """All-or-nothing: the per-row (score, pred) list when EVERY row
        hits, else None (partial hits still ride the scored path, so a
        response is always one model version end to end). Both counters
        are in ROWS — hit rows bypassed the scorer, miss rows rode the
        scored path — so hit/(hit+miss) is a true row hit rate even for
        multi-row requests. `scope` (a mesh-obs family name) mirrors each
        counter per model at the same site as its global twin."""
        if not self.enabled:
            return None
        out = []
        with self._lock:
            for row in rows:
                k = (model_key, row_key(row))
                hit = self._lru.get(k)
                if hit is None:
                    obs_inc("serve.cache.miss", len(rows))
                    if scope is not None:
                        obs_inc(
                            f"serve.model.{scope}.cache.miss", len(rows)
                        )
                    return None
                self._lru.move_to_end(k)
                out.append(hit)
        obs_inc("serve.cache.hit", len(rows))
        if scope is not None:
            obs_inc(f"serve.model.{scope}.cache.hit", len(rows))
        return out

    def store(
        self, model_key: tuple, rows: Sequence[Dict[str, float]], scores,
        preds, scope: Optional[str] = None,
    ) -> None:
        """Insert scored rows (score_i, pred_i from the batch arrays).
        `scope` attributes the stored rows to a mesh-obs family for the
        per-model occupancy view; eviction re-credits the evicted key's
        own scope, not the storer's."""
        if not self.enabled:
            return
        with self._lock:
            for i, row in enumerate(rows):
                k = (model_key, row_key(row))
                s, p = scores[i], preds[i]
                # multi-output models: scores[i] on a (B, K) array is a
                # VIEW whose .base pins the whole batch array — a
                # "bounded" cache of views can hold gigabytes. Scalars
                # (1-D indexing) are already copies.
                if isinstance(s, np.ndarray):
                    s = np.array(s, copy=True)
                if isinstance(p, np.ndarray):
                    p = np.array(p, copy=True)
                fresh = k not in self._lru
                self._lru[k] = (s, p)
                self._lru.move_to_end(k)  # re-stored keys keep recency
                if scope is not None:
                    old = self._key_scope.get(k)
                    if fresh or old != scope:
                        if old is not None and not fresh:
                            self._scope_rows[old] = (
                                self._scope_rows.get(old, 1) - 1
                            )
                        self._key_scope[k] = scope
                        self._scope_rows[scope] = (
                            self._scope_rows.get(scope, 0) + 1
                        )
            evicted = 0
            while len(self._lru) > self.max_rows:
                k, _ = self._lru.popitem(last=False)
                old = self._key_scope.pop(k, None)
                if old is not None:
                    left = self._scope_rows.get(old, 1) - 1
                    if left > 0:
                        self._scope_rows[old] = left
                    else:
                        self._scope_rows.pop(old, None)
                evicted += 1
            n = len(self._lru)
        if evicted:
            obs_inc("serve.cache.evict", evicted)
        obs_gauge("serve.cache.rows", n)

    def scope_rows(self) -> Dict[str, int]:
        """Live cached-row count per mesh-obs family scope (rows stored
        without a scope are not attributed)."""
        with self._lock:
            return {s: n for s, n in sorted(self._scope_rows.items()) if n > 0}

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._key_scope.clear()
            self._scope_rows.clear()
        obs_gauge("serve.cache.rows", 0)


def maybe_cache(max_rows: Optional[int] = None) -> Optional[PredictionCache]:
    """A PredictionCache when the rows knob (or explicit arg) is > 0."""
    cache = PredictionCache(max_rows)
    return cache if cache.enabled else None
