"""Bounded LRU prediction cache — Clipper's other deferred layer.

Online traffic is heavy-tailed: a small set of hot feature rows (the
popular item, the returning user) accounts for a large share of requests.
Clipper (NSDI'17 §4.1) puts a prediction cache in front of the batching
queue so those rows cost a dict lookup instead of a scorer pass. Rules:

  key        (model fingerprint + version, exact feature-row tuple) — the
             row itself is the key, not a hash of it, so a collision can
             never serve another row's prediction
  values     the (score, prediction) the SCORED path produced, stored
             per row — a hit is bit-identical to a cold request by
             construction (test-pinned)
  bound      `YTK_SERVE_CACHE_ROWS` rows, LRU eviction
             (`serve.cache.evict` counts)
  invalidation  free: the fingerprint/version in the key changes when the
             registry hot-swaps an entry, so every stale row simply stops
             matching and ages out of the LRU — no flush, no lock sweep,
             no coordination with the reload path
  writes     only from scored batches, keyed by the entry that ACTUALLY
             scored them (the batch meta), never by the entry that was
             current at submit time — a hot reload between submit and
             score must not poison the cache with mislabeled rows

Counters: `serve.cache.hit` / `serve.cache.miss` / `serve.cache.evict`
(+ `serve.cache.rows` gauge) land in `/metrics`.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ...config import knobs
from ...obs import gauge as obs_gauge, inc as obs_inc


def row_key(row: Dict[str, float]) -> tuple:
    """A feature-dict row as a canonical hashable key (sorted items —
    insertion order must not split identical rows into distinct keys)."""
    return tuple(sorted(row.items()))


class PredictionCache:
    """LRU of (model key, row key) -> (score, prediction) scalars/rows."""

    def __init__(self, max_rows: Optional[int] = None):
        if max_rows is None:
            max_rows = knobs.get_int("YTK_SERVE_CACHE_ROWS")
        self.max_rows = max(0, int(max_rows))
        self._lru: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.max_rows > 0

    @staticmethod
    def model_key(entry) -> tuple:
        """The invalidation half of the cache key: fingerprint + version
        of a registry entry. A hot reload (new fingerprint, bumped
        version) or a rollback (older version) changes it, so stale rows
        never match again."""
        return (entry.fingerprint, entry.version)

    def lookup(
        self, model_key: tuple, rows: Sequence[Dict[str, float]]
    ) -> Optional[list]:
        """All-or-nothing: the per-row (score, pred) list when EVERY row
        hits, else None (partial hits still ride the scored path, so a
        response is always one model version end to end). Both counters
        are in ROWS — hit rows bypassed the scorer, miss rows rode the
        scored path — so hit/(hit+miss) is a true row hit rate even for
        multi-row requests."""
        if not self.enabled:
            return None
        out = []
        with self._lock:
            for row in rows:
                k = (model_key, row_key(row))
                hit = self._lru.get(k)
                if hit is None:
                    obs_inc("serve.cache.miss", len(rows))
                    return None
                self._lru.move_to_end(k)
                out.append(hit)
        obs_inc("serve.cache.hit", len(rows))
        return out

    def store(
        self, model_key: tuple, rows: Sequence[Dict[str, float]], scores, preds
    ) -> None:
        """Insert scored rows (score_i, pred_i from the batch arrays)."""
        if not self.enabled:
            return
        with self._lock:
            for i, row in enumerate(rows):
                k = (model_key, row_key(row))
                s, p = scores[i], preds[i]
                # multi-output models: scores[i] on a (B, K) array is a
                # VIEW whose .base pins the whole batch array — a
                # "bounded" cache of views can hold gigabytes. Scalars
                # (1-D indexing) are already copies.
                if isinstance(s, np.ndarray):
                    s = np.array(s, copy=True)
                if isinstance(p, np.ndarray):
                    p = np.array(p, copy=True)
                self._lru[k] = (s, p)
                self._lru.move_to_end(k)  # re-stored keys keep recency
            evicted = 0
            while len(self._lru) > self.max_rows:
                self._lru.popitem(last=False)
                evicted += 1
            n = len(self._lru)
        if evicted:
            obs_inc("serve.cache.evict", evicted)
        obs_gauge("serve.cache.rows", n)

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
        obs_gauge("serve.cache.rows", 0)


def maybe_cache(max_rows: Optional[int] = None) -> Optional[PredictionCache]:
    """A PredictionCache when the rows knob (or explicit arg) is > 0."""
    cache = PredictionCache(max_rows)
    return cache if cache.enabled else None
