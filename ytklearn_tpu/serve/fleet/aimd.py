"""AIMD adaptive batch sizing under a latency SLO (Clipper, NSDI'17 §4.3).

r9 shipped fixed `max_batch`/`max_wait_ms` knobs: the operator had to
guess the largest batch that still meets the latency target, and a wrong
guess either wasted throughput (too small) or blew the SLO (too large).
Clipper's answer is an additive-increase / multiplicative-decrease search
— the same control law TCP uses for congestion windows — over the batch
size itself:

  - every `window` batches the controller judges the window's WORST
    observed request latency (enqueue -> response, the client-visible
    number) against the SLO,
  - a clean window additively raises the raw target by `inc` rows,
  - a violating window multiplicatively backs the raw target off by
    `backoff` (default 0.5 — halve, like TCP),

so the batch size climbs toward the throughput knee and retreats fast
when the SLO breaks (queue buildup, a slow replica, a noisy neighbor).

TPU twist: the raw AIMD target is continuous, but the *effective* batch
bound always snaps DOWN to a compiled shape-ladder rung — the controller
can only ever pick sizes the scorer already compiled at warmup, so the
zero-steady-state-retrace contract survives adaptation (the whole reason
the ladder exists). The linger window is derived from the SLO instead of
a fixed `max_wait_ms`: waiting longer than a small fraction of the SLO
for stragglers eats budget the scorer needs.

Thread-safety: `observe()`/`note_batch()` run on the batcher worker
thread only; `max_batch`/`max_wait_ms` are single-attribute reads safe
from any producer.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ...config import knobs
from ...obs import event as obs_event, gauge as obs_gauge, inc as obs_inc

#: linger budget as a fraction of the SLO — a batch should never spend
#: more than this share of its deadline waiting for stragglers
_WAIT_SLO_FRACTION = 0.05
_WAIT_CAP_MS = 5.0


class AIMDController:
    """Searches the largest ladder-snapped batch size meeting the p99 SLO."""

    def __init__(
        self,
        ladder: Sequence[int],
        slo_ms: Optional[float] = None,
        inc: Optional[int] = None,
        backoff: Optional[float] = None,
        window: Optional[int] = None,
    ):
        self.ladder: Tuple[int, ...] = tuple(sorted(set(int(r) for r in ladder)))
        if not self.ladder or self.ladder[0] < 1:
            raise ValueError(f"bad AIMD ladder {ladder!r}: rungs must be >= 1")
        self.slo_ms = float(
            slo_ms if slo_ms is not None else knobs.get_float("YTK_SERVE_SLO_MS")
        )
        self.inc = int(inc if inc is not None else knobs.get_int("YTK_SERVE_AIMD_INC"))
        self.backoff = float(
            backoff if backoff is not None
            else knobs.get_float("YTK_SERVE_AIMD_BACKOFF")
        )
        if not 0.0 < self.backoff < 1.0:
            raise ValueError(
                f"bad AIMD backoff {self.backoff!r}: must be in (0, 1)"
            )
        self.window = max(
            1,
            int(window if window is not None
                else knobs.get_int("YTK_SERVE_AIMD_WINDOW")),
        )
        # start one rung below the top (or the only rung): the search should
        # climb into the big batches, not start out violating the SLO
        start = self.ladder[-2] if len(self.ladder) > 1 else self.ladder[0]
        self._raw = float(start)
        self.max_batch = self._snap(self._raw)
        self.max_wait_ms = min(_WAIT_CAP_MS, self.slo_ms * _WAIT_SLO_FRACTION)
        self._window_worst_ms = 0.0
        self._window_batches = 0
        obs_gauge("serve.aimd.max_batch", self.max_batch)

    def _snap(self, raw: float) -> int:
        """Largest compiled rung <= raw (floor: the smallest rung)."""
        best = self.ladder[0]
        for r in self.ladder:
            if r <= raw:
                best = r
        return best

    # -- worker-thread side ----------------------------------------------

    def observe(self, latency_ms: float) -> None:
        """Feed one completed request's client-visible latency."""
        if latency_ms > self._window_worst_ms:
            self._window_worst_ms = latency_ms

    def note_batch(self) -> None:
        """One scored batch done; adjust once per `window` batches."""
        self._window_batches += 1
        if self._window_batches < self.window:
            return
        worst = self._window_worst_ms
        self._window_batches = 0
        self._window_worst_ms = 0.0
        before = self.max_batch
        if worst > self.slo_ms:
            # multiplicative decrease, floored at the smallest rung
            self._raw = max(float(self.ladder[0]), self._raw * self.backoff)
            obs_inc("serve.aimd.backoff")
        else:
            # additive increase, capped at the top rung (no headroom above
            # the ladder: the scorer has no compiled shape to grow into)
            self._raw = min(float(self.ladder[-1]), self._raw + self.inc)
            obs_inc("serve.aimd.increase")
        self.max_batch = self._snap(self._raw)
        if self.max_batch != before:
            obs_gauge("serve.aimd.max_batch", self.max_batch)
            obs_event(
                "serve.aimd.adjust",
                from_batch=before, to_batch=self.max_batch,
                worst_ms=round(worst, 3), slo_ms=self.slo_ms,
            )

    def snapshot(self) -> dict:
        return {
            "slo_ms": self.slo_ms,
            "max_batch": self.max_batch,
            "raw_target": round(self._raw, 2),
            "max_wait_ms": round(self.max_wait_ms, 3),
        }


def maybe_controller(ladder, slo_ms: Optional[float] = None):
    """An AIMDController when the SLO knob is armed, else None (fixed
    `max_batch`/`max_wait_ms` semantics). `slo_ms=0` disables explicitly."""
    slo = slo_ms if slo_ms is not None else knobs.get_float("YTK_SERVE_SLO_MS")
    if not slo or slo <= 0:
        return None
    return AIMDController(ladder, slo_ms=slo)
